"""Shared retry/backoff utility (utils/retry.py) and its production call
sites: dataset downloads (re-download on corrupt fetch) and AsyncExecutor
shard workers (retry-then-skip-and-count instead of aborting the job)."""

import hashlib
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.flags import FLAGS
from paddle_tpu.testing import chaos
from paddle_tpu.utils.retry import RetryError, backoff_delays, retry_call


@pytest.fixture(autouse=True)
def _chaos_isolation():
    yield
    for n in ("chaos", "chaos_io_errors", "chaos_feed_stall_s", "monitor"):
        FLAGS.reset(n)
    chaos.reset()


# ---------------------------------------------------------------------------
# retry_call
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    assert retry_call(flaky, retries=3, sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert len(slept) == 2  # one backoff per failed attempt


def test_retry_gives_up_with_typed_exception():
    def always():
        raise OSError("still down")

    with pytest.raises(RetryError) as ei:
        retry_call(always, retries=2, sleep=lambda s: None, name="unit")
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, OSError)
    assert "unit" in str(ei.value) and "still down" in str(ei.value)


def test_retry_does_not_swallow_unexpected_exceptions():
    def bug():
        raise KeyError("programming error")

    with pytest.raises(KeyError):
        retry_call(bug, retries=5, sleep=lambda s: None)


def test_backoff_is_exponential_capped_and_deterministic_when_seeded():
    a = list(backoff_delays(6, base_delay=0.1, factor=2.0, max_delay=1.0,
                            jitter=0.25, seed=7))
    b = list(backoff_delays(6, base_delay=0.1, factor=2.0, max_delay=1.0,
                            jitter=0.25, seed=7))
    assert a == b  # seeded => replayable schedule
    raw = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]
    for d, r in zip(a, raw):
        assert r * 0.75 <= d <= r * 1.25  # jitter stays within +-25%
    assert max(a) <= 1.25  # cap holds even with jitter


# ---------------------------------------------------------------------------
# deadline budget (ISSUE 18 satellite): cumulative sleep never exceeds the
# caller's remaining deadline
# ---------------------------------------------------------------------------


def test_backoff_deadline_budget_caps_cumulative_sleep():
    """The regression the satellite names: a 100 ms-deadline request must
    never sleep 200 ms — the generator stops yielding once cumulative
    sleep would exceed the budget, clipping the final delay."""
    for seed in range(20):
        total = sum(backoff_delays(10, base_delay=0.05, max_delay=2.0,
                                   seed=seed, deadline_s=0.1))
        assert total <= 0.1 + 1e-9, (seed, total)


def test_backoff_deadline_clips_final_delay_not_drops_it():
    # budget 0.12 with 50 ms then ~100 ms raw steps: the second delay is
    # clipped to the ~70 ms remainder, not dropped entirely
    ds = list(backoff_delays(5, base_delay=0.05, jitter=0.0,
                             deadline_s=0.12))
    assert ds == pytest.approx([0.05, 0.07])
    assert sum(ds) == pytest.approx(0.12)


def test_backoff_nonpositive_deadline_yields_nothing():
    assert list(backoff_delays(5, deadline_s=0.0)) == []
    assert list(backoff_delays(5, deadline_s=-1.0)) == []


def test_backoff_no_deadline_is_legacy_unbudgeted():
    assert len(list(backoff_delays(5, jitter=0.0))) == 5


def test_retry_call_deadline_stops_retrying_past_budget():
    """retry_call with deadline_s: the total sleep handed to the sleeper
    stays inside the budget and the call gives up (RetryError) instead of
    sleeping on."""
    slept = []

    def always():
        raise OSError("down")

    with pytest.raises(RetryError):
        retry_call(always, retries=50, base_delay=0.05, jitter=0.0,
                   sleep=slept.append, deadline_s=0.1)
    assert sum(slept) <= 0.1 + 1e-9
    assert len(slept) >= 1  # it did retry inside the budget


# ---------------------------------------------------------------------------
# dataset download hardening
# ---------------------------------------------------------------------------


def _patch_data_home(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    return common


def test_download_retries_flaky_opener(tmp_path, monkeypatch):
    common = _patch_data_home(tmp_path, monkeypatch)
    payload = b"dataset-bytes"
    md5 = hashlib.md5(payload).hexdigest()
    attempts = []

    def flaky(url, tmp):
        attempts.append(url)
        if len(attempts) < 3:
            raise OSError("connection reset")
        with open(tmp, "wb") as f:
            f.write(payload)

    monkeypatch.setattr(common, "_urlretrieve", flaky)
    monkeypatch.setattr("time.sleep", lambda s: None)
    path = common.download("http://x/f.bin", "unit", md5)
    assert open(path, "rb").read() == payload
    assert len(attempts) == 3


def test_download_redownloads_on_md5_mismatch(tmp_path, monkeypatch):
    """A corrupt fetch is a transient fault: re-download, don't raise."""
    common = _patch_data_home(tmp_path, monkeypatch)
    good = b"good-bytes"
    md5 = hashlib.md5(good).hexdigest()
    served = [b"corrupt!", b"corrupt!", good]

    def server(url, tmp):
        with open(tmp, "wb") as f:
            f.write(served.pop(0))

    monkeypatch.setattr(common, "_urlretrieve", server)
    monkeypatch.setattr("time.sleep", lambda s: None)
    path = common.download("http://x/f.bin", "unit", md5)
    assert open(path, "rb").read() == good
    assert not os.path.exists(path + ".part")  # no partials left behind


def test_download_cleans_stale_partial_and_gives_up_with_path(
        tmp_path, monkeypatch):
    common = _patch_data_home(tmp_path, monkeypatch)
    os.makedirs(os.path.join(common.DATA_HOME, "unit"), exist_ok=True)
    stale = os.path.join(common.DATA_HOME, "unit", "f.bin.part")
    open(stale, "wb").write(b"half-a-download")

    def down(url, tmp):
        raise OSError("offline")

    monkeypatch.setattr(common, "_urlretrieve", down)
    monkeypatch.setattr("time.sleep", lambda s: None)
    with pytest.raises(RuntimeError, match="f.bin"):
        common.download("http://x/f.bin", "unit", "0" * 32, retries=1)
    assert not os.path.exists(stale)  # stale partial was cleaned up


# ---------------------------------------------------------------------------
# AsyncExecutor shard fault tolerance
# ---------------------------------------------------------------------------


def _desc(batch_size=4):
    desc = pt.DataFeedDesc(batch_size=batch_size)
    desc.add_slot("dense", type="float", is_dense=True, dim=2)
    desc.add_slot("label", type="float", is_dense=True, dim=1)
    return desc


def _write_shard(path, n_lines, start=0):
    with open(path, "w") as f:
        for i in range(start, start + n_lines):
            f.write(f"2 {i % 7} {(i + 1) % 5} 1 {i % 2}\n")


def _tiny_net():
    dense = layers.data(name="dense", shape=[2], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="float32")
    pred = layers.fc(dense, size=1)
    loss = layers.mean(layers.square(pred - label))
    pt.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    return exe, loss


def test_shard_failure_skipped_and_counted(tmp_path):
    """One malformed shard must cost its own batches, not the job: the
    other shards train, the failure is counted
    (data_feed_shard_failures_total) and named."""
    FLAGS.monitor = True
    import paddle_tpu.monitor as monitor

    good1, bad, good2 = (str(tmp_path / n)
                         for n in ("g1.txt", "bad.txt", "g2.txt"))
    _write_shard(good1, 8)
    _write_shard(good2, 8, start=8)
    with open(bad, "w") as f:
        f.write("2 1.0 2.0 1 0.0\nthis line is hopeless\n")

    exe, loss = _tiny_net()
    aexe = pt.AsyncExecutor(pt.CPUPlace())
    aexe.executor = exe
    before = monitor.counter("data_feed.shard_failures_total").value
    res = aexe.run_from_files(
        pt.default_main_program(), _desc(), [good1, bad, good2],
        thread_num=2, fetch_list=[loss], shard_retries=1)
    assert len(res) >= 4  # 16 good lines / batch 4 = 4 full batches
    assert aexe.shard_failures == [bad]
    assert monitor.counter(
        "data_feed.shard_failures_total").value == before + 1


def test_shard_failure_raises_when_asked(tmp_path):
    bad = str(tmp_path / "bad.txt")
    with open(bad, "w") as f:
        f.write("not a multislot line\n")
    exe, loss = _tiny_net()
    aexe = pt.AsyncExecutor(pt.CPUPlace())
    aexe.executor = exe
    with pytest.raises(RetryError):
        aexe.run_from_files(
            pt.default_main_program(), _desc(), [bad], thread_num=1,
            fetch_list=[loss], shard_retries=0, on_shard_error="raise")


def test_shard_transient_fault_retried_to_success(tmp_path):
    """Chaos-injected transient I/O faults on the read path: the worker
    retries with backoff and delivers EVERY batch exactly once."""
    f1 = str(tmp_path / "s1.txt")
    _write_shard(f1, 12)
    exe, loss = _tiny_net()
    aexe = pt.AsyncExecutor(pt.CPUPlace())
    aexe.executor = exe
    FLAGS.chaos = True
    FLAGS.chaos_io_errors = 2  # first two read attempts die
    res = aexe.run_from_files(
        pt.default_main_program(), _desc(), [f1], thread_num=1,
        fetch_list=[loss], shard_retries=3)
    assert len(res) == 3  # 12 lines / batch 4, no duplicates, none lost
    assert aexe.shard_failures == []
    assert chaos.injected_counts().get("io_error") == 2


def test_mid_file_retry_does_not_duplicate_batches(tmp_path, monkeypatch):
    """A fault striking MID-file (some batches already queued) must not
    re-deliver them on retry — the yielded-count cursor skips them."""
    f1 = str(tmp_path / "s1.txt")
    _write_shard(f1, 12)  # 3 batches of 4
    exe, loss = _tiny_net()
    aexe = pt.AsyncExecutor(pt.CPUPlace())
    aexe.executor = exe

    real_read = pt.MultiSlotDataFeed.read_file
    state = {"fail_once": True}

    def flaky_read(self, path):
        it = real_read(self, path)
        yield next(it)  # first batch parses fine...
        if state.pop("fail_once", None):
            raise OSError("disk hiccup mid-file")
        for feed in it:
            yield feed

    monkeypatch.setattr(pt.MultiSlotDataFeed, "read_file", flaky_read)
    res = aexe.run_from_files(
        pt.default_main_program(), _desc(), [f1], thread_num=1,
        fetch_list=[loss], shard_retries=2)
    assert len(res) == 3  # exactly once each, despite the mid-file retry
