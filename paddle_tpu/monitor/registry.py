"""Metrics registry: counters, gauges, bounded-bucket histograms.

Prometheus-inspired but dependency-free; metric names are dotted strings
("executor.cache_miss") which the Prometheus exposition sanitizes to
underscore form.  All mutation goes through per-metric locks so parse
workers / serving threads can hammer the same counter safely (the GIL makes
`+=` *mostly* atomic in CPython, but "mostly" is not a contract).

The registry itself is intentionally always-on and cheap; the FLAGS.monitor
gate lives at the instrumentation call-sites (executor, data_feed,
inference, collectives) so the hot paths skip even the helper call when
telemetry is off.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Dict, List, Optional, Sequence


def enabled() -> bool:
    """Whether telemetry call-sites should write (the FLAGS.monitor gate)."""
    from ..flags import FLAGS

    return FLAGS.monitor


# latency-flavored default buckets (seconds): 100us .. 30s, bounded
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class Counter:
    """Monotonically increasing float counter."""

    kind = "counter"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError(f"counter {self.name!r}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"metric": self.name, "type": self.kind, "value": self._value}


class Gauge:
    """Instantaneous value (queue depth, last loss, ...)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"metric": self.name, "type": self.kind, "value": self._value}


class Histogram:
    """Fixed-bucket histogram (bounded memory: len(buckets)+1 counts).

    `buckets` are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail.  Exposition is cumulative (Prometheus `le` form).
    """

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum",
                 "_count")

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 help: str = ""):
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(bs):
            raise ValueError(
                f"histogram {name!r}: buckets must be ascending, got {bs}")
        self.name = name
        self.help = help
        self.buckets = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # +1: the +Inf tail
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float):
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile estimate (the upper bound of the
        bucket holding the q-th observation, Prometheus histogram_quantile
        style).  Returns None with no observations; observations past the
        top bucket return +Inf — widen the buckets if that matters."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return None
        target = q * total
        cum = 0
        for le, c in zip(self.buckets + (float("inf"),), counts):
            cum += c
            if cum >= target:
                return le
        return float("inf")

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, cum_counts = 0, []
        for le, c in zip(self.buckets + (float("inf"),), counts):
            cum += c
            cum_counts.append([le, cum])
        return {"metric": self.name, "type": self.kind, "count": total,
                "sum": s, "buckets": cum_counts}


class MetricsRegistry:
    """Name -> metric store; get-or-create, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  help: str = "") -> Histogram:
        h = self._get_or_create(
            name, Histogram, buckets=buckets or DEFAULT_BUCKETS, help=help)
        # explicit buckets that don't match the live metric would put
        # observations past the old top bucket in +Inf; warn (never
        # raise — instrumentation must not be able to fail a run)
        if buckets is not None and tuple(float(b) for b in buckets) != h.buckets:
            from ..log import warning

            warning(
                "histogram %r already registered with buckets %s; "
                "requested %s ignored", name, h.buckets, tuple(buckets))
        return h

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    # -- exposition ------------------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return [m.snapshot() for m in metrics]

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (scrape-ready)."""
        lines = []
        for snap in self.snapshot():
            name = _prom_name(snap["metric"])
            lines.append(f"# TYPE {name} {snap['type']}")
            if snap["type"] == "histogram":
                for le, cum in snap["buckets"]:
                    le_s = "+Inf" if le == float("inf") else _prom_num(le)
                    lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
                lines.append(f"{name}_sum {_prom_num(snap['sum'])}")
                lines.append(f"{name}_count {snap['count']}")
            else:
                lines.append(f"{name} {_prom_num(snap['value'])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def jsonl(self) -> str:
        """One JSON object per line per metric (BENCH-artifact style).
        Non-finite values (a NaN loss gauge from a diverged run) become
        strings so the output stays strict JSON."""
        ts = time.time()
        return "\n".join(
            json.dumps(_json_safe(dict(snap, ts=round(ts, 3))))
            for snap in self.snapshot()
        ) + ("\n" if self._metrics else "")

    def write_jsonl(self, path: str):
        with open(path, "w") as f:
            f.write(self.jsonl())

    def write_prometheus(self, path: str):
        with open(path, "w") as f:
            f.write(self.prometheus_text())


def _json_safe(v):
    import math

    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else (
            "Infinity" if v > 0 else "-Infinity")
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


def _prom_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_num(v) -> str:
    import math

    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help=help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help=help)


def histogram(name: str, buckets: Optional[Sequence[float]] = None,
              help: str = "") -> Histogram:
    return _default.histogram(name, buckets=buckets, help=help)
