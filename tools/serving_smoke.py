#!/usr/bin/env python
"""CI serving gate: export a model, boot the server, prove the batcher.

Driven by tools/run_ci.sh (the serving smoke step).  Three phases, all
against `python -m paddle_tpu.serving` subprocesses driven by
tools/loadgen.py:

  1. smoke    — a few hundred shape-varying requests (batch sizes cycle
     1,2,3,4) against a batched server; asserts the request-latency p99
     and batch-fill histograms appear in the scraped /metrics, and that
     the executor compile counter stayed FLAT during the load (warm
     bucket ladder: zero recompiles across the shape-varying stream).
  2. A/B      — the acceptance demonstration: the SAME single-row
     request stream against a batched server vs a --max-batch 1 server
     (both warm, same compiled-signature ladder).  Dynamic batching must
     deliver >= --ab-target x the QPS of batch-size-1 serving.  Trials
     are interleaved pairs (batched, batch1, batched, ...) so a noisy
     CI neighbour handicaps both modes of a pair roughly equally; the
     gate takes the best pair and stops early once the target is met.
  3. artifact — every loadgen JSON + an ab_summary.json with the
     per-trial QPS table lands in --out-dir for CI archiving.

Both servers stay resident across trials (warmup is paid once) and
requests ride keep-alive connections, so the measurement sees the
serving tier, not process startup or TCP churn.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def export_demo_model(dirname: str, in_dim: int = 32, hidden: int = 256,
                      nlayers: int = 32, out_dim: int = 4) -> str:
    """A deep-but-narrow fc stack: per-dispatch cost is dominated by the
    layer count (weight reads + dispatch overhead), nearly flat in batch
    size on CPU — the regime where coalescing visibly pays."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = 3
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = x
        for _ in range(nlayers):
            h = layers.fc(h, size=hidden, act="relu")
        out = layers.fc(h, size=out_dim)
    scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


class Server:
    """One `python -m paddle_tpu.serving` subprocess on an ephemeral
    port; parses the ready line, kills the process on close()."""

    def __init__(self, model_dir: str, extra_args):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving",
             "--model", f"demo={model_dir}", "--port", "0"]
            + list(extra_args),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        line = self.proc.stdout.readline().decode()
        try:
            ready = json.loads(line)
        except ValueError:
            err = self.proc.stderr.read().decode()[-2000:]
            raise RuntimeError(
                f"server did not print a ready line: {line!r}\n{err}")
        self.url = f"http://127.0.0.1:{ready['port']}"
        # Drain both pipes for the life of the server: an undrained PIPE
        # fills at ~64KB and blocks the server's writer (e.g. verbose
        # jax warnings), stalling requests until the loadgen timeout.
        for stream in (self.proc.stdout, self.proc.stderr):
            threading.Thread(target=self._drain, args=(stream,),
                             daemon=True).start()

    @staticmethod
    def _drain(stream):
        for _ in iter(stream.readline, b""):
            pass

    def close(self):
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def run_loadgen(url: str, out: str, requests: int, concurrency: int,
                batch_sizes: str) -> dict:
    cmd = [sys.executable, os.path.join(REPO_ROOT, "tools", "loadgen.py"),
           "--url", url, "--model", "demo",
           "--requests", str(requests), "--concurrency", str(concurrency),
           "--batch-sizes", batch_sizes, "--out", out]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        raise RuntimeError(f"loadgen failed:\n{r.stderr[-3000:]}")
    with open(out) as f:
        return json.load(f)


def scrape(url: str) -> str:
    import urllib.request

    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        return r.read().decode()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out-dir", default="ci_artifacts/serving")
    p.add_argument("--requests", type=int, default=300,
                   help="smoke-phase request count")
    p.add_argument("--ab-requests", type=int, default=200,
                   help="requests per A/B trial leg")
    p.add_argument("--concurrency", type=int, default=12)
    p.add_argument("--ab-target", type=float, default=2.0,
                   help="required batched/batch1 QPS ratio (best pair)")
    p.add_argument("--ab-trials", type=int, default=8,
                   help="max interleaved trial pairs (early exit on "
                        "target; the budget is sized for noisy shared "
                        "CI boxes where absolute QPS swings ~2x between "
                        "trials — a clean pair usually lands by trial 2)")
    args = p.parse_args(argv)

    os.makedirs(args.out_dir, exist_ok=True)
    model_dir = os.path.join(args.out_dir, "demo_model")
    if not os.path.exists(os.path.join(model_dir, "__model__")):
        export_demo_model(model_dir)

    policy = ["--buckets", "1,2,4,8,16", "--max-wait-ms", "4"]
    batched = Server(model_dir, policy)
    batch1 = Server(model_dir, policy + ["--max-batch", "1"])
    try:
        # -- phase 1: shape-varying smoke against the batched server ----
        smoke = run_loadgen(
            batched.url, os.path.join(args.out_dir, "loadgen_smoke.json"),
            args.requests, args.concurrency, "1,2,3,4")
        assert smoke["errors"] == 0, smoke
        assert smoke["latency_ms"]["p99"] > 0, smoke
        sm = smoke["server_metrics"]
        assert sm["executor_compiles_during_load"] == 0, \
            f"recompile during shape-varying load: {sm}"
        assert sm["unplanned_compiles"] == 0, sm
        assert sm["batch_fill_mean"] is not None, sm
        prom = scrape(batched.url)
        for needed in ("serving_demo_request_seconds_bucket",
                       "serving_demo_batch_fill_bucket",
                       "serving_demo_queue_seconds_bucket"):
            assert needed in prom, f"{needed} missing from /metrics"
        print(f"serving smoke OK: {smoke['completed']} requests, "
              f"qps={smoke['qps']} p99={smoke['latency_ms']['p99']}ms "
              f"fill={sm['batch_fill_mean']} recompiles=0", flush=True)

        # -- phase 2: batched vs batch-size-1 A/B (single-row stream) ---
        trials = []
        best = None
        for t in range(args.ab_trials):
            b = run_loadgen(
                batched.url,
                os.path.join(args.out_dir, "loadgen_batched.json"),
                args.ab_requests, args.concurrency, "1")
            s = run_loadgen(
                batch1.url,
                os.path.join(args.out_dir, "loadgen_batch1.json"),
                args.ab_requests, args.concurrency, "1")
            for rec in (b, s):
                assert rec["errors"] == 0, rec
                assert rec["server_metrics"][
                    "executor_compiles_during_load"] == 0, rec
            ratio = b["qps"] / max(s["qps"], 1e-9)
            trials.append({
                "trial": t, "batched_qps": b["qps"],
                "batch1_qps": s["qps"], "ratio": round(ratio, 3),
                "batched_fill": b["server_metrics"]["batch_fill_mean"],
                "batched_batches": b["server_metrics"]["batches"],
            })
            print(f"A/B trial {t}: batched {b['qps']} qps vs batch1 "
                  f"{s['qps']} qps -> {ratio:.2f}x", flush=True)
            if best is None or ratio > best["ratio"]:
                best = trials[-1]
            if ratio >= args.ab_target:
                break
            time.sleep(1.0)  # let a noisy-neighbour burst pass

        summary = {
            "tool": "serving_smoke",
            "policy": {"buckets": [1, 2, 4, 8, 16], "max_wait_ms": 4.0,
                       "batched_max_batch": 16, "batch1_max_batch": 1},
            "ab_requests": args.ab_requests,
            "concurrency": args.concurrency,
            "target_ratio": args.ab_target,
            "trials": trials,
            "best": best,
            "passed": best["ratio"] >= args.ab_target,
        }
        with open(os.path.join(args.out_dir, "ab_summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        print(json.dumps(summary["best"], indent=2))
        if not summary["passed"]:
            print(f"serving A/B gate FAILED: best ratio "
                  f"{best['ratio']}x < {args.ab_target}x "
                  f"across {len(trials)} trials", file=sys.stderr)
            return 1
        print(f"serving A/B gate OK: dynamic batching {best['ratio']}x "
              f"over batch-size-1 at zero recompiles", flush=True)
        return 0
    finally:
        batched.close()
        batch1.close()


if __name__ == "__main__":
    sys.exit(main())
