"""SSD training path: target_assign / mine_hard_examples numerics vs brute
force, density_prior_box, detection_map vs hand-computed AP, and an
integration test training a toy SSD head (multi_box_head + ssd_loss) to
decreasing loss with detection_output producing sane boxes.
Reference: layers/detection.py ssd_loss:779, detection_output:201,
multi_box_head:1259, density_prior_box:1133, detection_map:515;
operators/detection/{target_assign,mine_hard_examples,density_prior_box,
detection_map}_op."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import framework as fw

rng = np.random.RandomState(3)


def _run_op(op_type, inputs, outputs, attrs):
    prog, startup = fw.Program(), fw.Program()
    with fw.program_guard(prog, startup):
        blk = prog.global_block()
        feed = {}
        in_spec = {}
        for slot, (name, arr) in inputs.items():
            blk.create_var(name=name, shape=arr.shape, dtype=str(arr.dtype),
                           is_data=True)
            feed[name] = arr
            in_spec[slot] = [name]
        out_spec = {}
        for slot, name in outputs.items():
            blk.create_var(name=name, dtype="float32")
            out_spec[slot] = [name]
        blk.append_op(op_type, inputs=in_spec, outputs=out_spec, attrs=attrs)
    exe = pt.Executor(pt.CPUPlace())
    res = exe.run(prog, feed=feed, fetch_list=list(outputs.values()))
    return [np.asarray(r) for r in res]


def test_target_assign_matches_brute_force():
    N, G, P, K = 2, 3, 5, 4
    x = rng.randn(N, G, K).astype("float32")
    match = np.array([[0, -1, 2, 1, -1],
                      [2, 2, -1, 0, 1]], "int32")
    out, wt = _run_op(
        "target_assign",
        {"X": ("x", x), "MatchIndices": ("m", match)},
        {"Out": "o", "OutWeight": "w"},
        {"mismatch_value": 7},
    )
    for n in range(N):
        for p in range(P):
            if match[n, p] >= 0:
                np.testing.assert_allclose(out[n, p], x[n, match[n, p]])
                assert wt[n, p] == 1.0
            else:
                np.testing.assert_allclose(out[n, p], 7.0)
                assert wt[n, p] == 0.0


def test_target_assign_negative_mask():
    N, G, P = 1, 2, 4
    x = rng.randn(N, G, 1).astype("float32")
    match = np.array([[0, -1, -1, 1]], "int32")
    neg = np.array([[0, 1, 0, 0]], "int32")
    out, wt = _run_op(
        "target_assign",
        {"X": ("x", x), "MatchIndices": ("m", match),
         "NegIndices": ("n", neg)},
        {"Out": "o", "OutWeight": "w"},
        {"mismatch_value": 0},
    )
    # negatives: background value with weight 1 (they join the conf loss)
    assert out[0, 1, 0] == 0.0 and wt[0, 1, 0] == 1.0
    assert out[0, 2, 0] == 0.0 and wt[0, 2, 0] == 0.0


def test_mine_hard_examples_max_negative():
    N, P = 2, 6
    cls_loss = np.array([[5, 4, 3, 2, 1, 0.5],
                         [1, 6, 2, 5, 3, 4]], "float32")
    match = np.array([[0, -1, -1, -1, -1, -1],
                      [-1, 0, -1, 1, -1, -1]], "int32")
    dist = np.zeros((N, P), "float32")  # all below neg_dist_threshold
    neg, updated = _run_op(
        "mine_hard_examples",
        {"ClsLoss": ("c", cls_loss), "MatchIndices": ("m", match),
         "MatchDist": ("d", dist)},
        {"NegIndices": "n", "UpdatedMatchIndices": "u"},
        {"neg_pos_ratio": 2.0, "neg_dist_threshold": 0.5,
         "mining_type": "max_negative"},
    )
    np.testing.assert_array_equal(updated, match)
    # image 0: 1 positive -> 2 negatives, the highest-loss unmatched: p1, p2
    np.testing.assert_array_equal(neg[0], [0, 1, 1, 0, 0, 0])
    # image 1: 2 positives -> 4 negatives among eligible {0,2,4,5}: all 4
    np.testing.assert_array_equal(neg[1], [1, 0, 1, 0, 1, 1])


def test_density_prior_box_counts_and_geometry():
    feat = np.zeros((1, 8, 4, 4), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    fv = layers.data(name="feat", shape=[8, 4, 4], dtype="float32")
    iv = layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    boxes, var = layers.density_prior_box(
        fv, iv, densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0],
        clip=True)
    exe = pt.Executor(pt.CPUPlace())
    b, v = exe.run(feed={"feat": feat, "img": img}, fetch_list=[boxes, var])
    b = np.asarray(b)
    assert b.shape == (4, 4, 4, 4)  # H, W, density^2 priors, 4
    assert (b >= 0).all() and (b <= 1).all()
    w = b[..., 2] - b[..., 0]
    assert np.all(w <= 8.0 / 32 + 1e-6)
    np.testing.assert_allclose(np.asarray(v)[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_detection_map_hand_computed():
    # 1 image, 2 classes (bg=0 skipped), 2 gts of class 1; 3 detections:
    # det0 matches gt0 (score .9 tp), det1 misses (score .8 fp),
    # det2 matches gt1 (score .7 tp)
    det = np.array([[[1, 0.9, 0.0, 0.0, 0.4, 0.4],
                     [1, 0.8, 0.6, 0.6, 0.9, 0.9],
                     [1, 0.7, 0.0, 0.5, 0.4, 0.9],
                     [-1, 0, 0, 0, 0, 0]]], "float32")
    gt = np.array([[[1, 0.0, 0.0, 0.4, 0.4, 0],
                    [1, 0.0, 0.5, 0.4, 0.9, 0],
                    [-1, 0, 0, 0, 0, 0]]], "float32")
    dv = layers.data(name="det", shape=[4, 6], dtype="float32")
    gv = layers.data(name="gt", shape=[3, 6], dtype="float32")
    m = layers.detection_map(dv, gv, class_num=2)
    exe = pt.Executor(pt.CPUPlace())
    (mv,) = exe.run(feed={"det": det, "gt": gt}, fetch_list=[m])
    # integral AP: rec/prec points (.5, 1.0), (.5, .5), (1.0, 2/3)
    # AP = .5*1.0 + .5*(2/3) = 5/6
    np.testing.assert_allclose(np.asarray(mv)[0], 5.0 / 6.0, atol=1e-5)


def _toy_ssd_data(bs, rs):
    """Images with one bright square; gt = its box, label 1."""
    imgs = np.zeros((bs, 1, 32, 32), "float32")
    gtb = np.zeros((bs, 2, 4), "float32")
    gtl = np.zeros((bs, 2), "int64")
    cnt = np.ones((bs,), "int64")
    for i in range(bs):
        cx, cy = rs.randint(6, 26, 2)
        s = rs.randint(4, 8)
        x1, y1 = max(cx - s, 0), max(cy - s, 0)
        x2, y2 = min(cx + s, 31), min(cy + s, 31)
        imgs[i, 0, y1:y2, x1:x2] = 1.0
        gtb[i, 0] = [x1 / 32, y1 / 32, x2 / 32, y2 / 32]
        gtl[i, 0] = 1
    return imgs, gtb, gtl, cnt


def test_ssd_trains_end_to_end():
    bs = 8
    rs = np.random.RandomState(0)
    img = layers.data(name="img", shape=[1, 32, 32], dtype="float32")
    gtb = layers.data(name="gtb", shape=[2, 4], dtype="float32")
    gtl = layers.data(name="gtl", shape=[2], dtype="int64")
    cnt = layers.data(name="cnt", shape=[], dtype="int64")

    c1 = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                       stride=2, act="relu")              # [B,8,16,16]
    c2 = layers.conv2d(c1, num_filters=16, filter_size=3, padding=1,
                       stride=2, act="relu")              # [B,16,8,8]
    c3 = layers.conv2d(c2, num_filters=16, filter_size=3, padding=1,
                       stride=2, act="relu")              # [B,16,4,4]
    locs, confs, boxes, vars_ = layers.multi_box_head(
        inputs=[c2, c3], image=img, base_size=32, num_classes=2,
        aspect_ratios=[[1.0], [1.0]], min_sizes=[8.0, 16.0],
        max_sizes=[16.0, 24.0], flip=False)
    loss = layers.ssd_loss(locs, confs, gtb, gtl, boxes, vars_,
                           gt_count=cnt)
    avg = layers.mean(loss)
    dets, det_cnt = layers.detection_output(
        locs, confs, boxes, vars_, score_threshold=0.3, nms_top_k=16,
        keep_top_k=8)
    pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(avg)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(60):
        xb, bb, lb, cb = _toy_ssd_data(bs, rs)
        (lv,) = exe.run(feed={"img": xb, "gtb": bb, "gtl": lb, "cnt": cb},
                        fetch_list=[avg])
        losses.append(float(np.asarray(lv)))
    assert np.isfinite(losses).all(), losses[-5:]
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])

    # inference pass produces finite decoded boxes in [~0, ~1]
    test_prog = pt.default_main_program().clone(for_test=True)
    xb, bb, lb, cb = _toy_ssd_data(bs, rs)
    d, dc = exe.run(test_prog,
                    feed={"img": xb, "gtb": bb, "gtl": lb, "cnt": cb},
                    fetch_list=[dets, det_cnt])
    d = np.asarray(d)
    assert d.shape[0] == bs and d.shape[2] == 6
    assert np.isfinite(d[:, :, 2:]).all()
