"""Memory-tier ops: the recompute scheduling gate and the host-offload
memcpy pair (paddle_tpu/memory — the Fluid memory-optimization transpiler
class, rebuilt as graph rewrites over XLA).

All three are IDENTITY ops value-wise; what they buy is scheduling/CSE
structure the memory rewrites need:

  * `recompute_barrier` — optimization_barrier identity.  The recompute
    pass (memory/recompute.py) reads every cloned segment's boundary
    inputs through one of these so (a) XLA's CSE cannot merge the clone
    chain back into the stashed original (which would silently reinstate
    the activation stash the pass removed), and (b) when a `Gate` value
    from the incoming backward is attached, the barrier ties the clone
    chain's start to the backward front — the jax.checkpoint
    scheduling idiom, so the recomputation cannot be hoisted into the
    forward where it would defeat the memory win.
  * `memcpy_d2h` / `memcpy_h2d` — paired host-offload copies
    (memory/offload.py): d2h parks a long-lived stash var in host memory
    at its last forward use; h2d fetches it back at the backward's first
    read (Gate-tied like the barrier).  Lowerings ride
    jax.device_put with memory kinds (pinned_host <-> device) when the
    runtime supports them in-jit, and degrade to an optimization_barrier
    identity otherwise — value-identical either way, asserted in
    tests/test_memory.py.  Eagerly-executed (imperative) memcpys ride
    np.asarray / reader.decorator.device_put_chunked, the chunked
    host<->device path the feed tier already uses.
"""

from __future__ import annotations

from ..core.registry import register


def _identity_infer(ctx):
    ctx.set_output("Out", ctx.input_shape("X"), ctx.input_dtype("X"))


def _is_traced(x) -> bool:
    import jax.core

    return isinstance(x, jax.core.Tracer)


def _memory_kind_put(x, kind: str):
    """device_put to a memory kind inside a trace; None when this
    jax/backend combination cannot (caller falls back to a barrier)."""
    try:
        import jax
        from jax._src.sharding_impls import TransferToMemoryKind

        return jax.device_put(x, TransferToMemoryKind(kind))
    except Exception:
        return None


@register("recompute_barrier", infer_shape=_identity_infer, no_grad=True,
          doc="optimization-barrier identity guarding a recompute "
              "segment's boundary input (memory/recompute.py)")
def lower_recompute_barrier(ctx, ins):
    import jax

    x = ins["X"][0]
    gate = (ins.get("Gate") or [None])[0]
    if gate is not None:
        x, _ = jax.lax.optimization_barrier((x, gate))
        return {"Out": [x]}
    return {"Out": [jax.lax.optimization_barrier(x)]}


@register("memcpy_d2h", infer_shape=_identity_infer, no_grad=True,
          doc="park a stash var in host memory at its liveness edge "
              "(memory/offload.py)")
def lower_memcpy_d2h(ctx, ins):
    import jax
    import numpy as np

    x = ins["X"][0]
    if not _is_traced(x):
        # eager/imperative: a real device->host readback
        return {"Out": [np.asarray(x)]}
    out = _memory_kind_put(x, "pinned_host")
    if out is None:
        out = jax.lax.optimization_barrier(x)
    return {"Out": [out]}


@register("memcpy_h2d", infer_shape=_identity_infer, no_grad=True,
          doc="fetch an offloaded stash var back to HBM at the "
              "backward's first read (memory/offload.py)")
def lower_memcpy_h2d(ctx, ins):
    import jax

    x = ins["X"][0]
    gate = (ins.get("Gate") or [None])[0]
    if not _is_traced(x):
        from ..reader.decorator import device_put_chunked

        return {"Out": [device_put_chunked(x)]}
    if gate is not None:
        # the fetch must not be hoisted ahead of the backward front: tie
        # it to the earliest available backward value, like the
        # recompute barrier
        x, _ = jax.lax.optimization_barrier((x, gate))
    out = _memory_kind_put(x, "device")
    if out is None:
        out = jax.lax.optimization_barrier(x)
    return {"Out": [out]}


__all__ = ["lower_recompute_barrier", "lower_memcpy_d2h",
           "lower_memcpy_h2d"]
