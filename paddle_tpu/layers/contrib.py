"""Contrib layers: fused/TPU-native extensions beyond the reference API."""

from __future__ import annotations

from ..layer_helper import LayerHelper


def fused_attention(q, k, v, bias=None, scale=1.0, causal=False,
                    dropout_rate=0.0, block_q=512, block_k=512,
                    fmt="bhtd", weights_dropout=True, name=None):
    """Flash-attention layer (Pallas kernel on TPU) over [B,H,T,D] tensors
    (fmt="bhtd") or [B,T,H,D] tensors (fmt="bthd" — the transpose-free
    convention: reshape the projection output [B,T,H*D] to [B,T,H,D] and
    skip split/merge-head transposes entirely).

    With dropout_rate > 0 and weights_dropout=True (default), dropout
    applies to the attention WEIGHTS inside the kernels (the reference's
    dropout-on-softmax semantics, transformer_model.py:44) via a
    deterministic per-step mask that never exists in HBM: on compiled
    TPU the bits come from the hardware PRNG re-seeded per tile
    (kernels/attention.py _keep_tile_prng, FLAGS_tpu_prng_dropout —
    this removed the O(T²·H) hash-regeneration cost that used to make
    long sequences a net loss, so weights-dropout is now the default at
    every length); interpret/XLA fallbacks use the counter-based hash
    (kernels/hash_rng.py).  weights_dropout=False instead applies hash
    dropout to the attention OUTPUT (O(T·D) work, flash-style
    semantics)."""
    from ..core import framework as fw

    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    in_kernel_rate = dropout_rate if weights_dropout else 0.0
    helper.append_op(
        "fused_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "causal": causal,
            "block_q": block_q,
            "block_k": block_k,
            "fmt": fmt,
            "dropout_rate": float(in_kernel_rate),
            "rng_id": fw.unique_rng_id() if in_kernel_rate else 0,
        },
    )
    out.shape = q.shape
    if dropout_rate and not weights_dropout:
        from .nn import dropout

        out = dropout(out, dropout_prob=dropout_rate,
                      dropout_implementation="upscale_in_train")
    return out


def fused_qkv_attention(x, n_head, d_key, d_model, bias=None, scale=1.0,
                        causal=False, dropout_rate=0.0, block_q=512,
                        block_k=512, qkv_param_attr=None,
                        out_param_attr=None, name=None):
    """Self-attention layer with the q/k/v AND output projections fused
    into the flash-attention kernels (ops/fused_ops.py
    fused_qkv_attention; kernels/attention.py flash_qkv_attention).

    Creates the SAME two parameters as the unfused fc + split +
    fused_attention + fc composition — [d_model_in, 3*n_head*d_key]
    packed qkv weight and [n_head*d_key, d_model] output weight, same
    shapes, same default initializer — so checkpoints interop across
    FLAGS_fused_qkv_attention (pass the unfused path's names via
    qkv_param_attr/out_param_attr).  Weights-dropout semantics follow
    fused_attention (reference dropout-on-softmax, mask never in HBM)."""
    from ..core import framework as fw

    dtype = x.dtype
    # parameters ride the SAME LayerHelper("fc") name sequence as the
    # unfused qkv-fc + output-fc pair (the conv2d_bn recipe): explicit
    # attr names match trivially, and DEFAULT names — plus every later
    # unnamed fc in the model — land on identical fc_N draws, so
    # checkpoints interop across FLAGS_fused_qkv_attention (asserted in
    # tests/test_fused_qkv_attention.py on the BERT builder, whose ffn/
    # head fcs are unnamed)
    qkv_helper = LayerHelper("fc", param_attr=qkv_param_attr)
    w_qkv = qkv_helper.create_parameter(
        qkv_helper.param_attr(), shape=[x.shape[-1], 3 * d_key * n_head],
        dtype=dtype)
    out_helper = LayerHelper("fc", param_attr=out_param_attr)
    w_out = out_helper.create_parameter(
        out_helper.param_attr(), shape=[d_key * n_head, d_model],
        dtype=dtype)
    helper = LayerHelper("fused_qkv_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "WQkv": [w_qkv], "WOut": [w_out]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op(
        "fused_qkv_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "n_head": n_head,
            "scale": float(scale),
            "causal": causal,
            "block_q": block_q,
            "block_k": block_k,
            "dropout_rate": float(dropout_rate),
            "rng_id": fw.unique_rng_id() if dropout_rate else 0,
        },
    )
    out.shape = tuple(x.shape[:-1]) + (d_model,)
    return out


def ring_attention(q, k, v, scale=1.0, causal=False, axis_name="sp",
                   fmt="bhtd", name=None):
    """Context-parallel attention layer over [B,H,T,D] (fmt "bhtd") or
    [B,T,H,D] (fmt "bthd" — the transpose-free convention; the ring path
    reuses the single-device bthd block specs, so CP introduces no
    split/merge-head transposes) tensors: the T axis shards over mesh
    axis `axis_name` (see ops/fused_ops.py ring_attention).  Use through
    a ShardingPlan whose mesh declares that axis."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        "ring_attention",
        inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "causal": causal,
               "axis_name": axis_name, "fmt": fmt},
    )
    out.shape = q.shape
    return out
