"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
ErrorClipByValue, set_gradient_clip)."""

from __future__ import annotations

from .core import framework as fw
from .layer_helper import LayerHelper


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def append_clip_op(self, block, grad_name):
        block.append_op(
            "clip",
            inputs={"X": [grad_name]},
            outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


class GradientClipBase:
    def _process(self, param, grad):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _process(self, param, grad):
        helper = LayerHelper("clip_grad")
        out = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op(
            "clip",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"min": self.min, "max": self.max,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _process(self, param, grad):
        helper = LayerHelper("clip_grad_norm")
        out = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op(
            "clip_by_norm",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"max_norm": self.clip_norm,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """scale_i = clip_norm / max(global_norm, clip_norm) applied to every
    grad (reference: clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _process_all(self, params_grads):
        helper = LayerHelper("global_norm_clip")
        block = None
        sq_norms = []
        for p, g in params_grads:
            if g is None:
                continue
            block = g.block
            sq = helper.create_variable_for_type_inference(g.dtype)
            block.append_op(
                "squared_l2_norm", inputs={"X": [g]}, outputs={"Out": [sq]},
                attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
            )
            sq_norms.append(sq)
        if block is None:
            return params_grads
        total = helper.create_variable_for_type_inference("float32")
        block.append_op("sum", inputs={"X": sq_norms}, outputs={"Out": [total]},
                        attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward})
        gnorm = helper.create_variable_for_type_inference("float32")
        block.append_op("sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]},
                        attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward})
        # denom = max(global_norm, clip_norm); scale = clip_norm / denom
        clip_var = helper.create_variable_for_type_inference("float32")
        block.append_op(
            "fill_constant", outputs={"Out": [clip_var]},
            attrs={"shape": [1], "value": float(self.clip_norm),
                   "dtype": "float32",
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        denom = helper.create_variable_for_type_inference("float32")
        block.append_op(
            "elementwise_max", inputs={"X": [gnorm], "Y": [clip_var]},
            outputs={"Out": [denom]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        scale = helper.create_variable_for_type_inference("float32")
        block.append_op(
            "elementwise_div", inputs={"X": [clip_var], "Y": [denom]},
            outputs={"Out": [scale]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            ng = helper.create_variable_for_type_inference(g.dtype)
            g.block.append_op(
                "elementwise_mul", inputs={"X": [g], "Y": [scale]},
                outputs={"Out": [ng]},
                attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
            )
            out.append((p, ng))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    """Attach a clip strategy to `program` (default: the default main
    program) — scoped to the program, not process-global, so building a
    second model does not inherit the first one's clipping."""
    program = program or fw.default_main_program()
    program._gradient_clip = clip
    if param_list:
        for p in param_list:
            if isinstance(p, str):
                p = program.global_block().var(p)
            p.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads, program=None):
    program = program or fw.default_main_program()
    prog_clip = getattr(program, "_gradient_clip", None)
    if prog_clip is None and not any(
        getattr(p, "gradient_clip_attr", None) for p, g in param_grads
    ):
        return param_grads
    if isinstance(prog_clip, GradientClipByGlobalNorm):
        return prog_clip._process_all(param_grads)
    out = []
    for p, g in param_grads:
        clip = getattr(p, "gradient_clip_attr", None) or prog_clip
        if g is None or clip is None or isinstance(clip, GradientClipByGlobalNorm):
            out.append((p, g))
            continue
        out.append((p, clip._process(p, g)))
    return out
