"""Model-level integration test (reference: tests/book/test_recognize_digits.py
— train a few iterations, assert loss decreases, round-trip inference model)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _synthetic_digits(n, rng):
    """Linearly separable 'digit' images: class k has a bright kxk corner."""
    x = rng.rand(n, 1, 28, 28).astype("float32") * 0.1
    y = rng.randint(0, 10, (n, 1)).astype("int64")
    for i in range(n):
        k = int(y[i, 0])
        x[i, 0, k : k + 3, k : k + 3] += 1.0
    return x, y


def test_mlp_mnist_converges():
    img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    flat = layers.reshape(img, [-1, 784])
    h = layers.fc(input=flat, size=64, act="relu")
    predict = layers.fc(input=h, size=10, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=predict, label=label)

    opt = pt.optimizer.Adam(learning_rate=0.01)
    opt.minimize(avg_cost)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(42)
    losses = []
    for i in range(30):
        x, y = _synthetic_digits(64, rng)
        loss, a = exe.run(
            feed={"img": x, "label": y}, fetch_list=[avg_cost, acc]
        )
        losses.append(float(np.asarray(loss)))
    assert losses[-1] < losses[0] * 0.5, losses
    assert float(np.asarray(a)) > 0.5


def test_lenet_forward_shapes():
    from paddle_tpu.models.mnist import build_train_net

    img, label, avg_cost, acc, predict = build_train_net()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    x = rng.rand(8, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, (8, 1)).astype("int64")
    p, loss = exe.run(
        feed={"pixel": x, "label": y}, fetch_list=[predict, avg_cost]
    )
    assert p.shape == (8, 10)
    np.testing.assert_allclose(p.sum(-1), np.ones(8), atol=1e-5)


def test_save_load_inference_model(tmp_path):
    img = layers.data(name="img", shape=[4], dtype="float32")
    h = layers.fc(input=img, size=3, act="softmax")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    x = np.random.rand(5, 4).astype("float32")
    (out1,) = exe.run(feed={"img": x}, fetch_list=[h])

    pt.io.save_inference_model(str(tmp_path / "model"), ["img"], [h], exe)

    # fresh scope + program
    scope = pt.Scope()
    prog, feeds, fetches = pt.io.load_inference_model(
        str(tmp_path / "model"), exe, scope=scope
    )
    out2 = exe.run(
        prog, feed={feeds[0]: x}, fetch_list=fetches, scope=scope
    )[0]
    np.testing.assert_allclose(out1, out2, atol=1e-6)


def test_save_load_persistables(tmp_path):
    img = layers.data(name="img", shape=[4], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    h = layers.fc(input=img, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(input=h, label=label))
    pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    x = np.random.rand(5, 4).astype("float32")
    y = np.random.randint(0, 3, (5, 1)).astype("int64")
    exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

    # snapshot state after 1 step, then take step 2 in two universes
    pt.io.save_persistables(exe, str(tmp_path / "ckpt"), filename="all")
    (loss1,) = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

    scope = pt.Scope()
    pt.io.load_persistables(exe, str(tmp_path / "ckpt"), filename="all", scope=scope)
    # adam moments restored -> identical next-step loss
    exe2 = pt.Executor(pt.CPUPlace())
    (loss2,) = exe2.run(
        pt.default_main_program(), feed={"img": x, "label": y},
        fetch_list=[loss], scope=scope,
    )
    np.testing.assert_allclose(np.asarray(loss1), np.asarray(loss2), atol=1e-5)
