"""DeepFM CTR model (reference: python/paddle/fluid/tests/unittests/
dist_ctr.py + dist_ctr_reader.py — the sparse-lookup_table workload of
BASELINE.md).

Sparse path notes: embeddings use lookup_table with is_sparse=True (row-
sparse grads; lookup_table_op.h:132 parity).  On TPU the table lives in HBM
sharded over the mesh (ShardingPlan rule on the embedding param) — the
pserver-distributed path of the reference (remote_prefetch,
parameter_prefetch.cc) maps to mesh-sharded gathers, SURVEY.md §2.4."""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


# dist_ctr_reader.py: dense 13 continuous + 26 categorical slots hashed to 1e6
DENSE_DIM = 13
SPARSE_SLOTS = 26
HASH_DIM = 10001  # scaled-down default (dist_ctr uses 1000001)


def ctr_deepfm(dense_input, sparse_inputs, embedding_size=10,
               hash_dim=HASH_DIM, is_sparse=True, fm=True,
               hidden_sizes=(400, 400, 400)):
    """Returns click probability [B, 2] (softmax)."""
    # --- embeddings (shared table per slot, reference dist_ctr.py style) ---
    emb_outs = []
    first_order = []
    for i, slot in enumerate(sparse_inputs):
        emb = layers.embedding(
            slot,
            size=[hash_dim, embedding_size],
            is_sparse=is_sparse,
            param_attr=ParamAttr(name=f"deepfm_emb_{i}"),
        )
        # slot input is [B, 1] ids -> emb [B, emb]
        emb_outs.append(emb)
        if fm:
            w1 = layers.embedding(
                slot,
                size=[hash_dim, 1],
                is_sparse=is_sparse,
                param_attr=ParamAttr(name=f"deepfm_w1_{i}"),
            )
            first_order.append(w1)

    concat_emb = layers.concat(emb_outs, axis=1)  # [B, slots*emb]

    parts = [dense_input, concat_emb]

    if fm:
        # FM second-order: 0.5 * ((sum v)^2 - sum v^2), fields stacked
        stacked = layers.stack(emb_outs, axis=1)  # [B, slots, emb]
        sum_v = layers.reduce_sum(stacked, dim=1)  # [B, emb]
        sum_sq = layers.square(sum_v)
        sq = layers.square(stacked)
        sq_sum = layers.reduce_sum(sq, dim=1)
        second = layers.scale(
            layers.elementwise_sub(sum_sq, sq_sum), scale=0.5
        )
        first = layers.concat(first_order, axis=1)  # [B, slots]
        parts += [first, second]

    x = layers.concat(parts, axis=1)
    for i, h in enumerate(hidden_sizes):
        x = layers.fc(input=x, size=h, act="relu",
                      param_attr=ParamAttr(name=f"deepfm_fc{i}_w"),
                      bias_attr=ParamAttr(name=f"deepfm_fc{i}_b"))
    return layers.fc(input=x, size=2, act="softmax",
                     param_attr=ParamAttr(name="deepfm_out_w"),
                     bias_attr=ParamAttr(name="deepfm_out_b"))


def build_train_net(embedding_size=10, hash_dim=HASH_DIM, is_sparse=True,
                    with_optimizer=True, lr=1e-3, optimizer="adam"):
    """optimizer: "sgd" (reference dist_ctr.py:107 parity — fully row-sparse
    updates, per-step cost O(touched rows)) or "adam" (lazy_mode is enabled
    so the sparse tables keep row-sparse moment updates, adam_op.h:233)."""
    from .. import optimizer as opt_mod

    dense = layers.data(name="dense_input", shape=[DENSE_DIM], dtype="float32")
    sparse = [
        layers.data(name=f"C{i}", shape=[1], dtype="int64")
        for i in range(SPARSE_SLOTS)
    ]
    label = layers.data(name="click", shape=[1], dtype="int64")
    predict = ctr_deepfm(dense, sparse, embedding_size, hash_dim, is_sparse)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    auc_var, _ = layers.auc(input=predict, label=label)
    if with_optimizer:
        if optimizer == "sgd":
            opt_mod.SGD(learning_rate=lr).minimize(avg_cost)
        else:
            opt_mod.Adam(learning_rate=lr, lazy_mode=True).minimize(avg_cost)
    # Fused sparse tier (PERF.md round 8): coalesce the 2x26 per-slot
    # lookup_table ops, their grads, and the per-table sgd/lazy-adam
    # chains into one multi-table launch per table group.  Parameter and
    # grad names are untouched, so checkpoints interop across the flag;
    # flag off leaves the graph op-for-op identical to the per-slot
    # composition above.
    from ..flags import FLAGS

    if FLAGS.fused_embedding:
        from .. import passes

        prog = avg_cost.block.program
        passes.apply_pass("fused_embedding", prog)
    feeds = ["dense_input"] + [f"C{i}" for i in range(SPARSE_SLOTS)] + ["click"]
    return avg_cost, auc_var, predict, feeds


def make_batch(batch_size, hash_dim=HASH_DIM, rng=None):
    """Synthetic CTR batch with a LEARNABLE click signal: the label
    depends on the dense features (plus noise), so a training loop can
    drive log-loss below ln 2 — the bench asserts that decrease
    (self-validating record; random labels would pin loss at ln 2)."""
    import numpy as np

    rng = rng or np.random.RandomState(0)
    dense = rng.rand(batch_size, DENSE_DIM).astype("float32")
    feed = {"dense_input": dense}
    for i in range(SPARSE_SLOTS):
        feed[f"C{i}"] = rng.randint(0, hash_dim, (batch_size, 1)).astype("int64")
    logit = 4.0 * (dense[:, 0] - 0.5) + 2.0 * (dense[:, 1] - 0.5)
    p = 1.0 / (1.0 + np.exp(-logit))
    feed["click"] = (rng.rand(batch_size) < p).astype("int64")[:, None]
    return feed
