"""Typed runtime flags with environment overrides (reference: the gflags
surface — ~50 FLAGS_* defined at point-of-use, e.g.
FLAGS_check_nan_inf operator.cc:943, FLAGS_fraction_of_gpu_memory_to_use
gpu_info.cc, FLAGS_allocator_strategy allocator_strategy.cc — plus the
Python bootstrap that whitelists FLAGS_* env vars into gflags,
python/paddle/fluid/__init__.py:95-170 __bootstrap__).

TPU-first: one typed registry (SURVEY §5.6 plan) instead of scattered
globals.  Flags are declared with a type + default + help; values resolve
in priority order CLI-set < env (`FLAGS_<name>`) < programmatic set_flag.
`paddle_tpu.flags.FLAGS.<name>` reads; unknown names raise.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: lambda s: int(s, 0),
    float: float,
    str: str,
}


class _FlagDef:
    __slots__ = ("name", "type", "default", "help")

    def __init__(self, name, type_, default, help_):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_


class _Flags:
    def __init__(self):
        object.__setattr__(self, "_defs", {})
        object.__setattr__(self, "_values", {})

    def define(self, name: str, type_: type, default, help_: str = ""):
        if name in self._defs:
            raise ValueError(f"flag {name!r} already defined")
        if type_ not in _PARSERS:
            raise TypeError(f"unsupported flag type {type_!r}")
        self._defs[name] = _FlagDef(name, type_, default, help_)

    def __getattr__(self, name):
        defs = object.__getattribute__(self, "_defs")
        if name not in defs:
            raise AttributeError(f"unknown flag {name!r}")
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            return _PARSERS[defs[name].type](env)
        return defs[name].default

    def __setattr__(self, name, value):
        self.set(name, value)

    def set(self, name, value):
        defs = object.__getattribute__(self, "_defs")
        if name not in defs:
            raise AttributeError(f"unknown flag {name!r}")
        d = defs[name]
        if not isinstance(value, d.type):
            value = _PARSERS[d.type](str(value))
        object.__getattribute__(self, "_values")[name] = value

    def reset(self, name=None):
        values = object.__getattribute__(self, "_values")
        if name is None:
            values.clear()
        else:
            values.pop(name, None)

    def help(self) -> str:
        defs = object.__getattribute__(self, "_defs")
        lines = []
        for d in sorted(defs.values(), key=lambda d: d.name):
            lines.append(
                f"FLAGS_{d.name} ({d.type.__name__}, default "
                f"{d.default!r}): {d.help}")
        return "\n".join(lines)


FLAGS = _Flags()

# -- the framework's flag surface (reference points cited per flag) ---------

FLAGS.define(
    "check_nan_inf", bool, False,
    "validate every op output for NaN/Inf and name the offending op "
    "(reference FLAGS_check_nan_inf, operator.cc:943)")
FLAGS.define(
    "check_numerics", str, "off",
    "numerics observability tier (analysis/numerics.py + "
    "monitor/numerics.py): 'off' = zero-cost (no graph change, "
    "byte-identical fingerprint), 'summary' = instrument grads / param "
    "updates / loss with one fused stats reduction per tensor, packed "
    "into a single [N,4] device->host fetch per step and published as "
    "per-param-group gauges (grad-norm, weight-norm, update-to-weight "
    "ratio), 'locate' = per-op-output instrumentation naming the first "
    "op in topological order with a non-finite output — the reference "
    "FLAGS_check_nan_inf rebuilt for whole-block XLA; also enables the "
    "watchdog's deterministic failing-step replay on a nan_loss trip")
FLAGS.define(
    "benchmark", bool, False,
    "synchronize after every executor call for stable timing "
    "(reference FLAGS_benchmark, operator.cc:938)")
FLAGS.define(
    "cpu_deterministic", bool, True,
    "kept for parity; determinism is free under XLA "
    "(reference FLAGS_cpu_deterministic)")
FLAGS.define(
    "eager_delete_tensor_gb", float, 0.0,
    "kept for parity; buffer lifetime is XLA's job "
    "(reference FLAGS_eager_delete_tensor_gb)")
FLAGS.define(
    "prefetch_chunk_mb", int, 32,
    "chunk size for double-buffer host->device transfers "
    "(reader/decorator.py device_put_chunked)")
FLAGS.define(
    "prefetch_threads", int, 4,
    "thread-pool width for chunked host->device transfers")
FLAGS.define(
    "synthetic_data", bool, False,
    "datasets yield synthetic offline samples (same as "
    "PADDLE_TPU_SYNTH_DATA=1)")
FLAGS.define(
    "hash_dropout", bool, True,
    "generate dropout masks with the fusible counter-based hash PRNG "
    "(kernels/hash_rng.py) instead of jax.random.bernoulli; the hash "
    "fuses into consumers so no random-bits tensor exists in HBM")
FLAGS.define(
    "tpu_prng_dropout", bool, True,
    "in-kernel dropout masks (flash attention weights-dropout, fused "
    "dropout-add epilogue) draw bits from the TPU hardware PRNG "
    "(pltpu.prng_seed/prng_random_bits, re-seeded per tile) instead of "
    "the lowbias32 hash chain; compiled-TPU only — interpret mode and "
    "the XLA fallbacks always use the hash (kernels/attention.py, "
    "kernels/dropout_epilogue.py)")
FLAGS.define(
    "fused_bn", bool, True,
    "NHWC training batch-norm runs the fused Pallas BN path "
    "(kernels/conv_bn.py): models emit one conv2d_bn op per "
    "conv->bn[->add->relu] chain (1x1 convs as a dot with a BN-stats "
    "epilogue; other convs keep XLA's conv with a one-pass stats kernel), "
    "and standalone NHWC batch_norm uses the one-pass stats + fused "
    "apply kernels with a backward that folds the dgamma/dbeta channel "
    "reductions into the dx pass; off = the reference conv2d + "
    "batch_norm composition with XLA's separate stat reductions "
    "(flag-off graphs are op-for-op identical to the pre-fusion ones)")
FLAGS.define(
    "fused_embedding", bool, True,
    "the sparse embedding tier coalesces same-shape per-slot lookup_table "
    "op groups into one fused multi-table gather launch (ids prefetched "
    "via scalar memory), their grads into one SelectedRows-compatible "
    "fused grad, and the per-table sgd/lazy-adam chains into one "
    "row-sparse group apply (kernels/embedding.py, passes.py "
    "fused_embedding pass; applied by models/deepfm.py); off = the "
    "reference per-slot composition, graphs op-for-op identical to the "
    "pre-fusion ones")
FLAGS.define(
    "fused_qkv_attention", bool, True,
    "transformer/BERT self-attention sites lower to ONE fused_qkv_attention "
    "op whose Pallas kernels compute the q/k/v and output projection dots "
    "tile-by-tile inside the flash-attention grid (kernels/attention.py "
    "flash_qkv_attention): q/k/v never exist in HBM, so the dot-preferred"
    "<->custom-call relayout copies at the projection boundaries disappear "
    "(PERF.md round 9); off = the reference fc + split + fused_attention + "
    "fc composition, graphs op-for-op identical to the pre-fusion ones and "
    "parameter names unchanged (checkpoints interop)")
FLAGS.define(
    "kv_cache", bool, True,
    "autoregressive generation rides the KV-cache decode path "
    "(paddle_tpu/generation): prefill writes per-layer K/V into "
    "ring-buffer scope state [L, b, max_t, h, dh] threaded through the "
    "executor's donated rw-state machinery, and every generated token "
    "runs ONE compiled single-query decode program (dynamic-slice cache "
    "writes, length-independent compile key); models/transformer.py "
    "build_decoder carries the same cache through its beam-search While "
    "loop; off = the per-step full-prefix recompute route, output-"
    "identical (parity asserted in tests/test_generation.py)")
FLAGS.define(
    "fused_decode_step", bool, True,
    "cached_decoder_step lowers each decoder layer of the per-token "
    "decode program to ONE fused_decode_step op (kernels/decode_step.py "
    "per-layer Pallas megastep: qkv projection, in-place cache row write "
    "at the runtime counter, single-query online-softmax walk, output "
    "projection, residual+layer-norm epilogues — q/k/v and the attention "
    "context never exist in HBM), and greedy kv-cache decode programs "
    "self-feed the sampled token through scope state (the host stops "
    "round-tripping it); off = the reference per-layer composition "
    "(fc + kv_cache_update + decode_attention + fc + layer_norm chain), "
    "graphs op-for-op identical to the pre-fusion ones and parameter "
    "names unchanged (checkpoints interop); off-contract shapes run the "
    "numerically-identical XLA fallback inside the op")
FLAGS.define(
    "flash_decode", bool, True,
    "the decode_attention op lowers to the Pallas single-query flash-"
    "decode kernel (kernels/decode_attention.py: one q row against the "
    "HBM-resident growing cache, online softmax over DMA'd k/v blocks, "
    "per-sequence lengths scalar-prefetched so masked tail blocks are "
    "never read) when the plan gate accepts; off or off-contract = the "
    "numerically-identical XLA fallback")
FLAGS.define(
    "paged_kv_cache", bool, False,
    "generation programs allocate the KV cache as a paged block pool "
    "(generation/kv_cache.py PagedKVCache: a global [layers, blocks, "
    "block_t, heads, d_head] pool per side plus per-slot int32 block "
    "tables, free-list/ref-count allocator with copy-on-write append) "
    "instead of the contiguous ring buffer; decode attention and the "
    "fused megastep walk blocks through the table. Off (default) = the "
    "ring layout, byte-stable graphs, unchanged parameter names")
FLAGS.define(
    "kv_block_t", int, 16,
    "rows (time steps) per KV-cache block when FLAGS_paged_kv_cache is "
    "on; must be a multiple of 8 (TPU sublane quantum). Small blocks "
    "cut per-sequence HBM waste to <block_t rows (vs the ring's 128-"
    "row quanta) which is the concurrent-slot capacity win; large "
    "blocks amortize DMA issue overhead in the block walk")
FLAGS.define(
    "kv_cache_blocks", int, 0,
    "total blocks in the paged KV pool per side (self/cross); 0 = "
    "auto, sized ring-equivalent (slots x ceil(max_t / block_t)) so "
    "the static identity mapping reproduces the ring capacity exactly. "
    "Serving deployments set this to the HBM budget and let block-"
    "budget admission carry more short sequences than slot-count would")
FLAGS.define(
    "serving_decode_slots", int, 4,
    "default cache-slot count (the decode batch dimension) of a "
    "generation serving model (paddle_tpu/serving/generation.py): the "
    "continuous batcher coalesces decode steps across up to this many "
    "in-flight sequences; per-model override via GenerationConfig.slots")
FLAGS.define(
    "pipelined_feed", bool, True,
    "AsyncExecutor.run_from_files overlaps host ingest with device "
    "compute: batch N+1's feed arrays are device_put while step N "
    "executes, and step N's fetches materialize one step late "
    "(data_feed.py; off = the strict parse->put->run->sync loop)")
FLAGS.define(
    "fused_dropout_add", bool, True,
    "the bundled transformer/BERT models lower their dropout+residual "
    "pairs through the fused dropout-add epilogue kernel "
    "(kernels/dropout_epilogue.py): one Pallas kernel, mask regenerated "
    "from scalar seeds in the backward, no mask or random-bits tensor in "
    "HBM; off = the separate graph-level hash dropout + add ops")
FLAGS.define(
    "recompute", str, "",
    "activation-recompute (gradient checkpointing) policy for the memory "
    "tier (paddle_tpu/memory/recompute.py), applied by "
    "memory.maybe_optimize_memory consumers (bench.py --recompute, user "
    "training scripts): '' = off (the rewrite never runs; graphs are "
    "byte-identical to today — the zero-cost contract), 'auto' = "
    "sqrt(N)-segment boundaries chosen over the planner's activation "
    "watermark to minimize estimated peak, or a comma-separated list of "
    "checkpoint var names (the reference's checkpoints= annotation).  "
    "Each segment's forward ops are cloned in front of their grad ops "
    "instead of stashing intermediates; RNG-deriving ops replay the SAME "
    "step key via their static rng_id (dropout masks bit-identical "
    "between stash and recompute, asserted)")
FLAGS.define(
    "recompute_segments", int, 0,
    "with FLAGS_recompute=auto: explicit segment count; 0 = the "
    "sqrt(N)-over-forward-ops default (Chen et al., sublinear memory)")
FLAGS.define(
    "offload_activations", bool, False,
    "host offload for long-lived stash vars (memory/offload.py): vars "
    "the planner proves have a long fwd->bwd gap and large size get "
    "paired memcpy_d2h/memcpy_h2d ops at their liveness edges — parked "
    "in host memory across the gap, fetched back at the backward's "
    "first read.  Off (default) = the rewrite never runs")
FLAGS.define(
    "offload_min_mb", float, 1.0,
    "offload candidate threshold: minimum var size in MB")
FLAGS.define(
    "offload_min_gap", float, 0.25,
    "offload candidate threshold: minimum fwd->bwd liveness gap as a "
    "fraction of the program's op count")
FLAGS.define(
    "verify_program", bool, True,
    "run the static program verifier (paddle_tpu/analysis) before every "
    "executor compile: def-before-use/SSA across blocks, shape+dtype "
    "contract re-inference, donation/fetch alias conflicts, and the "
    "RNG-determinism lint (key-deriving ops the executor would not "
    "thread the step key for) all raise ProgramVerifyError with named "
    "findings instead of surfacing as late XLA trace errors.  Verified "
    "signatures are memoized per executor, so the cost is one O(program) "
    "walk per compile — zero hot-path cost; the inference server flips "
    "it off once all models are warm (serving/server.py _warmup_verified) "
    "so cold-signature stragglers skip straight to the trace")
FLAGS.define(
    "vlog", int, 0,
    "verbose logging level, like glog's VLOG(n) (reference init.cc "
    "InitGLOG); see paddle_tpu.log")
FLAGS.define(
    "monitor", bool, False,
    "enable the runtime telemetry registry (paddle_tpu.monitor): executor "
    "compile/run/recompile counters, data-feed queue gauges, inference "
    "latency histograms, collective byte counters; off = zero writes on "
    "the hot paths")
FLAGS.define(
    "monitor_jsonl", str, "",
    "path for StepMonitor per-step JSONL records (bench.py/trainer "
    "loops); empty keeps records in memory only")
FLAGS.define(
    "device_model", str, "",
    "device model the static cost model attributes against "
    "(paddle_tpu/analysis/costmodel.py DEVICE_MODELS key, e.g. "
    "'TPU v5e'); empty = auto-detect from the jax backend's device_kind, "
    "falling back to the measured 'cpu-host' entry off-chip")
FLAGS.define(
    "peak_flops", float, 0.0,
    "override the device peak FLOP/s used by the cost model and "
    "StepMonitor MFU (per chip); 0 = resolve from the cost model's "
    "device table — and OMIT MFU entirely when the device is unknown "
    "rather than publish a wrong number")
FLAGS.define(
    "launch_overhead_us", float, 0.0,
    "override the per-launch dispatch overhead (microseconds) the cost "
    "model charges each op; 0 = the device-table constant (measure "
    "yours with `python bench.py --model dispatch`)")
FLAGS.define(
    "flight_dir", str, "",
    "directory for flight-recorder JSONL dumps (monitor/flight.py): on "
    "crash, SIGTERM/SIGUSR1, or watchdog trip the in-memory event ring "
    "is written here so a dead run leaves a black box; empty disables "
    "dumping (the ring still records in memory while FLAGS.monitor is on)")
FLAGS.define(
    "flight_events", int, 2048,
    "capacity of the flight-recorder event ring (bounded memory; oldest "
    "events are evicted first)")
FLAGS.define(
    "monitor_port", int, 0,
    "TCP port for the scrape endpoint (monitor/serve.py): /metrics "
    "Prometheus text, /health, /flight last-N events; 0 disables the "
    "server")
FLAGS.define(
    "health_stall_s", float, 600.0,
    "/health reports a trainer as stalled (HTTP 503) when a step monitor "
    "exists but no step completed for this many seconds; a process that "
    "never stepped (a pure inference server) is never 'stalled' — its "
    "health comes from serving READINESS (monitor/serve.py)")
FLAGS.define(
    "serving_buckets", str, "1,2,4,8,16",
    "default pad-to-bucket batch-size ladder for the inference server "
    "(paddle_tpu/serving): requests coalesce and pad up to the smallest "
    "bucket >= total rows, so the executor compile cache sees a BOUNDED "
    "set of feed signatures; per-model override via ModelConfig.buckets")
FLAGS.define(
    "serving_max_batch", int, 16,
    "default dynamic-batcher cap on coalesced rows per executed batch "
    "(paddle_tpu/serving); effective cap is min(this, largest bucket)")
FLAGS.define(
    "serving_max_wait_ms", float, 5.0,
    "default dynamic-batcher deadline: a queued request is executed at "
    "most this many ms after arrival even if its batch is not full "
    "(latency/fill tradeoff knob of the batching policy)")
FLAGS.define(
    "serving_max_queue_depth", int, 128,
    "admission control: a model's batcher sheds new requests (HTTP 429 "
    "with a Retry-After derived from the observed queue-latency EWMA, "
    "serving.<model>.shed_total counter) once this many requests are "
    "already queued ahead of them; the generation tier bounds its "
    "slot wait-queue the same way.  0 = unbounded queues (the pre-"
    "admission-control behavior: under overload, queue latency grows "
    "without bound and every request times out)")
FLAGS.define(
    "serving_max_inflight", int, 0,
    "server-level cap on concurrently admitted requests across ALL "
    "models of one InferenceServer (predict + generate); at the cap new "
    "requests shed with HTTP 429 + Retry-After.  0 = uncapped")
FLAGS.define(
    "serving_drain_timeout_s", float, 10.0,
    "graceful-drain budget: on SIGTERM the serving CLI flips /health to "
    "'draining' (503), rejects new requests with 503, lets in-flight "
    "and queued-admitted work complete up to this many seconds, dumps "
    "the flight recorder (trigger 'drain'), and exits 0")
FLAGS.define(
    "serving_breaker_threshold", int, 5,
    "per-model circuit breaker: this many CONSECUTIVE batch-execution "
    "failures open the breaker — submits fail fast with HTTP 503 "
    "(serving.<model>.breaker_state gauge: 0 closed / 1 open / 2 half-"
    "open) instead of queueing against a broken executor; after "
    "FLAGS_serving_breaker_cooldown_s ONE half-open probe is admitted "
    "and its outcome closes or re-opens the breaker.  0 disables "
    "(every request reaches the executor, the pre-breaker behavior)")
FLAGS.define(
    "serving_breaker_cooldown_s", float, 5.0,
    "how long an open circuit breaker rejects before admitting its "
    "half-open probe request")
FLAGS.define(
    "serving_cache_dir", str, "",
    "persistent XLA compilation-cache directory for the inference server "
    "(jax compilation cache): warmup compiles of the bucket ladder are "
    "reused across server restarts; empty disables persistence")
FLAGS.define(
    "trace_requests", bool, False,
    "request-scoped distributed tracing for the serving tier "
    "(monitor/tracing.py): every serving request gets a trace id "
    "(accepting/emitting a W3C traceparent header) and a span tree "
    "decomposing its latency — queue wait, batch form, pad-to-bucket "
    "overhead, executor compile/run, de-batch, and per-token decode "
    "iterations for generation; traces land in the bounded trace store "
    "(/v1/traces endpoints), the flight ring, and the unified chrome "
    "timeline.  Off = zero cost: no trace objects, no registry entries, "
    "no flight events on the request path")
FLAGS.define(
    "trace_store", int, 256,
    "capacity of the in-memory finished-trace store behind /v1/traces "
    "(bounded memory; oldest traces evicted first)")
FLAGS.define(
    "serving_slo_ms", str, "",
    "per-model serving latency objective in milliseconds, e.g. '50' "
    "(every model) or 'demo=50,gendemo=500' (per model; a bare number "
    "entry is the default for unlisted models).  When set, every "
    "finished/shed request counts as a good or bad SLO event "
    "(serving.<model>.slo_good_total / slo_bad_total) and multi-window "
    "burn-rate gauges (slo_burn_rate_5m/30m/1h — observed bad fraction "
    "over the window divided by the 1-FLAGS_serving_slo_target error "
    "budget; 1.0 = burning exactly at budget) refresh on every /metrics "
    "scrape.  Empty disables the SLO engine")
FLAGS.define(
    "serving_slo_target", float, 0.999,
    "availability objective behind the burn-rate gauges: the error "
    "budget is 1 - this fraction of requests allowed to miss "
    "FLAGS_serving_slo_ms")
FLAGS.define(
    "router_port", int, 0,
    "TCP port for the serving router front-end (serving/router.py): "
    "proxies /v1/models/*:predict and :generate across the replica "
    "fleet; 0 = pick a free port")
FLAGS.define(
    "router_probe_interval_s", float, 0.5,
    "router health-probe period: every replica's /health is polled this "
    "often to drive the in-rotation / draining-out / evicted state "
    "machine (serving/router.py)")
FLAGS.define(
    "router_probe_timeout_s", float, 2.0,
    "per-probe HTTP timeout; a probe that times out counts as a failure "
    "toward FLAGS_router_evict_failures")
FLAGS.define(
    "router_evict_failures", int, 3,
    "consecutive failed health probes (connect error, timeout, or "
    "scheduler_dead status) before a replica is EVICTED from rotation; "
    "a single passing 'ready' probe re-admits it")
FLAGS.define(
    "router_retries", int, 2,
    "max failover attempts per proxied request AFTER the first (each on "
    "a different replica where possible), budgeted against the "
    "request's own timeout_s deadline — the router never sleeps or "
    "retries past it.  Predict retries on connect error/5xx/429; "
    "generation fails over only before the first upstream byte")
FLAGS.define(
    "router_hedge_ms", float, 0.0,
    "tail-latency hedging: if a proxied predict gets no response within "
    "this many ms, a second attempt is fired at a DIFFERENT replica and "
    "the first response wins (loser's connection is dropped; "
    "router.hedges_total / hedges_won_total).  0 disables; generation "
    "is never hedged")
FLAGS.define(
    "router_slo_weight", float, 0.0,
    "SLO-aware load balancing: a replica's effective load is "
    "inflight + this weight x its serving slo_burn_rate_5m gauge "
    "(scraped with each health probe), steering new requests away from "
    "replicas burning error budget; 0 = pure least-inflight")
FLAGS.define(
    "record_lowered_ops", bool, False,
    "test/debug flag: the executor trace records every lowered op type "
    "into the flight recorder (monitor/flight.py lowered_op_types) — the "
    "op-contract gate asserts registry coverage against this set")
FLAGS.define(
    "watchdog", bool, False,
    "arm the training anomaly watchdog (monitor/watchdog.py) in "
    "StepMonitor-instrumented loops: NaN/Inf loss, loss-spike z-score, "
    "throughput collapse, and a hang monitor on a daemon thread")
FLAGS.define(
    "watchdog_action", str, "dump",
    "what a watchdog trip does: 'log' (warn only), 'dump' (warn + write "
    "a flight record to FLAGS.flight_dir), or 'raise' (dump, then raise "
    "WatchdogError / interrupt the main thread — for tests)")
FLAGS.define(
    "checkpoint_async", bool, False,
    "CheckpointManager default save mode: snapshot device->host "
    "synchronously, then write/fsync/rename on a background thread so "
    "the step loop never blocks on disk (io.py checkpoint v2)")
FLAGS.define(
    "checkpoint_dir", str, "",
    "bench.py: arm interval checkpointing + emergency-save for every "
    "workload under this directory (one subdir per workload); empty "
    "disables")
FLAGS.define(
    "checkpoint_interval", int, 50,
    "bench.py checkpoint interval (in run_steps calls) when "
    "FLAGS.checkpoint_dir is set")
FLAGS.define(
    "chaos", bool, False,
    "master switch for deterministic fault injection "
    "(paddle_tpu/testing/chaos.py); off = every chaos hook is a no-op")
FLAGS.define(
    "chaos_seed", int, 0,
    "seed for any randomized chaos schedule (kept 0/deterministic by the "
    "built-in injections; reserved for custom harnesses)")
FLAGS.define(
    "chaos_kill_at_step", int, -1,
    "SIGKILL the process when a training loop reports this completed "
    "step (chaos.on_step); -1 disables")
FLAGS.define(
    "chaos_kill_at_run", int, -1,
    "SIGKILL the process on the Nth Executor.run call (1-based, "
    "chaos.on_executor_run); -1 disables")
FLAGS.define(
    "chaos_torn_write", int, -1,
    "truncate a tensor file of the Nth checkpoint save (0-based) after "
    "its manifest is computed — a disk-level torn write the integrity "
    "check must catch; -1 disables")
FLAGS.define(
    "chaos_io_errors", int, 0,
    "the first K chaos-guarded I/O calls (checkpoint rename/open, shard "
    "open, dataset download) raise a transient OSError; 0 disables")
FLAGS.define(
    "chaos_feed_stall_s", float, 0.0,
    "sleep injected per parsed batch in data-feed workers (feed "
    "starvation); 0 disables")
FLAGS.define(
    "chaos_nan_at_step", int, -1,
    "training loops report a NaN loss at this step (watchdog fodder); "
    "-1 disables")
FLAGS.define(
    "chaos_nan_var", str, "",
    "graph-level NaN injection: at trace time the named op-output var "
    "is poisoned with NaN (testing/chaos.poison_var, applied in "
    "core/executor.trace_block) — unlike chaos_nan_at_step's host-side "
    "fake loss, the NaN is real in the compiled graph, so the numerics "
    "locate replay must find the op that wrote it; '' disables")
FLAGS.define(
    "chaos_serve_latency_s", float, 0.0,
    "sleep injected into every serving batch execution / generation "
    "decode step (chaos.maybe_serve_latency — a slow-executor "
    "simulation that pins serving capacity so the CI overload gate is "
    "box-independent); 0 disables")
FLAGS.define(
    "chaos_serve_errors", int, 0,
    "the first K serving batch executions raise a transient "
    "RuntimeError (chaos.maybe_serve_error — circuit-breaker fodder; "
    "the budget is process-global and deterministic); 0 disables")
FLAGS.define(
    "chaos_serve_flood", int, 0,
    "request-flood burst: the FIRST admitted serving request after "
    "arming additionally fires this many synthetic duplicate requests "
    "at its own model (chaos.serve_flood — deterministic queue-pressure "
    "spike); 0 disables")
FLAGS.define(
    "chaos_kill_replica_after", int, -1,
    "replica-death injection: SIGKILL this serving process right after "
    "it finishes its Nth predict/generate request (1-based, "
    "chaos.on_request_done) — armed per replica via env override, the "
    "router/supervisor failover-and-restart fodder; -1 disables")
FLAGS.define(
    "chaos_probe_flap", int, 0,
    "health-probe flapping: every Nth /health readiness evaluation "
    "(1-based count of calls, process-global) reports not-ready "
    "(chaos.probe_flap) — exercises router eviction/re-admission "
    "hysteresis; 0 disables")
FLAGS.define(
    "chaos_replica_latency_s", float, 0.0,
    "slow-replica simulation: sleep injected once per proxied serving "
    "HTTP request at the handler level (chaos.maybe_replica_latency) — "
    "unlike chaos_serve_latency_s this delays the whole request path "
    "including admission, making one replica a hedging/eviction "
    "straggler; 0 disables")
