"""Fleet router (serving/router.py, ISSUE 18): probe-driven rotation
state machine, least-inflight + SLO-weighted balancing, deadline-budgeted
retry-with-failover, hedging, traceparent passthrough — all against
scriptable stdlib fake replicas (no jax, no subprocesses) — plus the
/health per-model readiness detail, the replica chaos injectors'
zero-cost-off contract, and the router tier's own zero-cost contract
(unused => un-imported, no registry entries)."""

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddle_tpu.flags import FLAGS
from paddle_tpu.monitor import default_registry, flight
from paddle_tpu.serving.router import (
    DRAINING,
    EVICTED,
    IN_ROTATION,
    Router,
    WARMING,
    _body_timeout_s,
)
from paddle_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _fresh_state():
    FLAGS.reset()
    FLAGS.monitor = True  # flight events + router counters are asserted
    default_registry().reset()
    chaos.reset()
    flight.default_recorder().clear()
    yield
    FLAGS.reset()
    default_registry().reset()
    chaos.reset()
    flight.default_recorder().clear()


# ---------------------------------------------------------------------------
# scriptable fake replica
# ---------------------------------------------------------------------------


class _FakeHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, code, obj, extra=None):
        data = (json.dumps(obj) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        s = self.server
        if self.path == "/health":
            body = s.health
            code = 200 if body.get("status") == "ok" else 503
            self._send(code, body)
        elif self.path == "/metrics":
            text = s.metrics_text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
        elif self.path.startswith("/v1/models"):
            self._send(200, {"models": [{"name": "m", "tag": s.tag}]})
        else:
            self._send(404, {})

    def do_POST(self):
        s = self.server
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        with s.lock:
            s.requests += 1
        if s.delay_s:
            time.sleep(s.delay_s)
        with s.lock:
            if s.fail_statuses:
                code = s.fail_statuses.pop(0)
                self._send(code, {"error": "scripted", "tag": s.tag})
                return
        tp = self.headers.get("traceparent")
        self._send(200, {"tag": s.tag, "traceparent_seen": tp},
                   extra={"traceparent": tp} if tp else None)


class FakeReplica:
    """One scriptable backend: set .health, queue .fail_statuses, set
    .delay_s; .requests counts POSTs seen."""

    def __init__(self, tag):
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeHandler)
        self.srv.daemon_threads = True
        self.srv.tag = tag
        self.srv.lock = threading.Lock()
        self.srv.requests = 0
        self.srv.delay_s = 0.0
        self.srv.fail_statuses = []
        self.srv.metrics_text = ""
        self.srv.health = {
            "status": "ok",
            "serving": {"ready": True,
                        "models": {"m": {"state": "ready"}}},
        }
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    @property
    def port(self):
        return self.srv.server_address[1]

    @property
    def requests(self):
        return self.srv.requests

    def set_health(self, body):
        self.srv.health = body

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


@pytest.fixture
def fakes():
    reps = []

    def make(tag):
        r = FakeReplica(tag)
        reps.append(r)
        return r

    yield make
    for r in reps:
        r.close()


@pytest.fixture
def router():
    routers = []

    def make(*reps, start=False):
        r = Router()
        for i, rep in enumerate(reps):
            r.add_replica("127.0.0.1", rep.port, rid=f"r{i}")
        if start:
            r.start()
        routers.append(r)
        return r

    yield make
    for r in routers:
        r.stop()


def _proxy(r, kind="predict", timeout_s=5.0, headers=None):
    body = json.dumps({"timeout_s": timeout_s}).encode()
    return r.proxy(kind, f"/v1/models/m:{kind}", body,
                   dict({"Content-Type": "application/json"},
                        **(headers or {})))


# ---------------------------------------------------------------------------
# probe state machine
# ---------------------------------------------------------------------------


class TestProbeStateMachine:
    def test_ready_replica_enters_rotation_on_registration(self, fakes,
                                                           router):
        r = router(fakes("a"))
        assert r.replica_state("r0") == IN_ROTATION

    def test_warming_is_not_evicted(self, fakes, router):
        a = fakes("a")
        a.set_health({"status": "not_ready", "serving": {
            "ready": False,
            "models": {"m": {"state": "warming", "warm_buckets": 1,
                             "ladder_size": 4}}}})
        r = router(a)
        # many consecutive not-ready probes: warming never trips eviction
        for _ in range(FLAGS.router_evict_failures * 3):
            r.probe_now("r0")
        assert r.replica_state("r0") == WARMING
        # warmup finishes -> back in rotation
        a.set_health({"status": "ok", "serving": {"ready": True}})
        r.probe_now("r0")
        assert r.replica_state("r0") == IN_ROTATION

    def test_scheduler_dead_evicts_immediately(self, fakes, router):
        a = fakes("a")
        r = router(a)
        a.set_health({"status": "scheduler_dead",
                      "serving": {"ready": False,
                                  "scheduler_dead": ["m"]}})
        r.probe_now("r0")  # ONE probe, no hysteresis
        assert r.replica_state("r0") == EVICTED
        evs = flight.default_recorder().events(kind="router.evict")
        assert evs and evs[-1]["reason"] == "scheduler_dead"

    def test_draining_leaves_rotation_without_eviction(self, fakes,
                                                       router):
        a = fakes("a")
        r = router(a)
        a.set_health({"status": "draining",
                      "serving": {"ready": False, "draining": True,
                                  "draining_reason": "sigterm"}})
        for _ in range(FLAGS.router_evict_failures * 2):
            r.probe_now("r0")
        assert r.replica_state("r0") == DRAINING
        assert not flight.default_recorder().events(kind="router.evict")

    def test_connect_failures_evict_then_recovery_readmits(self, fakes,
                                                           router):
        a = fakes("a")
        r = router(a)
        port = a.port
        a.close()  # dead socket
        for _ in range(FLAGS.router_evict_failures):
            r.probe_now("r0")
        assert r.replica_state("r0") == EVICTED
        # a new listener on the same port: single passing probe re-admits
        b = FakeReplica("a2")
        try:
            r.update_replica("r0", "127.0.0.1", b.port)
            assert r.replica_state("r0") == IN_ROTATION
            evs = flight.default_recorder().events(kind="router.readmit")
            assert evs, "re-admission not flight-recorded"
        finally:
            b.close()
        assert port  # silence lint: port captured for debuggability

    def test_probe_publishes_per_replica_gauges(self, fakes, router):
        FLAGS.monitor = True
        r = router(fakes("a"))
        r.probe_now("r0")
        reg = default_registry()
        assert reg.get("router.replica.r0.state").value == 0
        assert reg.get("router.replica.r0.inflight") is not None
        assert reg.get("router.replica.r0.probe_latency_ms").value >= 0


# ---------------------------------------------------------------------------
# balancing
# ---------------------------------------------------------------------------


class TestBalancing:
    def test_least_inflight_wins(self, fakes, router):
        r = router(fakes("a"), fakes("b"))
        with r._lock:
            r._replicas["r0"].inflight = 3
        assert r.pick().rid == "r1"

    def test_exclusion_prefers_fresh_replica(self, fakes, router):
        r = router(fakes("a"), fakes("b"))
        assert r.pick(exclude={"r0"}).rid == "r1"
        # all excluded: falls back to a tried one rather than None
        assert r.pick(exclude={"r0", "r1"}) is not None

    def test_slo_weight_steers_away_from_burning_replica(self, fakes,
                                                         router):
        FLAGS.router_slo_weight = 2.0
        r = router(fakes("a"), fakes("b"))
        with r._lock:
            r._replicas["r0"].slo_burn = 5.0  # r0 burning error budget
        assert r.pick().rid == "r1"

    def test_slo_burn_scraped_from_metrics(self, fakes, router):
        FLAGS.router_slo_weight = 1.0
        a = fakes("a")
        a.srv.metrics_text = (
            "# TYPE serving_m_slo_burn_rate_5m gauge\n"
            "serving_m_slo_burn_rate_5m 3.5\n"
            "serving_m_slo_burn_rate_30m 1.0\n")
        r = router(a)
        r.probe_now("r0")
        with r._lock:
            assert r._replicas["r0"].slo_burn == 3.5


# ---------------------------------------------------------------------------
# failover / retry policy
# ---------------------------------------------------------------------------


class TestFailover:
    def test_predict_5xx_fails_over_and_counts(self, fakes, router):
        FLAGS.monitor = True
        a, b = fakes("a"), fakes("b")
        a.srv.fail_statuses = [500] * 5
        r = router(a, b)
        status, _h, body = _proxy(r)
        assert status == 200
        assert json.loads(body)["tag"] == "b"
        assert default_registry().get(
            "router.failover_total").value >= 1
        evs = flight.default_recorder().events(kind="router.failover")
        assert evs and evs[-1]["status"] == 500

    def test_predict_429_fails_over(self, fakes, router):
        a, b = fakes("a"), fakes("b")
        a.srv.fail_statuses = [429] * 5
        r = router(a, b)
        status, _h, body = _proxy(r)
        assert status == 200
        assert json.loads(body)["tag"] == "b"

    def test_connect_error_fails_over(self, fakes, router):
        a, b = fakes("a"), fakes("b")
        r = router(a, b)
        a.close()
        oks = sum(_proxy(r)[0] == 200 for _ in range(4))
        assert oks == 4  # every request lands on the live replica

    def test_exhausted_retries_return_last_error(self, fakes, router):
        FLAGS.router_retries = 1
        a = fakes("a")
        a.srv.fail_statuses = [500] * 10
        r = router(a)
        status, _h, body = _proxy(r)
        assert status == 500
        assert json.loads(body)["error"] == "scripted"

    def test_generate_not_retried_on_500(self, fakes, router):
        a, b = fakes("a"), fakes("b")
        a.srv.fail_statuses = [500]
        b.srv.fail_statuses = [500]
        r = router(a, b)
        status, _h, _b = _proxy(r, kind="generate")
        assert status == 500  # tokens may have flowed: no blind retry
        assert a.requests + b.requests == 1

    def test_generate_retries_preadmission_rejections(self, fakes,
                                                      router):
        a, b = fakes("a"), fakes("b")
        a.srv.fail_statuses = [429, 503]
        r = router(a, b)
        for _ in range(2):  # one 429 failover, then one 503 failover
            status, _h, _b = _proxy(r, kind="generate")
            assert status == 200
        assert a.requests == 2 and b.requests == 2

    def test_deadline_bounds_total_retry_time(self, fakes, router):
        """The satellite regression at the router: a 100 ms-deadline
        request against always-500 replicas resolves well inside ~2x
        its deadline — never a full unbudgeted backoff ladder."""
        FLAGS.router_retries = 10
        a = fakes("a")
        a.srv.fail_statuses = [500] * 50
        r = router(a)
        t0 = time.monotonic()
        status, _h, _b = _proxy(r, timeout_s=0.1)
        dt = time.monotonic() - t0
        # the last word may be the scripted 500, a deadline 504, or a 502
        # when the shrinking per-attempt timeout cut the socket first
        assert status in (500, 502, 504)
        assert dt < 1.0, f"retried {dt:.2f}s past a 100ms deadline"

    def test_no_replicas_is_a_named_503(self, fakes, router):
        r = router()
        status, _h, body = _proxy(r)
        assert status == 503
        assert json.loads(body)["reason"] == "no_replicas"

    def test_draining_replica_takes_no_new_requests(self, fakes, router):
        a, b = fakes("a"), fakes("b")
        r = router(a, b)
        r.set_draining("r0")
        for _ in range(4):
            assert _proxy(r)[0] == 200
        assert a.requests == 0 and b.requests == 4


# ---------------------------------------------------------------------------
# hedging
# ---------------------------------------------------------------------------


class TestHedging:
    def test_hedge_wins_against_straggler(self, fakes, router):
        FLAGS.monitor = True
        FLAGS.router_hedge_ms = 30.0
        a, b = fakes("a"), fakes("b")
        a.srv.delay_s = 1.5  # the straggler (picked first: rid order)
        r = router(a, b)
        t0 = time.monotonic()
        status, _h, body = _proxy(r)
        dt = time.monotonic() - t0
        assert status == 200
        assert json.loads(body)["tag"] == "b"  # the hedge's response won
        assert dt < 1.0  # did not wait out the straggler
        reg = default_registry()
        assert reg.get("router.hedges_total").value == 1
        assert reg.get("router.hedges_won_total").value == 1
        assert reg.get("router.replica.r1.hedges_won").value == 1

    def test_fast_primary_never_hedges(self, fakes, router):
        FLAGS.monitor = True
        FLAGS.router_hedge_ms = 200.0
        a, b = fakes("a"), fakes("b")
        r = router(a, b)
        assert _proxy(r)[0] == 200
        assert default_registry().get("router.hedges_total") is None
        assert b.requests == 0

    def test_generate_is_never_hedged(self, fakes, router):
        FLAGS.monitor = True
        FLAGS.router_hedge_ms = 10.0
        a, b = fakes("a"), fakes("b")
        a.srv.delay_s = 0.3
        r = router(a, b)
        assert _proxy(r, kind="generate")[0] == 200
        assert default_registry().get("router.hedges_total") is None


# ---------------------------------------------------------------------------
# HTTP front-end: proxying, traceparent, introspection
# ---------------------------------------------------------------------------


def _post(url, body, headers=None, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.getheaders()), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class TestHTTPFrontend:
    def test_end_to_end_proxy_and_traceparent(self, fakes, router):
        r = router(fakes("a"), start=True)
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        status, headers, body = _post(
            f"{r.url}/v1/models/m:predict",
            {"timeout_s": 5}, headers={"traceparent": tp})
        assert status == 200
        payload = json.loads(body)
        assert payload["traceparent_seen"] == tp  # router -> replica
        assert headers.get("traceparent") == tp   # replica -> client

    def test_replicas_endpoint_reports_fleet(self, fakes, router):
        r = router(fakes("a"), fakes("b"), start=True)
        with urllib.request.urlopen(f"{r.url}/v1/replicas",
                                    timeout=5) as resp:
            reps = json.loads(resp.read())["replicas"]
        assert [x["rid"] for x in reps] == ["r0", "r1"]
        assert all(x["state"] == IN_ROTATION for x in reps)
        assert all("probe_latency_ms" in x for x in reps)

    def test_models_get_proxies_to_a_replica(self, fakes, router):
        r = router(fakes("a"), start=True)
        with urllib.request.urlopen(f"{r.url}/v1/models",
                                    timeout=5) as resp:
            models = json.loads(resp.read())["models"]
        assert models[0]["name"] == "m"

    def test_unknown_post_is_404(self, fakes, router):
        r = router(fakes("a"), start=True)
        status, _h, _b = _post(f"{r.url}/v1/oops", {})
        assert status == 404

    def test_body_timeout_parse(self):
        assert _body_timeout_s(
            json.dumps({"timeout_s": 2.5}).encode(),
            "application/json") == 2.5
        assert _body_timeout_s(b"\x93NUMPY", "application/x-npz") == 30.0
        assert _body_timeout_s(b"not json", "application/json") == 30.0
        assert _body_timeout_s(b"", None) == 30.0


# ---------------------------------------------------------------------------
# replica chaos injectors (satellite): zero-cost off + armed behavior
# ---------------------------------------------------------------------------


class TestReplicaChaos:
    def test_zero_cost_off(self):
        """The standard chaos contract: with FLAGS_chaos off the hooks
        are no-ops — no state, no counters — whatever the sub-flags
        say."""
        FLAGS.chaos_kill_replica_after = 1
        FLAGS.chaos_probe_flap = 1
        FLAGS.chaos_replica_latency_s = 9.0
        t0 = time.perf_counter()
        chaos.on_request_done()
        assert chaos.probe_flap(True) is True
        chaos.maybe_replica_latency()
        assert time.perf_counter() - t0 < 1.0  # no 9 s sleep
        assert chaos.injected_counts() == {}

    def test_kill_replica_after_counts_to_n(self, monkeypatch):
        killed = []
        monkeypatch.setattr(chaos, "kill",
                            lambda reason: killed.append(reason))
        FLAGS.chaos = True
        FLAGS.chaos_kill_replica_after = 3
        chaos.on_request_done()
        chaos.on_request_done()
        assert killed == []  # not yet
        chaos.on_request_done()
        assert len(killed) == 1 and "3" in killed[0]
        assert chaos.injected_counts()["kill_replica"] == 1

    def test_probe_flap_every_nth(self):
        FLAGS.chaos = True
        FLAGS.chaos_probe_flap = 3
        verdicts = [chaos.probe_flap(True) for _ in range(6)]
        assert verdicts == [True, True, False, True, True, False]
        assert chaos.injected_counts()["probe_flap"] == 2

    def test_replica_latency_sleeps(self):
        FLAGS.chaos = True
        FLAGS.chaos_replica_latency_s = 0.05
        t0 = time.perf_counter()
        chaos.maybe_replica_latency()
        assert time.perf_counter() - t0 >= 0.05
        assert chaos.injected_counts()["replica_latency"] == 1


# ---------------------------------------------------------------------------
# zero-cost contract: the router tier unused is the router tier absent
# ---------------------------------------------------------------------------


class TestZeroCost:
    def test_router_not_imported_by_serving_package(self):
        """`import paddle_tpu.serving` (the single-replica path) must not
        pull the router/fleet modules — they are lazy __getattr__
        exports."""
        import importlib

        import paddle_tpu.serving  # noqa: F401 — the import IS the test

        importlib.import_module("paddle_tpu.serving")
        # this test file imported the router itself; the contract is
        # about the package import graph, checked on a fresh interpreter
        # in test_fleet's subprocess — here assert the lazy export works
        # without eagerly binding
        import paddle_tpu.serving as s

        assert "Router" not in s.__dict__
        assert s.Router is Router
        assert s.ReplicaSupervisor is not None

    def test_no_router_metrics_without_router_traffic(self):
        FLAGS.monitor = True
        reg = default_registry()
        assert not [s for s in reg.snapshot()
                    if s["metric"].startswith("router.")]
