"""CompiledProgram: compile-time strategy wrapper (reference:
python/paddle/fluid/compiler.py:33 CompiledProgram,
with_data_parallel:72 wrapping ParallelExecutor).

TPU-first: `with_data_parallel` does NOT build per-device SSA graphs with
collective op-handles (details/multi_devices_graph_pass.cc).  It shards the
batch over a `jax.sharding.Mesh` with NamedSharding and jits the same traced
step function; XLA SPMD inserts the all-reduces over ICI.  BuildStrategy /
ExecutionStrategy are kept as typed knobs for parity (build_strategy.h:34,
execution_strategy.h:22) — most of their fields are no-ops under XLA and are
documented as such.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .core import framework as fw
from .core.executor import prng_key as _prng_key
from .core import executor as exec_mod
from .core import registry


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class BuildStrategy:
    """Parity container (details/build_strategy.h:34).  Under XLA SPMD most
    knobs are subsumed by the compiler; kept so user code ports cleanly."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_elewise_add_act_ops = False  # XLA fuses automatically
        self.memory_optimize = True  # XLA buffer assignment
        self.enable_inplace = True
        self.cache_runtime_context = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0  # XLA owns scheduling
        self.num_iteration_per_drop_scope = 1
        self.allow_op_delay = False
        self.use_experimental_executor = False


class CompiledProgram:
    def __init__(self, program: fw.Program):
        self._program = program
        self._data_parallel = False
        self._loss_name = None
        self._build_strategy = None
        self._exec_strategy = None
        self._share_vars_from = None
        self._places = None
        self._mesh = None
        self._cache: Dict[Any, Any] = {}
        self._run_counter = 0

    # -- public API (parity: compiler.py:72) ------------------------------
    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from: Optional["CompiledProgram"] = None,
        places: Optional[Sequence] = None,
    ) -> "CompiledProgram":
        self._data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    # -- execution ---------------------------------------------------------
    def _get_mesh(self):
        import jax
        from jax.sharding import Mesh

        if self._mesh is not None:
            return self._mesh
        devices = np.array(jax.devices())
        if self._places is not None and len(self._places) > 0 and not isinstance(
            self._places[0], exec_mod.Place
        ):
            devices = np.array(list(self._places))
        self._mesh = Mesh(devices, axis_names=("data",))
        return self._mesh

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        """Called by Executor.run when handed a CompiledProgram."""
        if not self._data_parallel:
            return executor.run(
                self._program, feed, fetch_list, scope, return_numpy,
            )
        return self._run_data_parallel(
            executor, feed or {}, fetch_list or [], scope or exec_mod.global_scope(),
            return_numpy,
        )

    def _run_data_parallel(self, executor, feed, fetch_list, scope, return_numpy):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        program = self._program
        mesh = self._get_mesh()
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v for v in fetch_list
        ]
        feed_names = sorted(feed)
        block = program.global_block()

        key = (
            program.fingerprint(),
            bool(getattr(program, "_amp_bf16", False)),
            bool(getattr(program, "_is_test", False)),
            tuple(feed_names),
            tuple(
                (tuple(np.asarray(feed[n]).shape), str(np.asarray(feed[n]).dtype))
                for n in feed_names
            ),
            tuple(fetch_names),
        )
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile_dp(program, feed, feed_names, fetch_names, scope, mesh)
            self._cache[key] = entry
        (jitted, rw_state, ro_state, state_writes, needs_key, data_sharding,
         repl_sharding) = entry

        # place feeds: batch-sharded over mesh; state: replicated
        feed_vals = [
            jax.device_put(np.asarray(feed[n]), data_sharding) for n in feed_names
        ]
        rw_vals = [self._ensure_repl(scope.find_var(n), repl_sharding) for n in rw_state]
        ro_vals = [self._ensure_repl(scope.find_var(n), repl_sharding) for n in ro_state]

        self._run_counter += 1
        if needs_key:
            k = jax.random.fold_in(
                _prng_key(program.random_seed or 0), self._run_counter
            )
            fetches, new_state = jitted(feed_vals, rw_vals, ro_vals, k)
        else:
            fetches, new_state = jitted(feed_vals, rw_vals, ro_vals)
        for n, v in zip(state_writes, new_state):
            scope.set_var(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    def _ensure_repl(self, val, sharding):
        import jax

        if val is None:
            return None
        if hasattr(val, "sharding") and val.sharding == sharding:
            return val
        return jax.device_put(val, sharding)

    def _compile_dp(self, program, feed, feed_names, fetch_names, scope, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        block = program.global_block()
        state_reads, state_writes = exec_mod.analyze_block_io(
            block, feed_names, scope
        )
        write_set = set(state_writes)
        rw_state = [n for n in state_reads if n in write_set]
        ro_state = [n for n in state_reads if n not in write_set]

        data_sharding = NamedSharding(mesh, P("data"))
        repl_sharding = NamedSharding(mesh, P())

        probe_random = exec_mod.program_uses_random(block)

        n_dev = mesh.devices.size
        loss_name = self._loss_name

        def run_fn(feed_vals, rw_vals, ro_vals, key=None):
            if key is None:
                key = _prng_key(program.random_seed or 0)
            tctx = exec_mod.TraceContext(
                program, key, is_test=getattr(program, "_is_test", False),
                mesh=mesh,
            )
            env = {}
            for n, v in zip(feed_names, feed_vals):
                env[n] = v
            for n, v in zip(rw_state, rw_vals):
                env[n] = v
            for n, v in zip(ro_state, ro_vals):
                env[n] = v
            exec_mod.trace_block(block, env, tctx)
            fetches = [env[n] for n in fetch_names]
            new_state = [env.get(n) for n in state_writes]
            return fetches, new_state

        in_shardings = (
            [data_sharding] * len(feed_names),
            [repl_sharding] * len(rw_state),
            [repl_sharding] * len(ro_state),
        )
        out_shardings = (
            [None] * len(fetch_names),
            [repl_sharding] * len(state_writes),
        )
        if probe_random:
            jitted = jax.jit(
                run_fn,
                donate_argnums=(1,),
                in_shardings=in_shardings + (None,),
                out_shardings=out_shardings,
            )
        else:
            jitted = jax.jit(
                lambda f, rw, ro: run_fn(f, rw, ro),
                donate_argnums=(1,),
                in_shardings=in_shardings,
                out_shardings=out_shardings,
            )
        return (
            jitted, rw_state, ro_state, state_writes, probe_random,
            data_sharding, repl_sharding,
        )
