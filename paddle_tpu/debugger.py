"""Program visualization + structured dumps (reference:
python/paddle/fluid/debugger.py draw_block_graphviz, net_drawer.py,
graphviz.py — the reference shells out to graphviz; here the DOT source is
the artifact (render anywhere), plus a human-readable program printer).
"""

from __future__ import annotations

from typing import Optional, Set

from .core import framework as fw


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def draw_block_graphviz(block: fw.Block, highlights: Optional[Set[str]] = None,
                        path: Optional[str] = None) -> str:
    """Emit a graphviz DOT description of the block's op/var dataflow
    (reference debugger.py:draw_block_graphviz).  Ops are boxes, vars are
    ellipses (parameters shaded); returns the DOT source and optionally
    writes it to `path`."""
    highlights = highlights or set()
    params = {p.name for p in block.program.all_parameters()}
    lines = [
        "digraph G {",
        "  rankdir=TB;",
        '  node [fontsize=10, fontname="monospace"];',
    ]
    var_nodes: Set[str] = set()

    def var_node(name: str) -> str:
        # the escaped name IS the (deterministic, collision-free) node id
        nid = f"var_{_esc(name)}"
        if name not in var_nodes:
            var_nodes.add(name)
            style = 'style=filled, fillcolor="lightblue"' \
                if name in params else ""
            if name in highlights:
                style = 'style=filled, fillcolor="orange"'
            v = block._find_var_recursive(name)
            shape = getattr(v, "shape", None)
            label = _esc(name if shape is None else f"{name}\\n{shape}")
            lines.append(
                f'  "{nid}" [label="{label}", shape=ellipse, {style}];')
        return nid

    for i, op in enumerate(block.ops):
        oid = f"op_{i}"
        lines.append(
            f'  "{oid}" [label="{_esc(op.type)}", shape=box, '
            'style=filled, fillcolor="lightgrey"];')
        for n in op.input_arg_names():
            if n:
                lines.append(f'  "{var_node(n)}" -> "{oid}";')
        for n in op.output_arg_names():
            if n:
                lines.append(f'  "{oid}" -> "{var_node(n)}";')
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def pprint_program(program: fw.Program) -> str:
    """Human-readable program dump: one line per op with inputs -> outputs
    and non-default attrs (reference debugger.pprint_program_codes)."""
    out = []
    for bi, block in enumerate(program.blocks):
        out.append(f"block {bi} (parent {block.parent_idx}):")
        for op in block.ops:
            ins = ", ".join(
                f"{slot}={names}" for slot, names in op.inputs.items()
                if names)
            outs = ", ".join(
                f"{slot}={names}" for slot, names in op.outputs.items()
                if names)
            attrs = {
                k: v for k, v in op.attrs.items()
                if k not in ("op_role", "sub_block")
                and not hasattr(v, "ops")
            }
            a = f"  attrs={attrs}" if attrs else ""
            out.append(f"  {op.type}({ins}) -> {outs}{a}")
    return "\n".join(out)
