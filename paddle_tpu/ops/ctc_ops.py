"""CTC (Connectionist Temporal Classification) op family.

Capability parity with the reference's warp-ctc integration
(paddle/fluid/operators/warpctc_op.cc — external Baidu warp-ctc library) and
ctc_align (paddle/fluid/operators/ctc_align_op.cc), rebuilt TPU-first:

  * The loss is a log-space alpha (forward-variable) recursion expressed as ONE
    `lax.scan` over time, vectorized over the batch and the extended-label axis
    — static shapes, no host library, fully differentiable, so the backward
    pass comes from `jax.vjp` via the registry's default grad maker instead of
    warp-ctc's hand-written beta recursion.
  * Ragged sequences use the repo-wide padded+Length idiom (SURVEY §5.7): the
    reference's LoD inputs ([Lp, C] logits / [Lg, 1] labels) become
    [B, T, C] logits + Logits_length and [B, L] labels + Label_length.
  * ctc_align's compaction (merge repeats, drop blanks) is a masked
    cumsum+scatter — a static-shape TPU formulation of the reference's
    per-sequence CPU loop (ctc_align_op.h:41-77).
"""

from __future__ import annotations

from ..core.registry import register


def _ctc_loss_padded(log_probs, labels, logit_lens, label_lens, blank):
    """log_probs: [B, T, C] (log-softmaxed), labels: [B, L] int32,
    logit_lens/label_lens: [B] int32. Returns per-example loss [B]."""
    import jax
    import jax.numpy as jnp

    B, T, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1

    # Extended label sequence: blank, l1, blank, l2, ..., blank  -> [B, S]
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))

    neg_inf = jnp.asarray(-1e30, dtype=log_probs.dtype)
    s_idx = jnp.arange(S)[None, :]                       # [1, S]
    valid_s = s_idx < (2 * label_lens[:, None] + 1)      # [B, S]

    # Transition structure: alpha[s] can come from s, s-1, and s-2 when
    # ext[s] != blank and ext[s] != ext[s-2] (the classic CTC skip rule).
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != blank) & (ext != ext_m2)          # [B, S]

    def emit(t):
        # log P(ext[s] at time t) gathered per batch: [B, S]
        return jnp.take_along_axis(log_probs[:, t, :], ext, axis=1)

    # alpha_0: only s=0 (blank) and s=1 (first label) are reachable.
    alpha0 = jnp.where(s_idx < 2, emit(0), neg_inf)
    alpha0 = jnp.where(valid_s, alpha0, neg_inf)

    def shift1(a):
        return jnp.pad(a, ((0, 0), (1, 0)), constant_values=neg_inf)[:, :S]

    def step(alpha, t):
        a0 = alpha
        a1 = shift1(alpha)
        a2 = jnp.where(can_skip, shift1(shift1(alpha)), neg_inf)
        stacked = jnp.stack([a0, a1, a2], axis=0)        # [3, B, S]
        merged = jax.scipy.special.logsumexp(stacked, axis=0)
        new = merged + emit(t)
        new = jnp.where(valid_s, new, neg_inf)
        # Frozen past each sequence's end so the final read sees alpha at len.
        new = jnp.where((t < logit_lens)[:, None], new, alpha)
        return new, None

    alpha_T, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    # Loss = -logsumexp(alpha[2*Llen], alpha[2*Llen - 1])
    last = 2 * label_lens                                # [B] (blank slot)
    a_last = jnp.take_along_axis(alpha_T, last[:, None], axis=1)[:, 0]
    prev = jnp.maximum(last - 1, 0)
    a_prev = jnp.take_along_axis(alpha_T, prev[:, None], axis=1)[:, 0]
    a_prev = jnp.where(label_lens > 0, a_prev, neg_inf)
    total = jax.scipy.special.logsumexp(jnp.stack([a_last, a_prev]), axis=0)
    return -total


@register("warpctc")
def lower_warpctc(ctx, ins):
    """CTC loss with integrated softmax (reference warpctc_op.cc:1; layer
    nn.py:4866). Logits: [B, T, C] raw scores; Label: [B, L] int.
    Optional Logits_length / Label_length: [B] (default: full)."""
    import jax.numpy as jnp

    logits = ins["Logits"][0]
    labels = ins["Label"][0]
    if labels.ndim == 3:  # tolerate [B, L, 1]
        labels = labels[..., 0]
    B, T, C = logits.shape
    L = labels.shape[1]
    llen = ins.get("Logits_length", [None])[0]
    tlen = ins.get("Label_length", [None])[0]
    llen = (jnp.full((B,), T, jnp.int32) if llen is None
            else llen.reshape(-1).astype(jnp.int32))
    tlen = (jnp.full((B,), L, jnp.int32) if tlen is None
            else tlen.reshape(-1).astype(jnp.int32))
    blank = ctx.attr("blank", 0)
    norm_by_times = ctx.attr("norm_by_times", False)

    logp = logits.astype(jnp.float32)
    logp = logp - jnp.max(logp, axis=-1, keepdims=True)
    logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=-1, keepdims=True))
    loss = _ctc_loss_padded(logp, labels, llen, tlen, blank)
    if norm_by_times:
        loss = loss / jnp.maximum(llen.astype(loss.dtype), 1.0)
    return {"Loss": [loss.reshape(B, 1)]}


def _align_rows(tokens, lens, blank, pad_value):
    """tokens: [B, T] int; merge adjacent repeats, drop blanks, left-compact.
    Returns (aligned [B, T], out_lens [B])."""
    import jax.numpy as jnp

    B, T = tokens.shape
    prev = jnp.pad(tokens, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    in_range = jnp.arange(T)[None, :] < lens[:, None]
    keep = (tokens != blank) & (tokens != prev) & in_range
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1   # target slot
    # route dropped tokens to a scratch column T, then slice it off
    pos = jnp.where(keep, pos, T)
    out = jnp.full((B, T + 1), pad_value, dtype=tokens.dtype)
    b_idx = jnp.arange(B)[:, None].repeat(T, axis=1)
    out = out.at[b_idx.reshape(-1), pos.reshape(-1)].set(tokens.reshape(-1))
    out_lens = jnp.sum(keep.astype(jnp.int32), axis=1)
    return out[:, :T], out_lens


@register("ctc_align", no_grad=True)
def lower_ctc_align(ctx, ins):
    """Merge repeated tokens then remove blanks (reference ctc_align_op.cc:1).
    Input: [B, T] int token ids (+ optional Length). Output: padded [B, T]
    (padding_value attr) + OutLength [B]."""
    import jax.numpy as jnp

    x = ins["Input"][0]
    if x.ndim == 3:
        x = x[..., 0]
    B, T = x.shape
    lens = ins.get("Length", [None])[0]
    lens = (jnp.full((B,), T, jnp.int32) if lens is None
            else lens.reshape(-1).astype(jnp.int32))
    blank = ctx.attr("blank", 0)
    pad_value = ctx.attr("padding_value", 0)
    out, out_lens = _align_rows(x.astype(jnp.int32), lens, blank, pad_value)
    return {"Output": [out], "OutLength": [out_lens]}


@register("ctc_greedy_decoder", no_grad=True)
def lower_ctc_greedy_decoder(ctx, ins):
    """argmax over classes per step, then CTC collapse (reference layer
    nn.py:4783: Step 1 argmax, Step 2 merge+deblank)."""
    import jax.numpy as jnp

    probs = ins["Input"][0]                              # [B, T, C]
    B, T, _ = probs.shape
    lens = ins.get("Length", [None])[0]
    lens = (jnp.full((B,), T, jnp.int32) if lens is None
            else lens.reshape(-1).astype(jnp.int32))
    blank = ctx.attr("blank", 0)
    pad_value = ctx.attr("padding_value", 0)
    tokens = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    out, out_lens = _align_rows(tokens, lens, blank, pad_value)
    return {"Output": [out], "OutLength": [out_lens]}
