"""Program-level autodiff: append_backward.

Capability parity with the reference (python/paddle/fluid/backward.py:394
`append_backward`, :135 `_addup_repetitive_outputs_`, :204 no-grad pruning),
TPU-first: grad ops default to vjp-of-forward lowerings (registry.py), so the
generated backward program is both introspectable IR *and* exactly XLA's
gradient when compiled.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import framework as fw
from . import registry


def _forward_slice(block: fw.Block, loss_name: str) -> List[int]:
    """Indices of ops that (transitively) contribute to loss, in order."""
    needed = {loss_name}
    keep = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if any(o in needed for o in op.output_arg_names()):
            keep.append(i)
            needed.update(n for n in op.input_arg_names() if n)
    return list(reversed(keep))


def _collect_no_grad(
    block: fw.Block, extra: Optional[Set[str]], want_grads: Optional[Set[str]] = None
) -> Set[str]:
    """want_grads: vars that must receive grads even if stop_gradient/is_data
    (calc_gradient asks for grads of arbitrary vars, incl. data)."""
    want = want_grads or set()
    no_grad = set(extra or ()) - want
    for v in block.vars.values():
        # data vars default stop_gradient=True (layers/tensor.py data());
        # explicitly setting stop_gradient=False on one requests its grad
        # (e.g. host-offloaded embedding rows, parallel/embedding.py)
        if v.stop_gradient and v.name not in want:
            no_grad.add(v.name)
    for op in block.ops:
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.no_grad:
            no_grad.update(n for n in op.output_arg_names() if n not in want)
    return no_grad


def append_backward(
    loss: fw.Variable,
    parameter_list: Optional[Sequence[str]] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    _want_grads: Optional[Set[str]] = None,
) -> List[Tuple[fw.Parameter, fw.Variable]]:
    """Append grad ops for `loss` to its program; return [(param, grad)]."""
    block = loss.block
    program = block.program
    loss_name = loss.name

    fwd_idx = _forward_slice(block, loss_name)
    no_grad = _collect_no_grad(block, no_grad_set, _want_grads)

    # var -> list of grad var names contributed by already-processed consumers
    contribs: Dict[str, List[str]] = {}
    loss_grad = fw.grad_var_name(loss_name)
    block.create_var(
        name=loss_grad, shape=loss.shape, dtype=loss.dtype, stop_gradient=True
    )
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={
            "shape": list(loss.shape or [1]),
            "value": 1.0,
            "dtype": loss.dtype,
            fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward | fw.OpRole.Loss,
        },
    )
    contribs[loss_name] = [loss_grad]

    def _ensure_var(name: str, like: Optional[str] = None):
        if not name or block.has_var_recursive(name):
            return
        proto = block._find_var_recursive(like) if like else None
        block.create_var(
            name=name,
            shape=proto.shape if proto is not None else None,
            dtype=proto.dtype if proto is not None else "float32",
            stop_gradient=True,
        )

    def _materialize_grad(var_name: str) -> Optional[str]:
        """Combine contributions for var_name into its canonical grad var."""
        lst = contribs.get(var_name)
        if not lst:
            return None
        gname = fw.grad_var_name(var_name)
        if len(lst) == 1:
            if lst[0] != gname:
                _ensure_var(gname, like=var_name)
                block.append_op(
                    "assign",
                    inputs={"X": [lst[0]]},
                    outputs={"Out": [gname]},
                    attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
                )
            contribs[var_name] = [gname]
            return gname
        # multiple consumers: sum (reference _addup_repetitive_outputs_)
        _ensure_var(gname, like=var_name)
        block.append_op(
            "sum",
            inputs={"X": list(lst)},
            outputs={"Out": [gname]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        contribs[var_name] = [gname]
        return gname

    processed_grad_names: Set[str] = {loss_grad}

    # No-grad branch pruning (reference: backward.py:204
    # _remove_no_grad_branch_): a var can carry gradient only if it chains
    # down to a trainable leaf (a param, a stop_gradient=False data var,
    # or an explicitly requested grad).  Without this, a subgraph rooted
    # ONLY at stop-gradient vars (e.g. reshapes of a label-weight feed
    # used by two consumers) still gets grad + sum ops appended — dead
    # weight XLA DCEs at trace time but the IR carries forever (surfaced
    # by the static verifier's dead-op check on transformer/BERT).
    produced_in_slice: Set[str] = set()
    for i in fwd_idx:
        produced_in_slice.update(block.ops[i].output_arg_names())
    useful: Set[str] = set(_want_grads or ())
    for i in fwd_idx:
        for n in block.ops[i].input_arg_names():
            if n and n not in produced_in_slice and n not in no_grad:
                useful.add(n)  # leaf the slice reads: param / trainable data
    for i in fwd_idx:
        op = block.ops[i]
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.no_grad:
            continue
        if any(n in useful for n in op.input_arg_names()):
            useful.update(
                n for n in op.output_arg_names() if n and n not in no_grad
            )

    for i in reversed(fwd_idx):
        op = block.ops[i]
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.no_grad:
            continue
        # any inputs needing grads?  (checked BEFORE materializing output
        # grads: an op whose inputs all sit on pruned/no-grad branches
        # must not leave orphaned assign/sum combines behind)
        wants = [
            n
            for n in op.input_arg_names()
            if n and n not in no_grad and n in useful
        ]
        if not wants:
            continue
        # materialize output grads; skip op if no output contributes
        out_grads_exist = False
        for o in op.output_arg_names():
            if _materialize_grad(o) is not None:
                out_grads_exist = True
        if not out_grads_exist:
            continue

        maker = (
            opdef.grad_maker
            if (opdef is not None and opdef.grad_maker is not None)
            else registry.default_grad_maker
        )
        # inputs on pruned branches get grad holes, like no_grad members
        hole_set = no_grad | {
            n for n in op.input_arg_names() if n and n not in useful
        }
        grad_op_descs = maker(op, hole_set)
        for desc in grad_op_descs:
            # rewrite grad outputs that already have contributions (another
            # consumer already produced grad for the same var): rename + defer
            # summation to _materialize_grad of the producing op.
            outputs = {}
            for slot, names in desc["outputs"].items():
                new_names = []
                for gname in names:
                    if not gname:
                        new_names.append("")
                        continue
                    base = (
                        gname[: -len(registry.GRAD_SUFFIX)]
                        if gname.endswith(registry.GRAD_SUFFIX)
                        else None
                    )
                    if base is not None:
                        lst = contribs.setdefault(base, [])
                        if gname in processed_grad_names or lst:
                            gname_new = f"{gname}@RENAME_{i}_{len(lst)}"
                            _ensure_var(gname_new, like=base)
                            lst.append(gname_new)
                            new_names.append(gname_new)
                            continue
                        lst.append(gname)
                    _ensure_var(gname, like=base)
                    processed_grad_names.add(gname)
                    new_names.append(gname)
                outputs[slot] = new_names
            # ensure grad input vars exist (zeros-holes handled by lowering)
            inputs = {}
            for slot, names in desc["inputs"].items():
                kept = []
                for n in names:
                    if n.endswith(registry.GRAD_SUFFIX) and not block.has_var_recursive(n):
                        # this fwd output got no grad: leave a hole
                        kept.append("")
                    else:
                        kept.append(n)
                inputs[slot] = kept
            block.append_op(desc["type"], inputs=inputs, outputs=outputs, attrs=desc["attrs"])

    # finalize grads for explicitly-requested vars (calc_gradient targets
    # have no producing op, so their contributions are combined here)
    for name in _want_grads or ():
        _materialize_grad(name)

    # finalize grads for parameters (and any leftover multi-contribs)
    params = (
        [block.program.global_block().vars[p] for p in parameter_list]
        if parameter_list
        else block.program.all_parameters()
    )
    param_grads: List[Tuple[fw.Parameter, fw.Variable]] = []
    for p in params:
        if p.name in no_grad or not getattr(p, "trainable", True):
            continue
        gname = _materialize_grad(p.name)
        if gname is None:
            continue
        gvar = block._find_var_recursive(gname)
        if gvar.shape is None:
            gvar.shape = p.shape
            gvar.dtype = p.dtype
        param_grads.append((p, gvar))
    return param_grads


def calc_gradient(
    targets, inputs, target_gradients=None, no_grad_set=None
) -> List[Optional[fw.Variable]]:
    """Gradients of `targets` w.r.t. arbitrary `inputs` (reference:
    backward.py:685 calc_gradient / gradients API)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    assert len(targets) == 1, "calc_gradient: single target supported"
    loss = targets[0]
    block = loss.block
    want = {i.name for i in inputs}
    append_backward(
        loss, no_grad_set=set(no_grad_set or ()) - want, _want_grads=want
    )
    out = []
    for iv in inputs:
        g = block._find_var_recursive(fw.grad_var_name(iv.name))
        out.append(g)
    return out
