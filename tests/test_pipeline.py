"""Pipeline-parallel training tier (parallel/pipeline/): stage
partitioner, GPipe/1F1B schedules, the host micro-batch scheduler's
BIT-parity vs Executor.run_accumulated (dropout on), the shard_map
pipe-mesh runner, the run_accumulated suffix-fetch satellite, and the
verify_program_set red/green gates."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import framework as fw
from paddle_tpu.parallel.pipeline import (
    PipelineMeshProgram,
    PipelineProgram,
    bubble_fraction,
    schedule_table,
    split_program,
    validate_schedule,
)
from paddle_tpu.parallel.pipeline.schedule import max_in_flight


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------


def _build_mlp(opt="adam", dropout=0.3):
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="tanh",
                  param_attr=pt.ParamAttr(name="w1"),
                  bias_attr=pt.ParamAttr(name="b1"))
    if dropout:
        h = layers.dropout(h, dropout_prob=dropout,
                           dropout_implementation="upscale_in_train")
    pred = layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                     bias_attr=pt.ParamAttr(name="b2"))
    loss = layers.mean(layers.square(pred - y))
    if opt == "adam":
        pt.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
    else:
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _mlp_programs(opt="adam", dropout=0.3):
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        loss = _build_mlp(opt=opt, dropout=dropout)
    return prog, start, loss


def _transformer_programs(n_layer=2, seq=16, dropout=0.1):
    from paddle_tpu.models import transformer as T

    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start), fw.guard_unique_name():
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=128, trg_vocab_size=128, max_length=32,
            n_layer=n_layer, n_head=4, d_key=16, d_value=16, d_model=64,
            d_inner_hid=128, dropout_rate=dropout, src_seq_len=seq,
            trg_seq_len=seq, use_flash=False)
        pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return prog, start, avg_cost.name, feeds


def _transformer_feed(k, mbs, seq=16):
    from paddle_tpu.models import transformer as T

    batches = [T.make_batch(mbs, seq, seq, 4, 128, 128,
                            rng=np.random.RandomState(s))
               for s in range(k)]
    return {n: np.stack([b[n] for b in batches]) for n in batches[0]}


def _init_and_snapshot(start, scope, exe, pnames, init=None):
    exe.run(start, scope=scope)
    if init is None:
        return {n: np.asarray(scope.find_var(n)).copy() for n in pnames}
    for n, v in init.items():
        scope.set_var(n, v)
    return init


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
@pytest.mark.parametrize("s,k", [(2, 2), (2, 8), (4, 4), (4, 8), (3, 5)])
def test_schedule_valid(kind, s, k):
    assert validate_schedule(s, k, kind) == []


def test_schedule_bubble_matches_analytic():
    # both schedules land on the GPipe bubble (S-1)/(K+S-1) at unit
    # fwd/bwd cost — 1F1B buys MEMORY, not bubble, in non-interleaved form
    for s, k in [(2, 4), (4, 8)]:
        expect = (s - 1) / (k + s - 1)
        assert abs(bubble_fraction(s, k, "gpipe") - expect) < 1e-9


def test_1f1b_bounds_in_flight():
    # GPipe stashes all K micro-batches on stage 0; 1F1B caps the stash
    # at the warmup depth min(K, S)
    assert max_in_flight(4, 16, "gpipe") == 16
    assert max_in_flight(4, 16, "1f1b") == 4


def test_schedule_unknown_kind_raises():
    with pytest.raises(ValueError, match="unknown schedule"):
        schedule_table(2, 4, "zigzag")


def test_schedule_per_stage_mb_order():
    # grad accumulation order contract: every stage sees micro-batches
    # 0..K-1 in order in BOTH phases, for both schedules
    for kind in ("gpipe", "1f1b"):
        seen = {}
        for tick in schedule_table(3, 6, kind):
            for s, phase, m in tick:
                seen.setdefault((s, phase), []).append(m)
        for order in seen.values():
            assert order == sorted(order)


# ---------------------------------------------------------------------------
# partitioner
# ---------------------------------------------------------------------------


def test_split_requires_optimizer():
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[4], dtype="float32")
        layers.mean(layers.fc(x, size=2))
    with pytest.raises(ValueError, match="no Optimize-role ops"):
        split_program(prog, ["x"], n_stages=2)


def test_split_rejects_control_flow():
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        loss = _build_mlp(opt="sgd", dropout=0.0)
        t = layers.fill_constant([1], "int64", 0)
        lim = layers.fill_constant([1], "int64", 2)
        cond = layers.less_than(t, lim)
        w = layers.While(cond)
        with w.block():
            layers.increment(t, value=1.0, in_place=True)
            layers.less_than(t, lim, cond=cond)
    with pytest.raises(ValueError, match="sub-block"):
        split_program(prog, ["x", "y"], n_stages=2)


def test_split_cut_vars_honored_and_checked():
    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    # the tanh activation is the natural cut
    cut = [op.output("Out")[0] for op in prog.global_block().ops
           if op.type == "tanh"]
    stages = split_program(prog, ["x", "y"], n_stages=2, cut_vars=cut)
    assert cut[0] in {n for n, _, _ in stages.stages[0].fwd_outputs}
    with pytest.raises(ValueError, match="cut var"):
        split_program(prog, ["x", "y"], n_stages=2,
                      cut_vars=["not_a_var"])
    with pytest.raises(ValueError, match="need 1 cut"):
        split_program(prog, ["x", "y"], n_stages=2, cut_vars=[])


def test_split_optimizer_stays_local():
    prog, start, loss = _mlp_programs()
    stages = split_program(prog, ["x", "y"], n_stages=2)
    for st in stages:
        owned = set(st.owned_params)
        for op in st.opt_ops():
            for p in op.inputs.get("Param", []):
                assert p in owned, (st.index, op.type, p)
    # every param is owned exactly once
    all_owned = [p for st in stages for p in st.owned_params]
    assert len(all_owned) == len(set(all_owned)) == 4


def test_split_marks_are_idempotent():
    prog, start, loss = _mlp_programs()
    split_program(prog, ["x", "y"], n_stages=2)
    fp1 = prog.fingerprint()
    split_program(prog, ["x", "y"], n_stages=2)
    assert prog.fingerprint() == fp1  # same split re-marks nothing


def test_stage_programs_verify_clean():
    """graph_lint-grade gate: every stage program passes the full
    verifier (dead-code analysis on) with zero findings, and the set
    check is clean."""
    from paddle_tpu.analysis import verify_program, verify_program_set

    prog, start, loss = _mlp_programs()
    stages = split_program(prog, ["x", "y"], n_stages=2)
    for st in stages:
        feeds = (st.feeds + [n for n, _, _ in st.fwd_inputs]
                 + [n for n, _, _ in st.bwd_inputs] + st.bwd_feeds)
        fetch = ([n for n, _, _ in st.fwd_outputs]
                 + [n for n, _, _ in st.bwd_outputs]
                 + ([loss.name] if loss.name in st.fetch_candidates
                    else []))
        findings = verify_program(st.program, feed_names=feeds,
                                  fetch_names=fetch, check_dead=True)
        assert findings == [], (st.index, [str(f) for f in findings])
    assert verify_program_set([st.io_summary() for st in stages]) == []


# ---------------------------------------------------------------------------
# verify_program_set red gates (one per check class)
# ---------------------------------------------------------------------------


def _summary(index, fwd_in=(), fwd_out=(), bwd_in=(), bwd_out=(),
             owned=(), program=None):
    return {"index": index, "fwd_inputs": list(fwd_in),
            "fwd_outputs": list(fwd_out), "bwd_inputs": list(bwd_in),
            "bwd_outputs": list(bwd_out), "owned_params": list(owned),
            "program": program}


def test_verify_set_flags_undefined_input():
    from paddle_tpu.analysis import verify_program_set

    findings = verify_program_set([
        _summary(0, fwd_out=[("a", (4, 8), "float32")]),
        _summary(1, fwd_in=[("ghost", (4, 8), "float32")]),
    ])
    assert any(f.check == "stage-undefined-input"
               and f.severity == "error" for f in findings)


def test_verify_set_flags_io_mismatch():
    from paddle_tpu.analysis import verify_program_set

    findings = verify_program_set([
        _summary(0, fwd_out=[("a", (4, 8), "float32")]),
        _summary(1, fwd_in=[("a", (4, 16), "float32")]),
    ])
    assert any(f.check == "stage-io-mismatch" for f in findings)
    findings = verify_program_set([
        _summary(0, bwd_in=[("a@GRAD", (4, 8), "float32")]),
        _summary(1, bwd_out=[("a@GRAD", (4, 8), "bfloat16")]),
    ])
    assert any(f.check == "stage-io-mismatch" for f in findings)


def test_verify_set_flags_foreign_optimizer():
    from paddle_tpu.analysis import verify_program_set

    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    stages = split_program(prog, ["x", "y"], n_stages=2)
    bad = [st.io_summary() for st in stages]
    bad[1]["owned_params"] = []  # pretend stage 1 owns nothing
    findings = verify_program_set(bad)
    assert any(f.check == "stage-foreign-optimizer"
               and f.severity == "error" for f in findings)


def test_verify_set_warns_unconsumed_output():
    from paddle_tpu.analysis import verify_program_set

    findings = verify_program_set([
        _summary(0, fwd_out=[("a", (4,), "float32")]),
        _summary(1),
    ])
    assert any(f.check == "stage-unconsumed-output"
               and f.severity == "warning" for f in findings)


# ---------------------------------------------------------------------------
# host scheduler: bit-parity vs run_accumulated
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_mlp_bit_parity(sched):
    """Adam + dropout MLP: pipeline loss trajectory AND final params are
    bit-identical to run_accumulated on the unsplit program."""
    prog, start, loss = _mlp_programs()
    pnames = [p.name for p in prog.all_parameters()]
    pipe = PipelineProgram(prog, ["x", "y"], n_stages=2, schedule=sched)

    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(4, 16, 8).astype("float32"),
            "y": rs.randn(4, 16, 1).astype("float32")}

    exe = pt.Executor(pt.CPUPlace())
    scope_a = pt.Scope()
    with pt.scope_guard(scope_a):
        init = _init_and_snapshot(start, scope_a, exe, pnames)
        tr_a = [np.asarray(exe.run_accumulated(
            prog, feed=feed, fetch_list=[loss], scope=scope_a)[0])
            for _ in range(6)]
        pa = {n: np.asarray(scope_a.find_var(n)) for n in pnames}

    exe2 = pt.Executor(pt.CPUPlace())
    scope_b = pt.Scope()
    with pt.scope_guard(scope_b):
        _init_and_snapshot(start, scope_b, exe2, pnames, init)
        tr_b = [np.asarray(exe2.run(
            pipe, feed=feed, fetch_list=[loss], scope=scope_b)[0])
            for _ in range(6)]
        pb = {n: np.asarray(scope_b.find_var(n)) for n in pnames}

    for n in pnames:  # training state: bit-exact
        assert np.array_equal(pa[n], pb[n]), (sched, n)
    for i, (a, b) in enumerate(zip(tr_a, tr_b)):
        # fetched loss: to the ulp (cross-module reduce rounding — see
        # _transformer_parity)
        np.testing.assert_allclose(a, b, rtol=3e-7, atol=0,
                                   err_msg=str((sched, i)))


def _transformer_parity(pp, scheds, n_layer, steps=2):
    """The pipeline parity contract: TRAINING STATE (params after every
    step) bit-identical to run_accumulated, loss trajectory within 1 ulp.

    The last-ulp carve-out on the fetched loss SCALAR is a measured XLA
    CPU property, not a scheduler defect: the reduce producing a fetched
    loss may tile differently between two separately compiled modules
    (scan-packaged, unrolled, or stage program — all pairs exhibit it on
    rounding-tie values), while every gradient, parameter and optimizer-
    state update stays bit-exact (probed per-grad at K=1 and K=4, clean
    and multi-device-polluted compiler state).  Any REAL numeric drift
    (wrong mask, dropped micro-batch, grad mis-rout) is orders of
    magnitude above 1 ulp and fails both asserts."""
    prog, start, loss, feeds = _transformer_programs(n_layer=n_layer)
    pnames = [p.name for p in prog.all_parameters()]
    stages = split_program(prog, feeds, n_stages=pp)
    feed = _transformer_feed(k=4, mbs=2)

    exe = pt.Executor(pt.CPUPlace())
    scope_a = pt.Scope()
    with pt.scope_guard(scope_a):
        init = _init_and_snapshot(start, scope_a, exe, pnames)
        tr_a = [np.asarray(exe.run_accumulated(
            prog, feed=feed, fetch_list=[loss], scope=scope_a)[0])
            for _ in range(steps)]
        pa = {n: np.asarray(scope_a.find_var(n)) for n in pnames}

    for sched in scheds:
        pipe = PipelineProgram(prog, feeds, schedule=sched, stages=stages)
        exe2 = pt.Executor(pt.CPUPlace())
        scope_b = pt.Scope()
        with pt.scope_guard(scope_b):
            _init_and_snapshot(start, scope_b, exe2, pnames, init)
            tr_b = [np.asarray(exe2.run(
                pipe, feed=feed, fetch_list=[loss], scope=scope_b)[0])
                for _ in range(steps)]
            pb = {n: np.asarray(scope_b.find_var(n)) for n in pnames}
        for n in pnames:  # training dynamics: bit-exact, always
            assert np.array_equal(pa[n], pb[n]), (pp, sched, n)
        for i, (a, b) in enumerate(zip(tr_a, tr_b)):
            np.testing.assert_allclose(  # fetched scalar: <= 1 ulp
                a, b, rtol=3e-7, atol=0, err_msg=str((pp, sched, i)))


@pytest.mark.slow
def test_transformer_pp2_bit_parity():
    """The acceptance gate, tier-1 shape: pp=2 transformer, dropout ON,
    GPipe AND 1F1B — state bit-parity + loss trajectory to the ulp."""
    _transformer_parity(2, ("gpipe", "1f1b"), n_layer=2)


@pytest.mark.slow
def test_transformer_pp4_bit_parity():
    """pp=4 on a 4-layer encoder-decoder (slow lane; the dryrun covers
    transformer-base widths at pp=2 AND pp=4)."""
    _transformer_parity(4, ("gpipe", "1f1b"), n_layer=4)


def test_run_accumulated_unroll_state_parity():
    """run_accumulated(unroll=True) — the reference multi-batch-merge
    shape (clone fwd/bwd K times) — matches the scanned form to a few
    ulp in params and losses over 4 Adam steps.  Unlike the pipeline
    parity pair, the two forms here share NO boundary-barrier marks, so
    nothing normalizes reduce association between the scan body and the
    straight-line clone — XLA may re-round a bias-grad reduce by an ulp
    (the PERF.md r11 class); identical math, not identical rounding."""
    prog, start, loss = _mlp_programs()
    pnames = [p.name for p in prog.all_parameters()]
    rs = np.random.RandomState(3)
    feed = {"x": rs.randn(4, 16, 8).astype("float32"),
            "y": rs.randn(4, 16, 1).astype("float32")}
    out = {}
    for mode in (False, True):
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        with pt.scope_guard(scope):
            if not out:
                init = _init_and_snapshot(start, scope, exe, pnames)
            else:
                _init_and_snapshot(start, scope, exe, pnames, init)
            tr = [np.asarray(exe.run_accumulated(
                prog, feed=feed, fetch_list=[loss], scope=scope,
                unroll=mode)[0]) for _ in range(4)]
            params = {n: np.asarray(scope.find_var(n)) for n in pnames}
        out[mode] = (tr, params)
    (tr_s, pa), (tr_u, pb) = out[False], out[True]
    for n in pnames:
        np.testing.assert_allclose(pa[n], pb[n], rtol=1e-5, atol=1e-7,
                                   err_msg=n)
    np.testing.assert_allclose(tr_s, tr_u, rtol=1e-6, atol=0)


def test_pipeline_fetch_contract():
    """Boundary/bwd/opt fetches: fwd fetches come back stacked [K,...],
    unknown fetches raise with the missing names."""
    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    pipe = PipelineProgram(prog, ["x", "y"], n_stages=2)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rs = np.random.RandomState(1)
    feed = {"x": rs.randn(3, 8, 8).astype("float32"),
            "y": rs.randn(3, 8, 1).astype("float32")}
    boundary = pipe.stages.stages[0].fwd_outputs[0][0]
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        lv, bv = exe.run(pipe, feed=feed, fetch_list=[loss, boundary],
                         scope=scope)
        assert np.asarray(lv).shape[0] == 3
        assert np.asarray(bv).shape[0] == 3  # stacked per micro-batch
        with pytest.raises(KeyError, match="ghost_fetch"):
            exe.run(pipe, feed=feed, fetch_list=["ghost_fetch"],
                    scope=scope)


def test_pipeline_scope_signature_in_cache_key():
    """A differently-populated scope must recompile, not reuse entries
    whose rw/ro state split was baked against another scope (the PR-9
    verifier-memo class, reintroduced-and-caught by review)."""
    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    pipe = PipelineProgram(prog, ["x", "y"], n_stages=2)
    exe = pt.Executor(pt.CPUPlace())
    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(2, 4, 8).astype("float32"),
            "y": rs.randn(2, 4, 1).astype("float32")}
    scope_a = pt.Scope()
    with pt.scope_guard(scope_a):
        exe.run(start, scope=scope_a)
        exe.run(pipe, feed=feed, fetch_list=[loss], scope=scope_a)
    assert len(pipe._cache) == 1
    # a scope where a formerly-program-local intermediate is RESIDENT
    # changes the state split -> distinct cache entry, not a stale hit
    scope_b = pt.Scope()
    with pt.scope_guard(scope_b):
        exe.run(start, scope=scope_b)
        inter = next(iter(pipe.stages.stages[0].fetch_candidates))
        scope_b.set_var(inter, np.zeros((4, 16), "float32"))
        exe.run(pipe, feed=feed, fetch_list=[loss], scope=scope_b)
    assert len(pipe._cache) == 2


def test_pipeline_batchnorm_rw_state_threads():
    """BN running stats advance once per micro-batch through the fwd
    carry — the run_accumulated scan-carry contract."""
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.batch_norm(layers.fc(x, size=4), momentum=0.5)
        loss = layers.mean(layers.square(layers.fc(h, size=1) - y))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
        bn_mean = [v for v in prog.global_block().vars.values()
                   if "batch_norm" in v.name and "mean" in v.name][0]
    pipe = PipelineProgram(prog, ["x", "y"], n_stages=2)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rs = np.random.RandomState(1)
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        m0 = np.asarray(scope.find_var(bn_mean.name)).copy()
        exe.run(pipe,
                feed={"x": (rs.randn(4, 16, 4) + 3).astype("float32"),
                      "y": rs.randn(4, 16, 1).astype("float32")},
                fetch_list=[loss], scope=scope)
        m1 = np.asarray(scope.find_var(bn_mean.name))
    assert not np.allclose(m0, m1)
    assert (np.abs(m1) > 1.0).any(), m1


# ---------------------------------------------------------------------------
# run_accumulated suffix-fetch satellite
# ---------------------------------------------------------------------------


def test_run_accumulated_fetches_suffix_outputs():
    """Optimize-suffix products are fetchable now (un-stacked), prefix
    fetches stay stacked [K, ...] — the former hard rejection is gone."""
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        loss = _build_mlp(opt="sgd", dropout=0.0)
        # a suffix-only product: an Optimize-role op whose output no
        # prefix op produces — it sees the AVERAGED grad the optimizer
        # consumes (suffix env = state + accumulated grads)
        blk = prog.global_block()
        blk.create_var(name="suffix_probe", shape=[8, 16],
                       dtype="float32")
        blk.append_op(
            "scale", inputs={"X": ["w1@GRAD"]},
            outputs={"Out": ["suffix_probe"]},
            attrs={"scale": 2.0,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize})
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(4, 8, 8).astype("float32"),
            "y": rs.randn(4, 8, 1).astype("float32")}
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        lv, g_stack, probe = exe.run_accumulated(
            prog, feed=feed,
            fetch_list=[loss, "w1@GRAD", "suffix_probe"], scope=scope)
        lv, g_stack, probe = map(np.asarray, (lv, g_stack, probe))
    assert lv.shape[0] == 4                      # prefix: stacked
    assert g_stack.shape == (4, 8, 16)           # prefix grads: stacked
    assert probe.shape == (8, 16)                # suffix: single value
    # the suffix consumed the micro-batch-AVERAGED gradient
    np.testing.assert_allclose(probe, 2.0 * g_stack.mean(axis=0),
                               rtol=1e-5, atol=1e-7)
    with pt.scope_guard(scope):
        # the static verifier names an unreachable fetch first; with the
        # gate off, run_accumulated's own fetch split names both sides
        from paddle_tpu.analysis import ProgramVerifyError
        from paddle_tpu.flags import FLAGS

        with pytest.raises(ProgramVerifyError, match="nowhere_var"):
            exe.run_accumulated(prog, feed=feed,
                                fetch_list=["nowhere_var"], scope=scope)
        FLAGS.set("verify_program", False)
        try:
            with pytest.raises(KeyError,
                               match="neither the fwd/bwd prefix"):
                exe.run_accumulated(prog, feed=feed,
                                    fetch_list=["nowhere_var"],
                                    scope=scope)
        finally:
            FLAGS.reset("verify_program")


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def test_pipeline_flight_spans_and_gauges():
    import paddle_tpu.monitor as monitor
    from paddle_tpu.flags import FLAGS
    from paddle_tpu.monitor import flight

    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    pipe = PipelineProgram(prog, ["x", "y"], n_stages=2, schedule="1f1b")
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(4, 8, 8).astype("float32"),
            "y": rs.randn(4, 8, 1).astype("float32")}
    FLAGS.set("monitor", True)
    try:
        flight.default_recorder().clear()
        with pt.scope_guard(scope):
            exe.run(start, scope=scope)
            exe.run(pipe, feed=feed, fetch_list=[loss], scope=scope)
        spans = flight.default_recorder().events(kind="pipeline.stage")
        assert len(spans) == 2 * 2 * 4  # stages x phases x micro-batches
        assert {e["ctx"] for e in spans} == {"pipeline/0", "pipeline/1"}
        assert {(e["stage"], e["phase"], e["mb"]) for e in spans} == {
            (s, ph, m) for s in (0, 1) for ph in ("fwd", "bwd")
            for m in range(4)}
        scheds = flight.default_recorder().events(
            kind="pipeline.schedule")
        assert scheds and scheds[-1]["schedule"] == "1f1b"
        assert scheds[-1]["bubble_fraction"] == pytest.approx(
            bubble_fraction(2, 4, "1f1b"), abs=1e-4)
        assert monitor.gauge("pipeline.microbatches_in_flight").value == \
            max_in_flight(2, 4, "1f1b")
    finally:
        FLAGS.reset("monitor")


def test_trace_report_renders_pipeline_section():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    doc = {"traceEvents": [], "flight": {"header": {}, "events": [
        {"kind": "pipeline.stage", "ctx": "pipeline/0", "stage": 0,
         "phase": "fwd", "mb": 0, "t0": 1.0, "dur": 0.01},
        {"kind": "pipeline.stage", "ctx": "pipeline/1", "stage": 1,
         "phase": "bwd", "mb": 0, "t0": 1.1, "dur": 0.02},
        {"kind": "pipeline.schedule", "schedule": "gpipe", "n_stages": 2,
         "n_micro": 4, "bubble_fraction": 0.2, "peak_in_flight": 4},
    ]}}
    text = tr.report(doc)
    assert "Pipeline stages" in text
    assert "pipeline/1" in text
    assert "bubble" in text.lower()
    assert "gpipe" in text


# ---------------------------------------------------------------------------
# mesh path (virtual 8-device CPU mesh from conftest)
# ---------------------------------------------------------------------------


def test_mesh_pipeline_dp_tp_pp():
    """dp=2 x tp=2 x pp=2: one compiled collective program; loss parity
    vs run_accumulated on the unsplit program (allclose — the mesh
    backward is a vjp recompute, so association differs by design)."""
    import jax

    from paddle_tpu.parallel.sharding import ShardingPlan

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
    pnames = [p.name for p in prog.all_parameters()]
    plan = ShardingPlan(mesh_axes={"data": 2, "model": 2, "pipe": 2})
    pipe = PipelineMeshProgram(prog, ["x", "y"], plan, schedule="gpipe")

    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(4, 8, 8).astype("float32"),
            "y": rs.randn(4, 8, 1).astype("float32")}

    exe = pt.Executor(pt.CPUPlace())
    scope_a = pt.Scope()
    with pt.scope_guard(scope_a):
        init = _init_and_snapshot(start, scope_a, exe, pnames)
        tr_a = [np.asarray(exe.run_accumulated(
            prog, feed=feed, fetch_list=[loss], scope=scope_a)[0])
            for _ in range(2)]
    exe2 = pt.Executor(pt.CPUPlace())
    scope_b = pt.Scope()
    with pt.scope_guard(scope_b):
        _init_and_snapshot(start, scope_b, exe2, pnames, init)
        tr_b = [np.asarray(exe2.run(
            pipe, feed=feed, fetch_list=[loss], scope=scope_b)[0])
            for _ in range(2)]
    np.testing.assert_allclose(tr_a, tr_b, rtol=1e-4, atol=1e-5)


def test_mesh_contract_errors_are_named():
    from paddle_tpu.parallel.sharding import ShardingPlan

    plan = ShardingPlan(mesh_axes={"data": 2, "pipe": 2})
    # no pipe axis in the plan
    with pytest.raises(ValueError, match="pipe"):
        prog, start, loss = _mlp_programs(opt="sgd", dropout=0.0)
        PipelineMeshProgram(prog, ["x", "y"],
                            ShardingPlan(mesh_axes={"data": 2}))
    # BN rw state in a forward stage
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.batch_norm(layers.fc(x, size=4))
        loss = layers.mean(layers.square(layers.fc(h, size=1) - y))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    pipe = PipelineMeshProgram(prog, ["x", "y"], plan)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rs = np.random.RandomState(0)
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        with pytest.raises(NotImplementedError, match="scope state"):
            exe.run(pipe, feed={"x": rs.randn(2, 4, 4).astype("float32"),
                                "y": rs.randn(2, 4, 1).astype("float32")},
                    fetch_list=[loss], scope=scope)
