#!/usr/bin/env python
"""bench_diff: the noise-aware bench regression sentry.

Compares a fresh bench/smoke JSON-lines artifact (bench.py output)
against a committed baseline ledger and exits nonzero naming every
regressed (workload, metric) pair — turning the BENCH_r* trajectory from
a human-reread document into an enforced contract (run_ci.sh gate).

Noise model: a record's `config.runs[]` (the PERF.md repeated-run
protocol) gives its observed envelope [min(runs), max(runs)]; both
envelopes are further widened by --rel-tol x value (cross-box / tunnel
variance the runs of ONE box cannot see).  A regression is flagged only
when the widened envelopes SEPARATE in the bad direction — overlap is
noise, never a finding.  Direction comes from the record's unit
("…/sec" higher-better; "ms"/"us"/"seconds" lower-better; anything else
is skipped with a note).

Records are keyed by (metric, occurrence index) — the A/B artifacts
archive the same metric twice with different flags (fused on/off,
kv_cache on/off) in a fixed order, so position is identity.

Exit codes: 0 clean, 1 regression / baseline metric missing from fresh,
2 usage or unreadable input.

Usage:
  python tools/bench_diff.py BASELINE.json FRESH.json [--rel-tol 0.30]
"""

from __future__ import annotations

import argparse
import json
import sys

LOWER_BETTER_UNITS = ("ms", "us", "seconds", "us/launch")
HIGHER_BETTER_MARK = "/sec"


def load_keyed(path):
    """[(key, record)] in file order; key = metric#occurrence."""
    seen = {}
    out = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        print(f"bench_diff: cannot read {path}: {e}", file=sys.stderr)
        sys.exit(2)
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        m = rec.get("metric")
        if not m:
            continue
        n = seen.get(m, 0)
        seen[m] = n + 1
        out.append((m if n == 0 else f"{m}#{n + 1}", rec))
    return out


def direction(rec):
    """+1 higher-better, -1 lower-better, 0 not comparable."""
    unit = str(rec.get("unit", ""))
    if HIGHER_BETTER_MARK in unit:
        return 1
    if unit in LOWER_BETTER_UNITS:
        return -1
    return 0


def envelope(rec, rel_tol):
    """(lo, hi) of the record's plausible true value: observed runs[]
    spread widened by rel_tol x value."""
    v = rec.get("value")
    if v is None:
        return None
    v = float(v)
    runs = (rec.get("config") or {}).get("runs")
    if isinstance(runs, list) and runs:
        lo, hi = float(min(runs)), float(max(runs))
    else:
        lo = hi = v
    pad = abs(v) * rel_tol
    return lo - pad, hi + pad


def provenance_line(tag, rec):
    p = rec.get("provenance") or {}
    commit = str(p.get("git_commit", "?"))[:12]
    dirty = "+dirty" if p.get("git_dirty") else ""
    return f"  {tag}: commit {commit}{dirty} jax {p.get('jax', '?')}"


def diff(baseline, fresh, rel_tol):
    """Returns (regressions, notes): regressions are the exit-nonzero
    findings, each naming the (workload, metric) pair."""
    fresh_map = dict(fresh)
    regressions, notes = [], []
    for key, base in baseline:
        d = direction(base)
        workload = key.split("_")[0]
        cur = fresh_map.pop(key, None)
        if cur is None:
            regressions.append(
                f"({workload}, {key}): present in baseline but MISSING "
                f"from the fresh artifact")
            continue
        if base.get("value") is None:
            notes.append(f"({workload}, {key}): baseline value is null; "
                         f"skipped")
            continue
        if cur.get("value") is None:
            regressions.append(
                f"({workload}, {key}): fresh value is null (workload "
                f"failed) vs baseline {base['value']}")
            continue
        if d == 0:
            notes.append(f"({workload}, {key}): unit "
                         f"{base.get('unit')!r} has no better-direction; "
                         f"skipped")
            continue
        b_lo, b_hi = envelope(base, rel_tol)
        c_lo, c_hi = envelope(cur, rel_tol)
        bv, cv = float(base["value"]), float(cur["value"])
        rel = (cv - bv) / abs(bv) if bv else 0.0
        if d > 0 and c_hi < b_lo:
            regressions.append(
                f"({workload}, {key}): REGRESSED {bv:g} -> {cv:g} "
                f"{base.get('unit')} ({rel:+.1%}); fresh envelope "
                f"[{c_lo:g}, {c_hi:g}] entirely below baseline "
                f"[{b_lo:g}, {b_hi:g}] at rel-tol {rel_tol:.0%}")
        elif d < 0 and c_lo > b_hi:
            regressions.append(
                f"({workload}, {key}): REGRESSED {bv:g} -> {cv:g} "
                f"{base.get('unit')} ({rel:+.1%}); fresh envelope "
                f"[{c_lo:g}, {c_hi:g}] entirely above baseline "
                f"[{b_lo:g}, {b_hi:g}] at rel-tol {rel_tol:.0%}")
        elif (d > 0 and c_lo > b_hi) or (d < 0 and c_hi < b_lo):
            notes.append(f"({workload}, {key}): improved {bv:g} -> "
                         f"{cv:g} {base.get('unit')} ({rel:+.1%})")
        else:
            notes.append(f"({workload}, {key}): ok {bv:g} -> {cv:g} "
                         f"({rel:+.1%}, within noise)")
    for key, _ in fresh:
        if key in fresh_map:
            notes.append(f"(new, {key}): present only in the fresh "
                         f"artifact; not compared")
    return regressions, notes


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", help="committed baseline ledger (JSONL)")
    ap.add_argument("fresh", help="fresh bench/smoke artifact (JSONL)")
    ap.add_argument("--rel-tol", type=float, default=0.30,
                    help="envelope widening as a fraction of value "
                         "(default 0.30: cross-box honesty; tighten for "
                         "same-box trend tracking)")
    ap.add_argument("--quiet", action="store_true",
                    help="print regressions only")
    args = ap.parse_args()

    baseline = load_keyed(args.baseline)
    fresh = load_keyed(args.fresh)
    if not baseline:
        print(f"bench_diff: no records in baseline {args.baseline}",
              file=sys.stderr)
        return 2
    regressions, notes = diff(baseline, fresh, args.rel_tol)
    if not args.quiet:
        if baseline and fresh:
            print(provenance_line("baseline", baseline[0][1]))
            print(provenance_line("fresh   ", fresh[0][1]))
        for n in notes:
            print(f"  note {n}")
    for r in regressions:
        print(f"  REGRESSION {r}")
    if regressions:
        print(f"bench_diff: {len(regressions)} regression(s) vs "
              f"{args.baseline}")
        return 1
    print(f"bench_diff: clean ({len(baseline)} baseline record(s), "
          f"rel-tol {args.rel_tol:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
