"""Detection op family (reference: paddle/fluid/operators/detection/ —
prior_box_op.h, box_coder_op.h, iou_similarity_op.h, bipartite_match_op.cc,
multiclass_nms_op.cc, roi_pool_op.cc, roi_align_op.cc).

TPU-first redesigns:
  * everything is dense/static-shape: multiclass_nms emits a fixed
    [N, keep_top_k, 6] tensor padded with label -1 plus a count vector
    (the reference emits a ragged LoD tensor on the host);
  * NMS suppression and bipartite matching are lax.fori_loop/scan chains
    over fixed trip counts, so the whole detection head stays inside one
    XLA program instead of falling back to per-image C++ loops;
  * roi_align/roi_pool sample with gathers — XLA fuses them; batch
    membership of each ROI is an explicit BatchIdx input (the reference
    encodes it in LoD).
"""

from __future__ import annotations

import math

import numpy as np

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _i64():
    """int64 clamped through jax's canonical dtype — int32 explicitly when
    x64 is off, instead of truncate-and-warn per trace (the one shared
    clamp: ops/tensor_ops.py _canon_i64)."""
    from .tensor_ops import _canon_i64

    return _canon_i64()


def _expand_aspect_ratios(ratios, flip):
    out = [1.0]
    for ar in ratios:
        if all(abs(ar - o) > 1e-6 for o in out):
            out.append(ar)
            if flip:
                out.append(1.0 / ar)
    return out


@register("prior_box", no_grad=True)
def lower_prior_box(ctx, ins):
    """SSD anchor generation (reference prior_box_op.h:54).  Outputs
    Boxes/Variances [H, W, num_priors, 4] in normalized ltrb."""
    jnp = _jnp()
    feat = ins["Input"][0]
    image = ins["Image"][0]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ratios = _expand_aspect_ratios(
        [float(r) for r in ctx.attr("aspect_ratios", [1.0])],
        ctx.attr("flip", False))
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    clip = ctx.attr("clip", False)
    offset = ctx.attr("offset", 0.5)
    mmao = ctx.attr("min_max_aspect_ratios_order", False)

    img_h, img_w = image.shape[2], image.shape[3]
    fh, fw = feat.shape[2], feat.shape[3]
    step_w = ctx.attr("step_w", 0.0) or img_w / fw
    step_h = ctx.attr("step_h", 0.0) or img_h / fh

    # per-cell half-extents (static python lists -> device constants)
    whs = []
    for si, ms in enumerate(min_sizes):
        per = []
        for ar in ratios:
            per.append((ms * math.sqrt(ar) / 2.0, ms / math.sqrt(ar) / 2.0))
        sq = None
        if si < len(max_sizes):
            m = math.sqrt(ms * max_sizes[si]) / 2.0
            sq = (m, m)
        if mmao:
            # min square, max square, then non-1 ratios
            ordered = [per[0]] + ([sq] if sq else []) + per[1:]
        else:
            ordered = per + ([sq] if sq else [])
        whs.extend(ordered)
    half = jnp.asarray(whs, jnp.float32)  # [P, 2] (w/2, h/2)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, half.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, half.shape[0]))
    bw = half[None, None, :, 0]
    bh = half[None, None, :, 1]
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


@register("box_coder", no_grad=True)
def lower_box_coder(ctx, ins):
    """Encode/decode boxes against priors with variances (reference
    box_coder_op.h encode_center_size/decode_center_size)."""
    jnp = _jnp()
    prior = ins["PriorBox"][0].reshape(-1, 4)
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = ctx.attr("code_type", "encode_center_size")
    norm = ctx.attr("box_normalized", True)
    one = 0.0 if norm else 1.0

    pcx, pcy, pw, ph = _center_size(prior, one)
    if pvar is not None:
        pvar = pvar.reshape(-1, 4)
        v0, v1, v2, v3 = pvar[:, 0], pvar[:, 1], pvar[:, 2], pvar[:, 3]
    else:
        v0 = v1 = v2 = v3 = 1.0

    if code_type.lower().startswith("encode"):
        t = target.reshape(-1, 4)  # [M, 4] gt boxes
        tcx, tcy, tw, th = _center_size(t, one)
        # out[i, j] = encoding of target j against prior i
        out = jnp.stack([
            (tcx[None, :] - pcx[:, None]) / pw[:, None] / _col(v0),
            (tcy[None, :] - pcy[:, None]) / ph[:, None] / _col(v1),
            jnp.log(tw[None, :] / pw[:, None]) / _col(v2),
            jnp.log(th[None, :] / ph[:, None]) / _col(v3),
        ], axis=-1)
        return {"OutputBox": [out]}

    # decode: target [N, M, 4] deltas against M priors
    t = target
    dcx = t[..., 0] * v0 * pw + pcx
    dcy = t[..., 1] * v1 * ph + pcy
    dw = jnp.exp(t[..., 2] * v2) * pw
    dh = jnp.exp(t[..., 3] * v3) * ph
    out = jnp.stack([
        dcx - dw * 0.5, dcy - dh * 0.5,
        dcx + dw * 0.5 - one, dcy + dh * 0.5 - one,
    ], axis=-1)
    return {"OutputBox": [out]}


def _col(v):
    jnp = _jnp()
    return v[:, None] if hasattr(v, "ndim") and v.ndim == 1 else v


def _center_size(boxes, one):
    """ltrb [..., 4] -> (cx, cy, w, h); `one` is the +1 pixel convention
    (0.0 for normalized coords).  The single source of truth for every
    box codec (box_coder, generate_proposals, rpn_target_assign)."""
    w = boxes[..., 2] - boxes[..., 0] + one
    h = boxes[..., 3] - boxes[..., 1] + one
    cx = boxes[..., 0] + w * 0.5
    cy = boxes[..., 1] + h * 0.5
    return cx, cy, w, h


def _iou_matrix(a, b, norm=True):
    """a [M,4], b [N,4] -> IoU [M,N] (reference iou_similarity_op.h)."""
    jnp = _jnp()
    one = 0.0 if norm else 1.0
    area_a = (a[:, 2] - a[:, 0] + one) * (a[:, 3] - a[:, 1] + one)
    area_b = (b[:, 2] - b[:, 0] + one) * (b[:, 3] - b[:, 1] + one)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + one, 0.0)
    ih = jnp.maximum(iy2 - iy1 + one, 0.0)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


@register("iou_similarity", no_grad=True)
def lower_iou_similarity(ctx, ins):
    x = ins["X"][0].reshape(-1, 4)
    y = ins["Y"][0].reshape(-1, 4)
    return {"Out": [_iou_matrix(x, y, ctx.attr("box_normalized", True))]}


@register("bipartite_match", no_grad=True)
def lower_bipartite_match(ctx, ins):
    """Greedy bipartite matching over a [M, N] similarity matrix
    (reference bipartite_match_op.cc BipartiteMatch): repeatedly take the
    globally-largest entry, match that (row gt, col prior) pair, exclude
    both.  match_type='per_prediction' additionally matches unmatched
    columns to their argmax row when similarity > dist_threshold.
    Outputs ColToRowMatchIndices/ColToRowMatchDist [1, N] (-1 = unmatched).
    """
    import jax

    jnp = _jnp()
    sim = ins["DistMat"][0]
    batched = sim.ndim == 3
    if not batched:
        sim = sim[None]                       # [1, M, N]
    m, n = sim.shape[1], sim.shape[2]
    match_type = ctx.attr("match_type", "bipartite")
    thresh = ctx.attr("dist_threshold", 0.5)

    def match_one(s0):
        def body(_, carry):
            s, col_row, col_dist = carry
            idx = jnp.argmax(s)
            r, c = idx // n, idx % n
            best = s[r, c]
            do = best > -1e9
            col_row = jnp.where(
                do & (jnp.arange(n) == c), r.astype(_i64()), col_row)
            col_dist = jnp.where(do & (jnp.arange(n) == c), best, col_dist)
            s = jnp.where(do & (jnp.arange(m)[:, None] == r), -1e10, s)
            s = jnp.where(do & (jnp.arange(n)[None, :] == c), -1e10, s)
            return s, col_row, col_dist

        col_row = jnp.full((n,), -1, _i64())
        col_dist = jnp.zeros((n,), jnp.float32)
        _, col_row, col_dist = jax.lax.fori_loop(
            0, min(m, n), body, (s0, col_row, col_dist))

        if match_type == "per_prediction":
            best_row = jnp.argmax(s0, axis=0).astype(_i64())
            best_val = jnp.max(s0, axis=0)
            extra = (col_row < 0) & (best_val > thresh)
            col_row = jnp.where(extra, best_row, col_row)
            col_dist = jnp.where(extra, best_val, col_dist)
        return col_row, col_dist

    col_row, col_dist = jax.vmap(match_one)(sim)   # [B, N]
    return {
        "ColToRowMatchIndices": [col_row],
        "ColToRowMatchDis": [col_dist],
    }


@register("multiclass_nms", no_grad=True)
def lower_multiclass_nms(ctx, ins):
    """Per-class NMS + cross-class top-k (reference multiclass_nms_op.cc).

    Dense output: Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2,
    y2), padded with label=-1; NmsRoisNum [N] valid counts (the reference
    returns a host-built LoD tensor)."""
    import jax

    jnp = _jnp()
    bboxes = ins["BBoxes"][0]   # [N, M, 4]
    scores = ins["Scores"][0]   # [N, C, M]
    bg = ctx.attr("background_label", 0)
    score_th = ctx.attr("score_threshold", 0.0)
    nms_th = ctx.attr("nms_threshold", 0.3)
    nms_top_k = ctx.attr("nms_top_k", 64)
    keep_top_k = ctx.attr("keep_top_k", 16)
    normalized = ctx.attr("normalized", True)

    n, c, m = scores.shape
    top = min(nms_top_k if nms_top_k > 0 else m, m)

    def one_class(boxes, sc):
        # boxes [M,4], sc [M] -> (scores_kept [top], idx [top]) after NMS
        vals, idx = jax.lax.top_k(sc, top)
        b = jnp.take(boxes, idx, axis=0)
        iou = _iou_matrix(b, b, normalized)
        valid0 = vals > score_th

        def body(i, keep):
            # suppress j>i overlapping an alive i
            alive_i = keep[i]
            sup = (iou[i] > nms_th) & (jnp.arange(top) > i) & alive_i
            return keep & ~sup

        keep = jax.lax.fori_loop(0, top, body, valid0)
        return jnp.where(keep, vals, -1.0), idx

    def one_image(boxes, sc):
        # sc [C, M]
        cls_scores, cls_idx = jax.vmap(
            lambda s: one_class(boxes, s))(sc)  # [C, top], [C, top]
        labels = jnp.broadcast_to(
            jnp.arange(c)[:, None], (c, top))
        flat_scores = cls_scores.reshape(-1)
        flat_idx = cls_idx.reshape(-1)
        flat_labels = labels.reshape(-1)
        if 0 <= bg < c:
            flat_scores = jnp.where(flat_labels == bg, -1.0, flat_scores)
        k = min(keep_top_k if keep_top_k > 0 else flat_scores.shape[0],
                flat_scores.shape[0])
        vals, sel = jax.lax.top_k(flat_scores, k)
        sel_boxes = jnp.take(boxes, jnp.take(flat_idx, sel), axis=0)
        sel_labels = jnp.take(flat_labels, sel)
        # suppressed / below-threshold / background entries carry score -1
        valid = vals > -0.5
        out = jnp.concatenate([
            jnp.where(valid, sel_labels, -1).astype(jnp.float32)[:, None],
            vals[:, None],
            sel_boxes,
        ], axis=1)
        return out, valid.sum().astype(_i64())

    outs, counts = jax.vmap(one_image)(bboxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


def _roi_common(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    rois = ins["ROIs"][0].reshape(-1, 4)
    if ins.get("BatchIdx"):
        bidx = ins["BatchIdx"][0].reshape(-1).astype(jnp.int32)
    else:
        bidx = jnp.zeros((rois.shape[0],), jnp.int32)
    return x, rois, bidx


@register("roi_align", no_grad=False)
def lower_roi_align(ctx, ins):
    """ROI align with bilinear sampling (reference roi_align_op.cc).
    sampling_ratio fixed grid; differentiable (generic vjp -> scatter)."""
    import jax

    jnp = _jnp()
    x, rois, bidx = _roi_common(ctx, ins)
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    scale = ctx.attr("spatial_scale", 1.0)
    ratio = ctx.attr("sampling_ratio", -1)
    ratio = ratio if ratio > 0 else 2
    n, ch, h, w = x.shape

    def one(roi, bi):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid [ph*ratio, pw*ratio]
        gy = y1 + (jnp.arange(ph * ratio) + 0.5) * bin_h / ratio
        gx = x1 + (jnp.arange(pw * ratio) + 0.5) * bin_w / ratio
        gy = jnp.clip(gy, 0.0, h - 1.0)
        gx = jnp.clip(gx, 0.0, w - 1.0)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x0 = jnp.floor(gx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = gy - y0
        wx = gx - x0
        img = x[bi]  # [C, H, W]
        # bilinear: [C, gy, gx]
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (v00 * ((1 - wy)[:, None] * (1 - wx)[None, :])
               + v01 * ((1 - wy)[:, None] * wx[None, :])
               + v10 * (wy[:, None] * (1 - wx)[None, :])
               + v11 * (wy[:, None] * wx[None, :]))
        # average each ratio x ratio cell
        val = val.reshape(ch, ph, ratio, pw, ratio).mean(axis=(2, 4))
        return val

    out = jax.vmap(one)(rois, bidx)
    return {"Out": [out]}


@register("roi_pool", no_grad=False)
def lower_roi_pool(ctx, ins):
    """ROI max pooling (reference roi_pool_op.cc).  Quantized bin edges,
    max within each bin (empty bins -> 0)."""
    import jax

    jnp = _jnp()
    x, rois, bidx = _roi_common(ctx, ins)
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    scale = ctx.attr("spatial_scale", 1.0)
    n, ch, h, w = x.shape

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one(roi, bi):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        img = x[bi]  # [C, H, W]

        # membership masks per pooled cell (static shapes, fused by XLA)
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        y_lo = jnp.clip(jnp.floor(y1 + py * bin_h), 0, h - 1)
        y_hi = jnp.clip(jnp.ceil(y1 + (py + 1) * bin_h), 0, h)
        x_lo = jnp.clip(jnp.floor(x1 + px * bin_w), 0, w - 1)
        x_hi = jnp.clip(jnp.ceil(x1 + (px + 1) * bin_w), 0, w)
        in_y = (ys[None, :] >= y_lo[:, None]) & (ys[None, :] < y_hi[:, None])
        in_x = (xs[None, :] >= x_lo[:, None]) & (xs[None, :] < x_hi[:, None])
        mask = in_y[:, None, :, None] & in_x[None, :, None, :]  # [ph,pw,H,W]
        masked = jnp.where(mask[None], img[:, None, None], -jnp.inf)
        val = masked.max(axis=(-1, -2))  # [C, ph, pw]
        return jnp.where(jnp.isfinite(val), val, 0.0)

    out = jax.vmap(one)(rois, bidx)
    return {"Out": [out]}


@register("anchor_generator", no_grad=True)
def lower_anchor_generator(ctx, ins):
    """RPN anchor generation (reference anchor_generator_op.h:26): per
    feature cell, one anchor per (aspect_ratio, anchor_size) pair in PIXEL
    (unnormalized) coordinates.  Outputs Anchors/Variances
    [H, W, num_anchors, 4]."""
    jnp = _jnp()
    feat = ins["Input"][0]
    sizes = [float(s) for s in ctx.attr("anchor_sizes")]
    ratios = [float(r) for r in ctx.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in ctx.attr("stride")]
    offset = ctx.attr("offset", 0.5)
    fh, fw = feat.shape[2], feat.shape[3]
    sw, sh = stride[0], stride[1]

    # static per-cell half-extents, reference loop order (ratio, size)
    whs = []
    for ar in ratios:
        base_w = round(math.sqrt(sw * sh / ar))
        base_h = round(base_w * ar)
        for sz in sizes:
            whs.append((sz / sw * base_w, sz / sh * base_h))
    wh = jnp.asarray(whs, jnp.float32)  # [A, 2]

    cx = jnp.arange(fw, dtype=jnp.float32) * sw + offset * (sw - 1)
    cy = jnp.arange(fh, dtype=jnp.float32) * sh + offset * (sh - 1)
    a = wh.shape[0]
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, a))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, a))
    aw = wh[None, None, :, 0]
    ah = wh[None, None, :, 1]
    anchors = jnp.stack([
        cxg - 0.5 * (aw - 1), cyg - 0.5 * (ah - 1),
        cxg + 0.5 * (aw - 1), cyg + 0.5 * (ah - 1),
    ], axis=-1)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), anchors.shape)
    return {"Anchors": [anchors], "Variances": [var]}


@register("box_clip", no_grad=True)
def lower_box_clip(ctx, ins):
    """Clip boxes to image bounds (reference box_clip_op.h): ImInfo rows
    are (height, width, scale); boxes clip to [0, dim - 1]."""
    jnp = _jnp()
    boxes = ins["Input"][0]  # [b, M, 4] or [M, 4]
    im_info = ins["ImInfo"][0].reshape(-1, 3)
    squeeze = boxes.ndim == 2
    if squeeze:
        boxes = boxes[None]
    h = im_info[:, 0].reshape(-1, 1, 1) - 1.0
    w = im_info[:, 1].reshape(-1, 1, 1) - 1.0
    out = jnp.concatenate([
        jnp.minimum(jnp.clip(boxes[..., 0:1], 0.0, None), w),
        jnp.minimum(jnp.clip(boxes[..., 1:2], 0.0, None), h),
        jnp.minimum(jnp.clip(boxes[..., 2:3], 0.0, None), w),
        jnp.minimum(jnp.clip(boxes[..., 3:4], 0.0, None), h),
    ], axis=-1)
    if squeeze:
        out = out[0]
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# SSD training ops (round 4): target_assign, mine_hard_examples,
# density_prior_box, detection_map
# ---------------------------------------------------------------------------


@register("target_assign", no_grad=True)
def lower_target_assign(ctx, ins):
    """Assign per-prior targets from matched gt rows (reference
    detection/target_assign_op.h TargetAssignFunctor).

    Dense idiom: X is [N, G, K] (or [N, G, P, K] for per-prior encodings,
    e.g. box_coder encode output), MatchIndices [N, P] (gt id or -1).
    Out[n,p] = X[n, match[n,p]] (or X[n, match[n,p], p]); weight 1 for
    matched, else mismatch_value/0.  Optional NegIndices is a dense [N, P]
    0/1 mask (the reference's LoD list of negative prior ids): negatives
    get Out=mismatch_value with weight 1 — that is how background labels
    enter the conf loss."""
    jnp = _jnp()
    x = ins["X"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)     # [N, P]
    mismatch = ctx.attr("mismatch_value", 0)
    n, p = match.shape
    safe = jnp.maximum(match, 0)
    if x.ndim == 4:
        # [N, G, P, K] -> out[n,p,k] = x[n, match[n,p], p, k] via one
        # advanced-indexing gather (NOT take_along_axis, whose broadcast
        # would materialize an O(P^2) [N, P, P, K] intermediate)
        bi = jnp.arange(n)[:, None]                      # [N, 1]
        pi = jnp.arange(p)[None, :]                      # [1, P]
        gathered = x[bi, safe, pi]                       # [N, P, K]
    else:
        gathered = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, gathered.dtype))
    wt = matched.astype(jnp.float32)
    if ins.get("NegIndices"):
        neg = ins["NegIndices"][0].reshape(n, p).astype(bool)
        out = jnp.where(neg[:, :, None],
                        jnp.asarray(mismatch, out.dtype), out)
        wt = jnp.maximum(wt, neg[:, :, None].astype(jnp.float32))
    return {"Out": [out], "OutWeight": [wt]}


@register("mine_hard_examples", no_grad=True)
def lower_mine_hard_examples(ctx, ins):
    """Hard-negative mining (reference detection/mine_hard_examples_op.cc).

    max_negative: eligible = unmatched priors with match_dist below
    neg_dist_threshold; keep the top num_pos*neg_pos_ratio by conf loss.
    NegIndices is emitted as a dense [N, P] 0/1 mask (reference: LoD id
    list).  UpdatedMatchIndices == MatchIndices for max_negative."""
    jnp = _jnp()
    cls_loss = ins["ClsLoss"][0]                          # [N, P]
    match = ins["MatchIndices"][0].astype(jnp.int32)      # [N, P]
    dist = ins["MatchDist"][0] if ins.get("MatchDist") else None
    ratio = ctx.attr("neg_pos_ratio", 3.0)
    thresh = ctx.attr("neg_dist_threshold", 0.5)
    mining = ctx.attr("mining_type", "max_negative")
    if mining != "max_negative":
        # the reference's kHardExample additionally demotes unselected
        # positives in UpdatedMatchIndices; refuse rather than half-do it
        raise NotImplementedError(
            "mine_hard_examples: only mining_type='max_negative' is "
            f"implemented (got {mining!r})")

    loss = cls_loss
    eligible = match < 0
    if dist is not None:
        eligible &= dist < thresh
    num_pos = jnp.sum((match >= 0).astype(jnp.int32), axis=1)  # [N]
    num_elig = jnp.sum(eligible.astype(jnp.int32), axis=1)
    neg_sel = jnp.minimum((num_pos.astype(jnp.float32)
                           * ratio).astype(jnp.int32), num_elig)

    masked = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)                  # desc by loss
    rank = jnp.argsort(order, axis=1)                     # rank per prior
    neg = eligible & (rank < neg_sel[:, None])
    return {
        "NegIndices": [neg.astype(jnp.int32)],
        "UpdatedMatchIndices": [match],
    }


@register("density_prior_box", no_grad=True)
def lower_density_prior_box(ctx, ins):
    """Densified anchors (reference detection/density_prior_box_op.h):
    for each fixed_size with density d, d*d shifted centers per cell; one
    box per fixed_ratio.  Outputs Boxes/Variances [H, W, P, 4]."""
    jnp = _jnp()
    feat = ins["Input"][0]
    image = ins["Image"][0]
    fixed_sizes = [float(s) for s in ctx.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in ctx.attr("fixed_ratios", [1.0])]
    densities = [int(d) for d in ctx.attr("densities", [])]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    clip = ctx.attr("clip", False)
    offset = ctx.attr("offset", 0.5)
    img_h, img_w = image.shape[2], image.shape[3]
    fh, fw = feat.shape[2], feat.shape[3]
    step_w = ctx.attr("step_w", 0.0) or img_w / fw
    step_h = ctx.attr("step_h", 0.0) or img_h / fh

    # per-cell (dx, dy, w/2, h/2) tuples, static; the shift grid is laid
    # out on step_average for BOTH axes (density_prior_box_op.h:65-87)
    step_avg = int((step_w + step_h) * 0.5)
    cells = []
    for size, density in zip(fixed_sizes, densities):
        shift = step_avg / density
        for r in fixed_ratios:
            bw = size * math.sqrt(r) / 2.0
            bh = size / math.sqrt(r) / 2.0
            for di in range(density):
                for dj in range(density):
                    dx = (dj + 0.5) * shift - step_avg * 0.5
                    dy = (di + 0.5) * shift - step_avg * 0.5
                    cells.append((dx, dy, bw, bh))
    spec = jnp.asarray(cells, jnp.float32)                # [P, 4]

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    pnum = spec.shape[0]
    ccx = cx[None, :, None] + spec[None, None, :, 0]
    ccy = cy[:, None, None] + spec[None, None, :, 1]
    ccx = jnp.broadcast_to(ccx, (fh, fw, pnum))
    ccy = jnp.broadcast_to(ccy, (fh, fw, pnum))
    bw = spec[None, None, :, 2]
    bh = spec[None, None, :, 3]
    boxes = jnp.stack(
        [(ccx - bw) / img_w, (ccy - bh) / img_h,
         (ccx + bw) / img_w, (ccy + bh) / img_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


@register("detection_map", no_grad=True)
def lower_detection_map(ctx, ins):
    """Mean average precision (reference detection/detection_map_op.cc,
    integral + 11point).  Dense idiom: DetectRes [N, D, 6] rows
    (label, score, x1, y1, x2, y2) padded with label=-1; Label [N, G, 6]
    rows (label, x1, y1, x2, y2, difficult) padded with label=-1.
    Single-shot evaluation (the reference's streaming PosCount/TruePos
    accumulation is served by CheckpointManager-style host metrics)."""
    import jax

    jnp = _jnp()
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    overlap_t = ctx.attr("overlap_threshold", 0.5)
    ap_type = ctx.attr("ap_type", "integral")
    class_num = ctx.attr("class_num")
    evaluate_difficult = ctx.attr("evaluate_difficult", True)
    n, d_max, _ = det.shape
    g_max = gt.shape[1]

    if gt.shape[2] >= 6:
        difficult = gt[:, :, 5] > 0.5
    else:
        difficult = jnp.zeros(gt.shape[:2], bool)
    gt_valid = gt[:, :, 0] >= 0
    det_valid = det[:, :, 0] >= 0

    # [N, D, G] IoU between detections and gts of the same image
    def img_iou(db, gb):
        return _iou_matrix(db, gb, True)

    ious = jax.vmap(img_iou)(det[:, :, 2:6], gt[:, :, 1:5])

    def ap_for_class(c):
        c_gt = gt_valid & (gt[:, :, 0].astype(jnp.int32) == c)
        if not evaluate_difficult:
            npos = jnp.sum((c_gt & ~difficult).astype(jnp.int32))
        else:
            npos = jnp.sum(c_gt.astype(jnp.int32))
        c_det = det_valid & (det[:, :, 0].astype(jnp.int32) == c)
        scores = jnp.where(c_det, det[:, :, 1], -jnp.inf)  # [N, D]

        # greedy per-image match: detection (desc score) claims the best
        # unclaimed same-class gt at IoU strictly > threshold (reference
        # detection_map_op.h overlap > threshold); with
        # evaluate_difficult=False, difficult gts are not claimable at all
        # (the reference leaves them unvisited)
        def match_image(sc, iou_im, gts, diff):
            order = jnp.argsort(-sc)
            claimable = gts if evaluate_difficult else (gts & ~diff)

            def body(i, carry):
                claimed, tp, fp = carry
                di = order[i]
                valid = sc[di] > -jnp.inf
                cand = jnp.where(claimable & ~claimed, iou_im[di], -1.0)
                best = jnp.argmax(cand)
                ok = (cand[best] > overlap_t) & valid
                claimed = claimed | (ok & (jnp.arange(g_max) == best))
                tp = tp.at[di].set(jnp.where(valid & ok, 1.0, 0.0))
                fp = fp.at[di].set(
                    jnp.where(valid & ~ok, 1.0, 0.0))
                return claimed, tp, fp

            claimed0 = jnp.zeros((g_max,), bool)
            tp0 = jnp.zeros((d_max,), jnp.float32)
            fp0 = jnp.zeros((d_max,), jnp.float32)
            _, tp, fp = jax.lax.fori_loop(0, d_max, body,
                                          (claimed0, tp0, fp0))
            return tp, fp

        tp, fp = jax.vmap(match_image)(scores, ious, c_gt, difficult)
        flat_scores = scores.reshape(-1)
        order = jnp.argsort(-flat_scores)
        tp_s = jnp.take(tp.reshape(-1), order)
        fp_s = jnp.take(fp.reshape(-1), order)
        tp_c = jnp.cumsum(tp_s)
        fp_c = jnp.cumsum(fp_s)
        prec = tp_c / jnp.maximum(tp_c + fp_c, 1e-10)
        rec = tp_c / jnp.maximum(npos.astype(jnp.float32), 1e-10)
        active = jnp.take(flat_scores, order) > -jnp.inf
        if ap_type == "11point":
            pts = jnp.linspace(0.0, 1.0, 11)
            pmax = jax.vmap(
                lambda t: jnp.max(jnp.where((rec >= t) & active, prec, 0.0))
            )(pts)
            ap = jnp.mean(pmax)
        else:
            d_rec = jnp.diff(rec, prepend=0.0)
            ap = jnp.sum(jnp.where(active, prec * d_rec, 0.0))
        return ap, npos > 0

    # one traced ap_for_class, vmapped over the class axis (a Python loop
    # would duplicate the whole greedy-match subgraph class_num times)
    bg = ctx.attr("background_label", 0)
    classes = jnp.arange(class_num)
    aps, has = jax.vmap(ap_for_class)(classes)
    has = has.astype(jnp.float32) * (classes != bg).astype(jnp.float32)
    m_ap = jnp.sum(aps * has) / jnp.maximum(jnp.sum(has), 1.0)
    return {"MAP": [m_ap.reshape((1,))]}
