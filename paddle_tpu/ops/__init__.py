"""Importing this package registers all op lowerings."""

from . import (  # noqa: F401
    math_ops,
    tensor_ops,
    nn_ops,
    reduce_ops,
    random_ops,
    optimizer_ops,
    metric_ops,
    fused_ops,
    control_flow_ops,
    sequence_ops,
    rnn_ops,
    misc_ops,
    quant_ops,
    detection_ops,
    ctc_ops,
    image_ops,
    rcnn_ops,
    generation_ops,
    memory_ops,
    numerics_ops,
)
