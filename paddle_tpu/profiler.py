"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler.cc
host event tables + CUPTI device tracer → chrome trace).

TPU equivalent: jax.profiler captures XPlane traces viewable in
TensorBoard/Perfetto (the reference's tools/timeline.py chrome-trace role),
plus a lightweight host-side step timer table for the per-op summary role."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional


class _HostEvents:
    def __init__(self):
        import threading

        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.maxes = defaultdict(float)
        # per-thread range stack: concurrent record_event() ranges on
        # different threads must not pop each other's (name, t0)
        self._local = threading.local()
        # add() is reached from serving/executor threads via
        # profiler.add_event; unlocked += would drop increments
        self._lock = threading.Lock()

    @property
    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def push(self, name):
        self._stack.append((name, time.perf_counter()))

    def pop(self):
        name, t0 = self._stack.pop()
        self.add(name, time.perf_counter() - t0)

    def add(self, name, dt):
        with self._lock:
            self.totals[name] += dt
            self.counts[name] += 1
            self.maxes[name] = max(self.maxes[name], dt)

    def summary(self, sorted_key="total"):
        rows = []
        with self._lock:  # add() on worker threads may insert new names
            names = list(self.totals)
            for name in names:
                total = self.totals[name]
                cnt = self.counts[name]
                rows.append(
                    (name, cnt, total, total / cnt, self.maxes[name]))
        key_idx = {"total": 2, "calls": 1, "ave": 3, "max": 4}.get(sorted_key, 2)
        rows.sort(key=lambda r: r[key_idx], reverse=True)
        return rows

    def reset(self):
        with self._lock:  # don't interleave with a worker thread's add()
            self.totals.clear()
            self.counts.clear()
            self.maxes.clear()


_events = _HostEvents()
_profiling = False


@contextlib.contextmanager
def record_event(name):
    """RAII range (reference: platform/profiler.h:72 RecordEvent)."""
    _events.push(name)
    try:
        yield
    finally:
        _events.pop()


def add_event(name, seconds: float):
    """Record an already-measured host range into the event table — used
    by instrumentation that owns its timer (the executor's monitored
    run/compile paths), so the profiler summary covers the runtime hot
    paths without nesting context managers through their control flow."""
    _events.add(name, seconds)


def host_events(sorted_key="total"):
    """Rows of (name, calls, total_s, avg_s, max_s) from the host event
    table, without printing (stop_profiler's table, accessor form)."""
    return _events.summary(sorted_key)


# clock bridge for the unified timeline: xplane event timestamps are
# relative to the trace-session start, flight-recorder events are epoch
# seconds — stamping time.time() at start_trace lets the export put both
# on one axis (skew = the microseconds start_trace takes to return)
_trace_start_epoch: Optional[float] = None
_trace_dir: Optional[str] = None


def start_profiler(state="All", trace_dir: Optional[str] = None):
    global _profiling, _trace_start_epoch, _trace_dir
    _profiling = True
    _events.reset()
    if trace_dir:
        import jax

        _trace_dir = trace_dir
        _trace_start_epoch = time.time()
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path: Optional[str] = None,
                  tracing: bool = False):
    global _profiling
    _profiling = False
    if tracing:
        import jax

        jax.profiler.stop_trace()
    rows = _events.summary(sorted_key)
    lines = ["Event                          Calls     Total(s)    Ave(s)      Max(s)"]
    for name, cnt, total, ave, mx in rows:
        lines.append(f"{name:<30} {cnt:>6} {total:>12.6f} {ave:>10.6f} {mx:>10.6f}")
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    print(report)
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             trace_dir: Optional[str] = None):
    """reference: fluid.profiler.profiler contextmanager."""
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, tracing=trace_dir is not None)


# ---------------------------------------------------------------------------
# Per-op DEVICE cost attribution (reference: platform/device_tracer.cc CUPTI
# kernel records correlated to host ranges; here two TPU-native sources:
# XLA's compiled cost analysis and captured xplane traces)
# ---------------------------------------------------------------------------


def cost_analysis(program, feed, fetch_list=None, scope=None):
    """Static device cost estimate from XLA's compiled cost model
    ({'flops': .., 'bytes accessed': .., 'utilization...': ..}) for one
    executor call over `program` — the reference's per-op FLOP accounting
    role (platform/profiler per-op tables), exact and without executing."""
    from .core import executor as ex
    from .core import framework as fw

    exe = ex.Executor()
    scope = scope or ex.global_scope()
    feed_names = sorted(feed)
    fetch_names = [
        v.name if isinstance(v, fw.Variable) else v
        for v in (fetch_list or [])
    ]
    entry = exe._compile(program, feed, feed_names, fetch_names, scope)
    feed_vals = [exe._to_device_array(program, n, feed[n])
                 for n in feed_names]
    rw_vals = [scope.find_var(n) for n in entry.rw_state]
    ro_vals = [scope.find_var(n) for n in entry.ro_state]
    if entry.needs_key:
        lowered = entry.fn.lower(feed_vals, rw_vals, ro_vals,
                                 ex.prng_key(0))
    else:
        lowered = entry.fn.lower(feed_vals, rw_vals, ro_vals)
    cost = lowered.compile().cost_analysis()
    # jax returns one properties dict per partition on some versions and a
    # bare dict on others; normalize to ONE dict (numeric keys summed)
    if isinstance(cost, (list, tuple)):
        merged = {}
        for entry_props in cost:
            for k, v in (entry_props or {}).items():
                if isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0.0) + v
        cost = merged
    return cost


def xplane_op_table(trace_dir: str, top_k: int = 30):
    """Aggregate per-op device time from a jax.profiler trace directory
    (the reference's profiler table role, device-side).  Returns rows of
    (op_group, total_seconds) sorted descending; op names collapse to
    their fusion-group prefix.  Requires a trace captured with
    start_profiler(trace_dir=...) around device work.  Decodes xplane.pb
    natively (paddle_tpu.xplane) — no TensorFlow proto dependency."""
    from collections import defaultdict

    from . import xplane as _xp

    files = _xp.find_xplane_files(trace_dir)
    if not files:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    agg = defaultdict(float)
    for path in files:
        space = _xp.parse_xspace_file(path)
        for plane in space.planes:
            if "TPU" not in plane.name and "GPU" not in plane.name:
                continue
            for line in plane.lines:
                if "Ops" not in line.name or "Async" in line.name:
                    continue
                for ev in line.events:
                    agg[ev.name.split(".")[0]] += ev.duration_ps / 1e12
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top_k]
    return rows


def print_op_table(trace_dir: str, top_k: int = 30):
    rows = xplane_op_table(trace_dir, top_k)
    lines = ["Device op group                          Total(s)"]
    for name, t in rows:
        lines.append(f"{name:<40} {t:>10.6f}")
    report = "\n".join(lines)
    print(report)
    return rows


def _xplane_chrome_events(trace_dir: str, max_events: int,
                          first_pid: int = 100):
    """Chrome-trace events (ts in trace-relative microseconds) for every
    xplane plane under `trace_dir`: one pid per plane (per-device tracks),
    one tid per line."""
    from . import xplane as _xp

    files = _xp.find_xplane_files(trace_dir)
    if not files:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    events = []
    n_slices = 0
    pid = first_pid - 1
    for path in files:
        space = _xp.parse_xspace_file(path)
        for plane in space.planes:
            if not plane.lines:
                continue
            pid += 1
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": plane.name,
                         "source": "xplane",
                         "device": _xp.is_device_plane(plane.name)}})
            for tid, line in enumerate(plane.lines):
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": line.name}})
                base = line.timestamp_ns
                for ev in line.events:
                    if n_slices >= max_events:
                        break
                    events.append({
                        "name": ev.name[:96],
                        "ph": "X",
                        "pid": pid,
                        "tid": tid,
                        "ts": (base + ev.offset_ps / 1000) / 1000.0,
                        "dur": ev.duration_ps / 1e6,
                    })
                    n_slices += 1
    return events


def export_chrome_trace(trace_dir: str, out_path: str, max_events=50000):
    """Convert a captured xplane trace to chrome://tracing JSON (the
    reference's tools/timeline.py role over its protobuf profile).  Each
    plane becomes a pid, each line a tid; op events carry their XLA
    names.  Decoded natively — no TensorFlow proto dependency."""
    import json as _json

    events = _xplane_chrome_events(trace_dir, max_events)
    with open(out_path, "w") as f:
        _json.dump({"traceEvents": events}, f)
    return len(events)


# ---------------------------------------------------------------------------
# Unified host+device timeline (tentpole of the flight-recorder PR): ONE
# chrome-trace file holding the flight recorder's host spans (executor
# compile/run, feed stalls, steps, collectives) and the XLA xplane device
# ops, on a shared clock.  The reference needed two tools (timeline.py for
# CUPTI + the host event table print); here one file answers "was the chip
# idle while the host stalled?" by inspection.
# ---------------------------------------------------------------------------

# flight-event kind prefix -> stable tid on the host process (chrome sorts
# tids numerically; keep executor on top).  "trace" carries the request-
# scoped serving spans (monitor/tracing.py trace.span / trace.request) —
# their own track next to the executor spans and xplane device ops, all
# on the one bridged clock.
_HOST_TIDS = (
    ("executor", 0), ("step", 1), ("feed", 2), ("collective", 3),
    ("trace", 4),
)


def _host_tid(kind: str):
    for prefix, tid in _HOST_TIDS:
        if kind == prefix or kind.startswith(prefix + "."):
            return tid
    return len(_HOST_TIDS)  # misc


def _flight_chrome_events(flight_events, trace_start_epoch, pid=1):
    """Flight-recorder events as chrome slices/instants, on the xplane
    clock (trace-relative microseconds)."""
    events = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": "paddle_tpu host (flight)", "source": "flight"}}]
    for prefix, tid in _HOST_TIDS + (("misc", len(_HOST_TIDS)),):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"host:{prefix}"}})
    for ev in flight_events:
        kind = ev.get("kind", "?")
        tid = _host_tid(kind)
        args = {k: v for k, v in ev.items()
                if k not in ("kind", "t0", "dur", "seq", "ts")
                and isinstance(v, (int, float, str, bool))}
        # request-trace events carry their span/request identity — name
        # the chrome slice after it, not the generic event kind
        name = kind
        if kind == "trace.span":
            name = f"trace:{ev.get('name', 'span')}"
        elif kind == "trace.request":
            name = f"request:{ev.get('model', '?')}"
        if "t0" in ev and "dur" in ev:  # span
            events.append({
                "name": name, "ph": "X", "pid": pid, "tid": tid,
                "ts": (ev["t0"] - trace_start_epoch) * 1e6,
                "dur": float(ev["dur"]) * 1e6,
                "args": args,
            })
        else:  # instant (recompile, watchdog trip, signal, ...)
            events.append({
                "name": name, "ph": "i", "s": "p", "pid": pid, "tid": tid,
                "ts": (ev.get("ts", trace_start_epoch)
                       - trace_start_epoch) * 1e6,
                "args": args,
            })
    return events


def export_unified_chrome_trace(out_path: str,
                                trace_dir: Optional[str] = None,
                                flight=None,
                                trace_start_epoch: Optional[float] = None,
                                max_events: int = 50000):
    """Merge host flight spans + xplane device ops into one chrome trace.

    trace_dir defaults to the directory of the last start_profiler
    (trace_dir=...) call; trace_start_epoch to the time.time() stamped
    there (the clock bridge).  `flight` defaults to the process flight
    recorder.  Device planes keep one pid per plane — per-device tracks.
    The flight header + raw events are embedded under the top-level
    "flight" key (chrome ignores it; tools/trace_report.py reads it)."""
    import json as _json

    from .monitor import flight as _flight

    rec = flight if flight is not None else _flight.default_recorder()
    trace_dir = trace_dir if trace_dir is not None else _trace_dir
    epoch = (trace_start_epoch if trace_start_epoch is not None
             else _trace_start_epoch)
    fl_events = rec.events()
    if epoch is None:
        # no trace session: host-only timeline anchored at the first event
        spans = [e["t0"] for e in fl_events if "t0" in e]
        epoch = min(spans) if spans else (
            min((e.get("ts", 0.0) for e in fl_events), default=0.0))

    events = _flight_chrome_events(fl_events, epoch)
    if trace_dir:
        events += _xplane_chrome_events(trace_dir, max_events)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ns",
        "flight": {
            "header": rec.header("unified_trace"),
            "trace_start_epoch": epoch,
            "events": fl_events,
        },
    }
    from .monitor.registry import _json_safe

    with open(out_path, "w") as f:
        _json.dump(_json_safe(doc), f)
    return len(events)
