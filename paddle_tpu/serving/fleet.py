"""Replica fleet lifecycle: spawn, crash-restart, rolling restart.

The Router (serving/router.py) decides where requests GO; the
ReplicaSupervisor here decides what EXISTS to send them to.  It owns N
`python -m paddle_tpu.serving` subprocesses (each on an ephemeral port,
discovered from the CLI's machine-readable ready line), registers them
with the router, and enforces two availability contracts:

  * Crash restart — a replica that exits unexpectedly (OOM-kill,
    preemption, chaos SIGKILL) is respawned with capped exponential
    backoff; `router.replica_restarts_total` counts them and a
    `router.replica_restart` flight event names the exit code.  The
    router meanwhile evicts the dead port via its probe machinery, so
    the restart races nothing.
  * Rolling restart with zero downtime — one replica at a time: mark it
    draining AT THE ROUTER first (no request races the signal), SIGTERM
    (the ISSUE-13 graceful-drain contract: in-flight work completes,
    exit 0), respawn against the SAME FLAGS_serving_cache_dir so warmup
    replays compiled executables out of the persistent cache instead of
    recompiling, wait for the ready line AND a passing router probe,
    then move on.  At every instant N-1 replicas take traffic.

Stdlib-only (subprocess + threads), imports no jax: the supervisor is a
control plane, the replicas are the data plane.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .router import IN_ROTATION, Router

_READY_EVENTS = ("serving_ready",)


class _ReplicaProc:
    """One replica subprocess + its pipe-drain bookkeeping."""

    def __init__(self, rid: str, proc: subprocess.Popen):
        self.rid = rid
        self.proc = proc
        self.port: Optional[int] = None
        self.ready = threading.Event()
        self.spawned_at = time.monotonic()
        self.stderr_tail: "collections.deque" = collections.deque(
            maxlen=50)
        # the CLI writes ONE ready line to stdout; both pipes must be
        # drained forever regardless (a full 64KB pipe wedges the child)
        threading.Thread(target=self._drain_stdout, daemon=True).start()
        threading.Thread(target=self._drain_stderr, daemon=True).start()

    def _drain_stdout(self) -> None:
        for line in self.proc.stdout:
            if not self.ready.is_set():
                try:
                    msg = json.loads(line)
                    if msg.get("event") in _READY_EVENTS:
                        self.port = int(msg["port"])
                        self.ready.set()
                except (ValueError, KeyError):
                    pass

    def _drain_stderr(self) -> None:
        for line in self.proc.stderr:
            self.stderr_tail.append(line.rstrip("\n"))


class ReplicaSupervisor:
    """Owns N serving replicas and keeps the router's view of them true.

    `replica_args` are the CLI arguments after `python -m
    paddle_tpu.serving` (models, buckets, ...); the supervisor forces
    `--port 0` per spawn and reads the real port from the ready line.
    `env` overlays os.environ for every replica; `per_replica_env[i]`
    overlays one replica (how chaos flags arm exactly one victim)."""

    def __init__(self, replica_args: List[str], n: int,
                 router: Optional[Router] = None,
                 env: Optional[dict] = None,
                 per_replica_env: Optional[Dict[int, dict]] = None,
                 python: Optional[str] = None,
                 cwd: Optional[str] = None,
                 host: str = "127.0.0.1",
                 ready_timeout_s: float = 180.0,
                 restart_base_delay_s: float = 0.5,
                 restart_max_delay_s: float = 10.0):
        args = list(replica_args)
        if "--port" in args:  # the supervisor owns port assignment
            i = args.index("--port")
            del args[i:i + 2]
        self.replica_args = args
        self.n = int(n)
        self.router = router if router is not None else Router(host=host)
        self.env = dict(env or {})
        self.per_replica_env = dict(per_replica_env or {})
        self.python = python or sys.executable
        self.cwd = cwd
        self.host = host
        self.ready_timeout_s = ready_timeout_s
        self.restart_base_delay_s = restart_base_delay_s
        self.restart_max_delay_s = restart_max_delay_s
        self._procs: Dict[str, _ReplicaProc] = {}
        self._restart_counts: Dict[str, int] = {}  # backoff (resettable)
        self._total_restarts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._restarting: set = set()
        self._monitor_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> Router:
        """Spawn the fleet, wait until every replica is ready, register
        each with the router, start crash monitoring.  Returns the
        router (started, serving)."""
        for i in range(self.n):
            rid = f"r{i}"
            self._procs[rid] = self._spawn(rid, i)
        for rid, rp in self._procs.items():
            self._await_ready(rp)
        self.router.start()
        for i in range(self.n):
            rid = f"r{i}"
            self.router.add_replica(self.host, self._procs[rid].port,
                                    rid=rid)
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="paddle-tpu-fleet-monitor",
            daemon=True)
        self._monitor_thread.start()
        return self.router

    def stop(self) -> None:
        self._stopping.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
            self._monitor_thread = None
        with self._lock:
            procs = list(self._procs.values())
        for rp in procs:
            if rp.proc.poll() is None:
                try:
                    rp.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 15.0
        for rp in procs:
            try:
                rp.proc.wait(
                    timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                rp.proc.kill()
                rp.proc.wait(timeout=5.0)
        self.router.stop()

    def replica_port(self, rid: str) -> Optional[int]:
        with self._lock:
            rp = self._procs.get(rid)
            return rp.port if rp is not None else None

    def replica_pid(self, rid: str) -> Optional[int]:
        with self._lock:
            rp = self._procs.get(rid)
            return rp.proc.pid if rp is not None else None

    def restart_count(self, rid: str) -> int:
        """Total crash restarts of this slot over the supervisor's life
        (the backoff counter resets after a stable hour; this doesn't)."""
        with self._lock:
            return self._total_restarts.get(rid, 0)

    # -- spawn plumbing --------------------------------------------------
    def _spawn(self, rid: str, index: int) -> _ReplicaProc:
        env = dict(os.environ)
        env.update(self.env)
        env.update(self.per_replica_env.get(index, {}))
        argv = ([self.python, "-m", "paddle_tpu.serving",
                 "--host", self.host, "--port", "0"]
                + self.replica_args)
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=self.cwd, env=env, text=True)
        return _ReplicaProc(rid, proc)

    def _await_ready(self, rp: _ReplicaProc) -> None:
        if not rp.ready.wait(timeout=self.ready_timeout_s):
            tail = "\n".join(rp.stderr_tail)
            raise RuntimeError(
                f"replica {rp.rid} (pid {rp.proc.pid}) not ready after "
                f"{self.ready_timeout_s}s; stderr tail:\n{tail}")

    # -- crash restart ---------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stopping.wait(0.2):
            with self._lock:
                dead = [
                    (rid, rp) for rid, rp in self._procs.items()
                    if rp.proc.poll() is not None
                    and rid not in self._restarting]
                for rid, _rp in dead:
                    self._restarting.add(rid)
            for rid, rp in dead:
                try:
                    self._restart(rid, rp)
                finally:
                    with self._lock:
                        self._restarting.discard(rid)

    def _restart(self, rid: str, rp: _ReplicaProc) -> None:
        """Respawn a crashed replica with capped exponential backoff.
        A replica that stayed up 60s earns a fresh backoff budget (a
        stable process that finally dies is an incident, not a crash
        loop)."""
        with self._lock:
            if time.monotonic() - rp.spawned_at > 60.0:
                self._restart_counts[rid] = 0
            self._restart_counts[rid] = \
                self._restart_counts.get(rid, 0) + 1
            count = self._restart_counts[rid]
            self._total_restarts[rid] = \
                self._total_restarts.get(rid, 0) + 1
        code = rp.proc.returncode
        from ..monitor import counter, enabled, flight

        if enabled():
            counter("router.replica_restarts_total").inc()
        flight.record("router.replica_restart", replica=rid,
                      exit_code=code, attempt=count)
        delay = min(self.restart_max_delay_s,
                    self.restart_base_delay_s * (2 ** (count - 1)))
        if self._stopping.wait(delay):
            return
        index = int(rid[1:]) if rid[1:].isdigit() else 0
        new_rp = self._spawn(rid, index)
        with self._lock:
            self._procs[rid] = new_rp
        try:
            self._await_ready(new_rp)
        except RuntimeError:
            # not ready in time: leave it; if it exited the monitor loop
            # takes another swing (with a longer backoff)
            return
        self.router.update_replica(rid, self.host, new_rp.port)

    # -- rolling restart -------------------------------------------------
    def rolling_restart(self,
                        drain_timeout_s: float = 30.0,
                        ready_wait_s: Optional[float] = None) -> None:
        """Restart every replica, one at a time, with zero downtime:
        router-drain -> SIGTERM (graceful drain, exit 0) -> respawn
        (same FLAGS_serving_cache_dir: warmup replays the persistent
        compilation cache) -> ready line -> passing probe -> next."""
        from ..monitor import flight

        if ready_wait_s is None:
            ready_wait_s = self.ready_timeout_s
        for i in range(self.n):
            rid = f"r{i}"
            with self._lock:
                rp = self._procs.get(rid)
                if rp is None:
                    continue
                self._restarting.add(rid)  # the crash monitor stands down
            try:
                flight.record("router.rolling_restart", replica=rid,
                              phase="drain")
                self.router.set_draining(rid)
                if rp.proc.poll() is None:
                    rp.proc.send_signal(signal.SIGTERM)
                    try:
                        rc = rp.proc.wait(timeout=drain_timeout_s + 10.0)
                    except subprocess.TimeoutExpired:
                        rp.proc.kill()
                        rc = rp.proc.wait(timeout=5.0)
                    if rc != 0:
                        flight.record("router.rolling_restart",
                                      replica=rid, phase="dirty_exit",
                                      exit_code=rc)
                new_rp = self._spawn(rid, i)
                with self._lock:
                    self._procs[rid] = new_rp
                self._await_ready(new_rp)
                self.router.update_replica(rid, self.host, new_rp.port)
                deadline = time.monotonic() + ready_wait_s
                while (self.router.replica_state(rid) != IN_ROTATION
                       and time.monotonic() < deadline):
                    self.router.probe_now(rid)
                    time.sleep(0.05)
                if self.router.replica_state(rid) != IN_ROTATION:
                    raise RuntimeError(
                        f"replica {rid} not back in rotation after "
                        f"{ready_wait_s}s (state "
                        f"{self.router.replica_state(rid)})")
                flight.record("router.rolling_restart", replica=rid,
                              phase="readmitted")
            finally:
                with self._lock:
                    self._restarting.discard(rid)
