"""WMT14 En-Fr translation dataset (reference:
python/paddle/dataset/wmt14.py — pre-tokenized parallel corpus with
train/test readers yielding (src_ids, trg_ids, trg_next_ids) and
get_dict(dict_size); the machine_translation book model's data).

Offline fallback: the same deterministic synthetic transduction scheme as
wmt16 (token-wise affine map), so seq2seq + attention genuinely learns."""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

_TOTAL_VOCAB = 30000
START, END, UNK = 0, 1, 2
START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"


def get_dict(dict_size, reverse=True, synthetic=True):
    """word dicts (src, trg) (reference wmt14.py:156; reverse=True returns
    id->word)."""
    dict_size = min(dict_size, _TOTAL_VOCAB)
    src = {START_MARK: START, END_MARK: END, UNK_MARK: UNK}
    trg = dict(src)
    for i in range(3, dict_size):
        src[f"en{i}"] = i
        trg[f"fr{i}"] = i
    if reverse:
        return ({v: k for k, v in src.items()},
                {v: k for k, v in trg.items()})
    return src, trg


def _reader(seed, n_samples, dict_size, synthetic):
    def reader():
        if not common.use_synthetic(synthetic):
            raise RuntimeError(
                "wmt14: real-corpus mode needs the tar at the dataset "
                "cache path (zero-egress image) — use synthetic=True")
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            ln = int(rng.randint(4, 16))
            # the target is a deterministic chain keyed by the source's
            # first token: trg[0] = key, trg[t] = 3 + (trg[t-1] + key) % m.
            # An encoder-final-state + teacher-forced decoder can learn
            # this EXACTLY (the state only needs to carry the key), so
            # beam decode reproduces the full target — unlike a
            # per-position src map, which a no-attention decoder cannot
            # represent.
            src = rng.randint(3, dict_size, ln)
            key = int(src[0])
            m = dict_size - 3
            trg = [key]
            for _ in range(ln - 1):
                trg.append(3 + (trg[-1] + key) % m)
            yield ([START] + src.tolist() + [END],
                   [START] + trg,
                   trg + [END])
    return reader


def synthetic_target(src_ids, dict_size):
    """The ground-truth target chain for a synthetic source (test hook)."""
    key = int(src_ids[0])
    m = dict_size - 3
    trg = [key]
    for _ in range(len(src_ids) - 1):
        trg.append(3 + (trg[-1] + key) % m)
    return trg


def train(dict_size, synthetic=True, n_samples=2000):
    return _reader(61, n_samples, dict_size, synthetic)


def test(dict_size, synthetic=True, n_samples=200):
    return _reader(62, n_samples, dict_size, synthetic)
