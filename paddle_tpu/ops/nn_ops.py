"""NN ops: conv, pool, norms, softmax/losses, embedding, dropout.

Reference parity: conv_op.cc / conv_cudnn_op.cu.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc, softmax_op.cc,
softmax_with_cross_entropy_op.cu, cross_entropy_op.cc, lookup_table_op.{cc,h},
dropout_op.cc, lrn_op.cc.  TPU-first notes:

  * conv2d lowers to `lax.conv_general_dilated`; XLA maps it onto the MXU and
    picks layouts itself — the cuDNN/MKLDNN kernel forks and exhaustive algo
    search of the reference are unnecessary by design.
  * batch_norm keeps the reference's stateful contract (running mean/variance
    passed in and written back) but functionally: the executor threads the
    updated stats back into the Scope.
  * dropout has an explicit grad op using the saved Mask (the reference does
    the same, dropout_op.cc) — required because the generic vjp grad re-traces
    the forward and would re-draw randomness.
"""

from __future__ import annotations

import numpy as np

from ..core import framework as fw
from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# conv2d
# ---------------------------------------------------------------------------


def _conv_infer(ctx):
    xs = ctx.input_shape("Input")
    ws = ctx.input_shape("Filter")
    if xs is None or ws is None:
        return
    strides = ctx.attr("strides", [1, 1])
    paddings = ctx.attr("paddings", [0, 0])
    dilations = ctx.attr("dilations", [1, 1])
    nhwc = ctx.attr("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, _ = xs
    else:
        n, _, h, w = xs
    oc, _, kh, kw = ws
    oh = (h + 2 * paddings[0] - (dilations[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (w + 2 * paddings[1] - (dilations[1] * (kw - 1) + 1)) // strides[1] + 1
    out = (n, oh, ow, oc) if nhwc else (n, oc, oh, ow)
    ctx.set_output("Output", out, ctx.input_dtype("Input"))


@register("conv2d", infer_shape=_conv_infer)
def lower_conv2d(ctx, ins):
    """data_format NHWC runs the MXU-preferred channel-last layout (the
    filter param stays OIHW for checkpoint compatibility; XLA folds the
    spec difference into its layout assignment — measured ~18% faster for
    ResNet-style conv chains on v5e than NCHW)."""
    import jax.lax as lax

    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(ctx.attr("strides", [1, 1]))
    p = ctx.attr("paddings", [0, 0])
    dilations = tuple(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    fmt = ctx.attr("data_format", "NCHW")
    dn = (fmt, "OIHW", fmt)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register("depthwise_conv2d", infer_shape=_conv_infer)
def lower_depthwise_conv2d(ctx, ins):
    import jax.lax as lax

    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(ctx.attr("strides", [1, 1]))
    p = ctx.attr("paddings", [0, 0])
    dilations = tuple(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", x.shape[1])
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register("conv2d_transpose")
def lower_conv2d_transpose(ctx, ins):
    """Transpose conv as input-dilated conv (supports groups, which
    lax.conv_transpose does not).  Filter layout [C_in, C_out/g, kh, kw]
    (reference conv_transpose_op.cc IOHW convention)."""
    import jax.lax as lax

    jnp = _jnp()
    x, w = ins["Input"][0], ins["Filter"][0]
    s = ctx.attr("strides", [1, 1])
    p = ctx.attr("paddings", [0, 0])
    d = ctx.attr("dilations", [1, 1])
    g = ctx.attr("groups", 1) or 1
    c_in, co_g, kh, kw = w.shape
    # [C_in, C_out/g, kh, kw] -> grouped OIHW [C_out, C_in/g, kh, kw], flipped
    w2 = w.reshape(g, c_in // g, co_g, kh, kw)
    w2 = jnp.transpose(w2, (0, 2, 1, 3, 4)).reshape(g * co_g, c_in // g, kh, kw)
    w2 = jnp.flip(w2, axis=(-2, -1))
    pad_h = d[0] * (kh - 1) - p[0]
    pad_w = d[1] * (kw - 1) - p[1]
    out = lax.conv_general_dilated(
        x,
        w2,
        window_strides=(1, 1),
        padding=[(pad_h, pad_h), (pad_w, pad_w)],
        lhs_dilation=tuple(s),
        rhs_dilation=tuple(d),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=g,
    )
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# pool2d
# ---------------------------------------------------------------------------


def _pool_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is None:
        return
    nhwc = ctx.attr("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, c = xs
    else:
        n, c, h, w = xs
    if ctx.attr("global_pooling", False):
        out = (n, 1, 1, c) if nhwc else (n, c, 1, 1)
        ctx.set_output("Out", out, ctx.input_dtype("X"))
        return
    k = ctx.attr("ksize", [2, 2])
    s = ctx.attr("strides", [1, 1])
    p = ctx.attr("paddings", [0, 0])
    if ctx.attr("ceil_mode", False):
        oh = int(np.ceil((h - k[0] + 2 * p[0]) / s[0])) + 1
        ow = int(np.ceil((w - k[1] + 2 * p[1]) / s[1])) + 1
    else:
        oh = (h - k[0] + 2 * p[0]) // s[0] + 1
        ow = (w - k[1] + 2 * p[1]) // s[1] + 1
    out = (n, oh, ow, c) if nhwc else (n, c, oh, ow)
    ctx.set_output("Out", out, ctx.input_dtype("X"))


@register("pool2d", infer_shape=_pool_infer)
def lower_pool2d(ctx, ins):
    import jax.lax as lax

    jnp = _jnp()
    x = ins["X"][0]
    ptype = ctx.attr("pooling_type", "max")
    nhwc = ctx.attr("data_format", "NCHW") == "NHWC"
    sp = (1, 2) if nhwc else (2, 3)
    if ctx.attr("global_pooling", False):
        if ptype == "max":
            return {"Out": [jnp.max(x, axis=sp, keepdims=True)]}
        return {"Out": [jnp.mean(x, axis=sp, keepdims=True)]}
    k = ctx.attr("ksize", [2, 2])
    s = ctx.attr("strides", [1, 1])
    p = ctx.attr("paddings", [0, 0])
    if nhwc:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        padding = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    else:
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        padding = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides, padding)
    else:
        ssum = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if ctx.attr("exclusive", True) and (p[0] or p[1]):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
            out = ssum / counts
        else:
            out = ssum / (k[0] * k[1])
    return {"Out": [out]}


@register("adaptive_pool2d")
def lower_adaptive_pool2d(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    oh, ow = ctx.attr("pooling_size", ctx.attr("ksize", [1, 1]))
    n, c, h, w = x.shape
    # static adaptive pooling: only even-division supported (TPU static shapes)
    assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible sizes"
    xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if ctx.attr("pooling_type", "avg") == "max":
        return {"Out": [jnp.max(xr, axis=(3, 5))]}
    return {"Out": [jnp.mean(xr, axis=(3, 5))]}


# ---------------------------------------------------------------------------
# Norms
# ---------------------------------------------------------------------------


def _same_shape_infer(out_slot="Y", in_slot="X"):
    def infer(ctx):
        xs = ctx.input_shape(in_slot)
        if xs is not None:
            ctx.set_output(out_slot, xs, ctx.input_dtype(in_slot))

    return infer


_bn_infer = _same_shape_infer("Y")
_out_infer = _same_shape_infer("Out")


@register("batch_norm", infer_shape=_bn_infer)
def lower_batch_norm(ctx, ins):
    """reference: batch_norm_op.cc.  Stateful contract preserved: MeanOut/
    VarianceOut (same var names as Mean/Variance inputs) are returned and the
    executor writes them back to the Scope."""
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    layout = ctx.attr("data_layout", "NCHW")
    is_test = ctx.attr("is_test", False) or ctx.is_test
    use_global = ctx.attr("use_global_stats", False) or is_test

    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[1 if layout == "NCHW" else -1] = x.shape[1 if layout == "NCHW" else -1]

    # Mixed precision: statistics accumulate in fp32 even when x is bf16
    # (bf16's 8-bit mantissa loses too much in large reductions); the
    # normalization itself is folded to a per-channel scale/shift applied in
    # x's dtype, so a bf16 conv->bn->relu chain stays bf16 and XLA fuses it.
    stat_dtype = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype

    # Fused route (FLAGS_fused_bn, NHWC training): one-pass Pallas
    # channel-stats kernel + fused apply whose custom VJP folds the
    # dgamma/dbeta reductions into the dx pass (kernels/conv_bn.py) —
    # same math, same fp32 stat accumulation, same stateful contract.
    from ..flags import FLAGS as _FLAGS

    fused = (not use_global and _FLAGS.fused_bn and layout == "NHWC"
             and x.ndim == 4
             and x.dtype in (jnp.float32, jnp.bfloat16))
    if fused:
        from ..kernels import conv_bn as _cbn

        n_count = 1
        for s in x.shape[:-1]:
            n_count *= int(s)
        s1, s2 = _cbn.channel_stats(x)
        mean = s1 / n_count
        var = s2 / n_count - jnp.square(mean)
        m = jax.lax.stop_gradient(mean)
        v = jax.lax.stop_gradient(var)
        mean_out = mean_in * momentum + m * (1 - momentum)
        var_out = var_in * momentum + v * (1 - momentum)
        y = _cbn.bn_apply(x, scale, bias, mean, var, eps=eps)
        return {
            "Y": [y],
            "MeanOut": [mean_out],
            "VarianceOut": [var_out],
            "SavedMean": [m],
            "SavedVariance": [v],
        }

    if use_global:
        mean, var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
        saved_mean, saved_var = mean_in, var_in
    else:
        xs = x.astype(stat_dtype)
        mean = jnp.mean(xs, axis=axes)
        var = jnp.mean(jnp.square(xs), axis=axes) - jnp.square(mean)
        m = jax.lax.stop_gradient(mean)
        v = jax.lax.stop_gradient(var)
        mean_out = mean_in * momentum + m * (1 - momentum)
        var_out = var_in * momentum + v * (1 - momentum)
        saved_mean, saved_var = m, v

    inv_std = jax.lax.rsqrt(var.astype(stat_dtype) + eps)
    w = scale.astype(stat_dtype) * inv_std                    # [C]
    b = bias.astype(stat_dtype) - mean.astype(stat_dtype) * w  # [C]
    y = x * w.astype(x.dtype).reshape(bshape) + b.astype(x.dtype).reshape(bshape)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


def _conv_bn_infer(ctx):
    xs = ctx.input_shape("Input")
    ws = ctx.input_shape("Filter")
    if xs is None or ws is None:
        return
    strides = ctx.attr("strides", [1, 1])
    paddings = ctx.attr("paddings", [0, 0])
    dilations = ctx.attr("dilations", [1, 1])
    nhwc = ctx.attr("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, _ = xs
    else:
        n, _, h, w = xs
    oc, _, kh, kw = ws
    oh = (h + 2 * paddings[0] - (dilations[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (w + 2 * paddings[1] - (dilations[1] * (kw - 1) + 1)) // strides[1] + 1
    out = (n, oh, ow, oc) if nhwc else (n, oc, oh, ow)
    ctx.set_output("Y", out, ctx.input_dtype("Input"))
    stat_dtype = ctx.input_dtype("Mean") or ctx.input_dtype("Input")
    for slot in ("SavedMean", "SavedVariance"):
        ctx.set_output(slot, (oc,), stat_dtype)


@register("conv2d_bn", infer_shape=_conv_bn_infer)
def lower_conv2d_bn(ctx, ins):
    """Fused conv2d + batch_norm [+ residual add] [+ ReLU] — ONE op for
    the conv->bn[->add->relu] chains the models emit under FLAGS_fused_bn
    (layers/nn.py conv2d_bn; kernels/conv_bn.py).

    Contract: the batch_norm op's stateful contract is preserved verbatim
    — MeanOut/VarianceOut (same var names as the Mean/Variance inputs)
    are returned and the executor writes them back to the Scope; Saved*
    carry the batch statistics.  The conv is bias-free (reference resnet
    conv_bn_layer convention: the BN shift subsumes the bias).

    Fused lowering (training, NHWC): kernels/conv_bn.py conv_bn_stats
    (1x1 convs as a dot with a per-channel sum/sum² epilogue — the conv
    output is never re-read from HBM for statistics; other shapes keep
    XLA's conv with the one-pass stats kernel) + bn_apply (normalize +
    scale/shift + residual + ReLU in one read, backward folds the
    dgamma/dbeta reductions into the dx pass).  Inference/use_global,
    NCHW, or FLAGS_fused_bn off at trace time: the reference XLA
    composition, numerically identical to the unfused op chain."""
    import jax
    import jax.lax as lax

    jnp = _jnp()
    from ..flags import FLAGS

    x, w = ins["Input"][0], ins["Filter"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    residual = ins["Residual"][0] if ins.get("Residual") else None
    strides = tuple(ctx.attr("strides", [1, 1]))
    p = ctx.attr("paddings", [0, 0])
    dil = tuple(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    fmt = ctx.attr("data_format", "NHWC")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    act = ctx.attr("act", "") or ""
    if act not in ("", "relu"):
        raise ValueError(f"conv2d_bn: unsupported act {act!r}")
    is_test = ctx.attr("is_test", False) or ctx.is_test
    use_global = ctx.attr("use_global_stats", False) or is_test

    fused = (not use_global and FLAGS.fused_bn and fmt == "NHWC"
             and x.dtype in (jnp.float32, jnp.bfloat16))
    if fused:
        from ..kernels import conv_bn as _cbn

        y, s1, s2 = _cbn.conv_bn_stats(x, w, strides, p, dil, groups)
        n_count = 1
        for s in y.shape[:-1]:
            n_count *= int(s)
        mean = s1 / n_count
        var = s2 / n_count - jnp.square(mean)
        m = jax.lax.stop_gradient(mean)
        v = jax.lax.stop_gradient(var)
        mean_out = mean_in * momentum + m * (1 - momentum)
        var_out = var_in * momentum + v * (1 - momentum)
        out = _cbn.bn_apply(y, scale, bias, mean, var, residual=residual,
                            eps=eps, act=act)
        return {"Y": [out], "MeanOut": [mean_out], "VarianceOut": [var_out],
                "SavedMean": [m], "SavedVariance": [v]}

    # reference XLA composition (inference/use_global, NCHW, or flag off
    # at trace time): conv + folded scale/shift (+residual) (+relu) —
    # XLA fuses the epilogue chain into one elementwise pass
    y = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=dil,
        dimension_numbers=(fmt, "OIHW", fmt),
        feature_group_count=groups,
    )
    caxis = 1 if fmt == "NCHW" else y.ndim - 1
    stat_dtype = jnp.float32 if y.dtype == jnp.bfloat16 else y.dtype
    axes = tuple(i for i in range(y.ndim) if i != caxis)
    bshape = [1] * y.ndim
    bshape[caxis] = y.shape[caxis]
    if use_global:
        mean, var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
        m, v = mean_in, var_in
    else:
        ys = y.astype(stat_dtype)
        mean = jnp.mean(ys, axis=axes)
        var = jnp.mean(jnp.square(ys), axis=axes) - jnp.square(mean)
        m = jax.lax.stop_gradient(mean)
        v = jax.lax.stop_gradient(var)
        mean_out = mean_in * momentum + m * (1 - momentum)
        var_out = var_in * momentum + v * (1 - momentum)
    inv_std = jax.lax.rsqrt(var.astype(stat_dtype) + eps)
    wv = scale.astype(stat_dtype) * inv_std
    bv = bias.astype(stat_dtype) - mean.astype(stat_dtype) * wv
    out = (y * wv.astype(y.dtype).reshape(bshape)
           + bv.astype(y.dtype).reshape(bshape))
    if residual is not None:
        out = out + residual.astype(out.dtype)
    if act == "relu":
        out = jax.nn.relu(out)
    return {"Y": [out], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [m], "SavedVariance": [v]}


def layer_norm_core(x, scale, bias, axis, eps):
    """Shared layer-norm math (also used by fused_layer_norm_gelu).

    Mixed precision: statistics in fp32 even for bf16 inputs (mantissa loss
    in the row reductions otherwise); the result is cast back to x's dtype so
    bf16 residual streams stay bf16 end to end."""
    import jax

    jnp = _jnp()
    stat_dtype = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype
    xs = x.astype(stat_dtype)
    axes = tuple(range(axis, x.ndim))
    mean = jnp.mean(xs, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xs - mean), axis=axes, keepdims=True)
    y = (xs - mean) * jax.lax.rsqrt(var + eps)
    norm_shape = (1,) * axis + x.shape[axis:]
    if scale is not None:
        y = y * scale.astype(stat_dtype).reshape(norm_shape)
    if bias is not None:
        y = y + bias.astype(stat_dtype).reshape(norm_shape)
    return y.astype(x.dtype), mean, var


@register("layer_norm", infer_shape=_bn_infer)
def lower_layer_norm(ctx, ins):
    """reference: layer_norm_op.cc; normalizes over dims >= begin_norm_axis."""
    x = ins["X"][0]
    axis = ctx.attr("begin_norm_axis", 1)
    y, mean, var = layer_norm_core(
        x,
        ins.get("Scale", [None])[0],
        ins.get("Bias", [None])[0],
        axis,
        ctx.attr("epsilon", 1e-5),
    )
    return {
        "Y": [y],
        "Mean": [mean.reshape(x.shape[:axis])],
        "Variance": [var.reshape(x.shape[:axis])],
    }


@register("group_norm")
def lower_group_norm(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    groups = ctx.attr("groups")
    eps = ctx.attr("epsilon", 1e-5)
    n, c, h, w = x.shape
    xg = x.reshape(n, groups, c // groups, h, w)
    mean = jnp.mean(xg, axis=(2, 3, 4), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(2, 3, 4), keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    scale = ins.get("Scale", [None])[0]
    bias = ins.get("Bias", [None])[0]
    if scale is not None:
        y = y * scale.reshape(1, c, 1, 1)
    if bias is not None:
        y = y + bias.reshape(1, c, 1, 1)
    return {
        "Y": [y],
        "Mean": [mean.reshape(n, groups)],
        "Variance": [var.reshape(n, groups)],
    }


@register("lrn")
def lower_lrn(ctx, ins):
    import jax.lax as lax

    jnp = _jnp()
    x = ins["X"][0]
    n_size = ctx.attr("n", 5)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    k = ctx.attr("k", 1.0)
    sq = jnp.square(x)
    half = n_size // 2
    acc = lax.reduce_window(
        sq, 0.0, lax.add, (1, n_size, 1, 1), (1, 1, 1, 1), ((0, 0), (half, half), (0, 0), (0, 0))
    )
    mid = jnp.power(k + alpha * acc, beta)
    return {"Out": [x / mid], "MidOut": [mid]}


@register("norm")
def lower_norm(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", 1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


# ---------------------------------------------------------------------------
# Softmax & losses
# ---------------------------------------------------------------------------


@register("softmax", infer_shape=_out_infer)
def lower_softmax(ctx, ins):
    import jax

    return {"Out": [jax.nn.softmax(ins["X"][0], axis=ctx.attr("axis", -1))]}


@register("log_softmax")
def lower_log_softmax(ctx, ins):
    import jax

    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=ctx.attr("axis", -1))]}


def _take_label(logp, label):
    """Pick -log p[label] along the last axis; label has trailing dim 1."""
    jnp = _jnp()
    lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
    picked = jnp.take_along_axis(logp, lbl[..., None].astype("int32"), axis=-1)
    return -picked


def _ce_loss_infer(ctx):
    """Loss-shaped output: X.shape[:-1] + (1,) — the trailing singleton
    the reference's CE family keeps (declared so the memory planner and
    the shape-contract re-inference see real bytes, not None)."""
    xs = ctx.input_shape("X")
    if xs is not None:
        ctx.set_output("Y", tuple(xs[:-1]) + (1,), ctx.input_dtype("X"))


def _swce_infer(ctx):
    ls = ctx.input_shape("Logits")
    if ls is not None:
        ctx.set_output("Softmax", ls)
        ctx.set_output("Loss", tuple(ls[:-1]) + (1,))


@register("softmax_with_cross_entropy", infer_shape=_swce_infer)
def lower_softmax_with_ce(ctx, ins):
    """Fused stable softmax+CE (reference: softmax_with_cross_entropy_op.cu).

    Mixed-precision inside: the max-shift stays in the logits dtype (bf16
    under AMP — this op is deliberately NOT on the AMP black list, which
    would materialize an fp32 copy of the whole [N, V] logits; at
    transformer-base vocab that is ~2 GB of HBM traffic per step), while
    the exp-sum reduction and the loss accumulate in fp32.  The Softmax
    output is an expression XLA dead-code-eliminates when unused (training
    consumes only Loss)."""
    import jax

    jnp = _jnp()
    logits, label = ins["Logits"][0], ins["Label"][0]
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    # cast BEFORE exp: fp32 exp terms feed the fp32 accumulation (the cast
    # fuses into the reduction — no [N, V] fp32 buffer materializes)
    sumexp = jnp.sum(
        jnp.exp(shifted.astype(jnp.float32)), axis=-1, keepdims=True)
    log_z = jnp.log(sumexp)  # [N, 1] fp32
    softmax = (jnp.exp(shifted.astype(jnp.float32)) / sumexp).astype(
        logits.dtype)
    if ctx.attr("soft_label", False):
        # logp materializes only on this (rare) path
        logp = shifted.astype(jnp.float32) - log_z
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=-1,
                        keepdims=True)
    else:
        ignore = ctx.attr("ignore_index", -100)
        label_shifted = _take_label(shifted, label)  # -> -label_logit
        loss = log_z + label_shifted.astype(jnp.float32)
        if ignore >= 0:
            lbl = label.reshape(loss.shape)
            loss = jnp.where(lbl == ignore, 0.0, loss)
    return {"Softmax": [softmax], "Loss": [loss]}


@register("cross_entropy", infer_shape=_ce_loss_infer)
def lower_cross_entropy(ctx, ins):
    jnp = _jnp()
    x, label = ins["X"][0], ins["Label"][0]
    logp = jnp.log(jnp.clip(x, 1e-12, None))
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        loss = _take_label(logp, label)
        ignore = ctx.attr("ignore_index", -100)
        if ignore >= 0:
            lbl = label.reshape(loss.shape)
            loss = jnp.where(lbl == ignore, 0.0, loss)
    return {"Y": [loss]}


@register("sigmoid_cross_entropy_with_logits")
def lower_sigmoid_ce(ctx, ins):
    jnp = _jnp()
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    if ignore >= 0:
        loss = jnp.where(label == ignore, 0.0, loss)
    if ctx.attr("normalize", False):
        n_valid = jnp.sum((label != ignore).astype(loss.dtype))
        loss = loss / jnp.maximum(n_valid, 1.0)
    return {"Out": [loss]}


@register("square_error_cost")
def lower_square_error_cost(ctx, ins):
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register("huber_loss")
def lower_huber_loss(ctx, ins):
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * jnp.square(r), delta * (ar - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register("log_loss")
def lower_log_loss(ctx, ins):
    jnp = _jnp()
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = ctx.attr("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register("hinge_loss")
def lower_hinge_loss(ctx, ins):
    jnp = _jnp()
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2 * labels - 1) * logits)]}


@register("margin_rank_loss")
def lower_margin_rank_loss(ctx, ins):
    jnp = _jnp()
    label, left, right = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (left - right) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(left.dtype)]}


@register("bpr_loss")
def lower_bpr_loss(ctx, ins):
    jnp = _jnp()
    x, label = ins["X"][0], ins["Label"][0]
    lbl = label.reshape(label.shape[0])
    pos = jnp.take_along_axis(x, lbl[:, None].astype("int32"), axis=1)
    diff = x - pos
    loss = jnp.mean(jnp.log1p(jnp.exp(diff)), axis=1, keepdims=True)
    return {"Y": [loss]}


# ---------------------------------------------------------------------------
# Embedding (reference: lookup_table_op.{cc,h} — the sparse-CTR workhorse)
# ---------------------------------------------------------------------------


def _lookup_infer(ctx):
    ws = ctx.input_shape("W")
    ids = ctx.input_shape("Ids")
    if ws is None or ids is None:
        return
    base = ids[:-1] if ids and ids[-1] == 1 else ids
    ctx.set_output("Out", tuple(base) + (ws[-1],), ctx.input_dtype("W"))


def _note_embed_stats(ctx, launches, rows):
    """Trace-time sparse-tier telemetry: accumulate gather-launch / rows-
    touched counts on the TraceContext (published once per traced step as
    `embedding.*` gauges by trace_block — see core/executor.py).  One
    monitor-enabled flag read at TRACE time; the run hot path never sees
    this, and eager contexts (no TraceContext accumulator) skip."""
    from .. import monitor

    if not monitor.enabled():
        return
    stats = getattr(ctx.executor_ctx, "embed_stats", None)
    if stats is not None:
        stats["gather_launches"] += launches
        stats["sparse_rows_touched"] += rows


def _lookup_table_grad_maker(op, no_grad_set, grad_sub_block_map=None):
    """Sparse-aware grad: emits lookup_table_grad producing a row-sparse
    gradient (SelectedRows parity, lookup_table_op.h:132) when is_sparse."""
    g_w = fw.grad_var_name(op.input("W")[0])
    if op.input("W")[0] in no_grad_set:
        return []
    return [
        {
            "type": "lookup_table_grad",
            "inputs": {
                "Ids": op.input("Ids"),
                "W": op.input("W"),
                "Out@GRAD": [fw.grad_var_name(n) for n in op.output("Out")],
            },
            "outputs": {"W@GRAD": [g_w]},
            "attrs": dict(op.attrs, **{fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward}),
        }
    ]


@register("lookup_table", infer_shape=_lookup_infer, grad_maker=_lookup_table_grad_maker)
def lower_lookup_table(ctx, ins):
    jnp = _jnp()
    w, ids = ins["W"][0], ins["Ids"][0]
    idshape = ids.shape
    flat = ids.reshape(-1).astype("int32")
    out = jnp.take(w, flat, axis=0)
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (flat != padding_idx)[:, None]
        out = out * mask.astype(out.dtype)
    base = idshape[:-1] if idshape and idshape[-1] == 1 else idshape
    _note_embed_stats(ctx, 1, int(flat.shape[0]))
    return {"Out": [out.reshape(tuple(base) + (w.shape[-1],))]}


@register("lookup_table_grad", no_grad=True)
def lower_lookup_table_grad(ctx, ins):
    """Embedding gradient (reference lookup_table_op.h:132).

    is_sparse=True: returns a SelectedRows (ids, rows) pair — O(batch)
    memory, consumed by the sparse variants of the optimizer ops, parity
    with the reference's SelectedRows grad + SparseAdagrad/SparseAdam
    functors (adagrad_op.h:24).
    is_sparse=False: dense scatter-add into a zeros_like(W) tensor
    (O(vocab) — fine for small tables)."""
    from ..core.selected_rows import SelectedRows

    w = ins["W"][0]
    ids = ins["Ids"][0].reshape(-1).astype("int32")
    gout = ins["Out@GRAD"][0]
    gout2 = gout.reshape(-1, w.shape[-1])
    jnp = _jnp()
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        gout2 = gout2 * (ids != padding_idx)[:, None].astype(gout2.dtype)
    if ctx.attr("is_sparse", False):
        return {"W@GRAD": [SelectedRows(ids, gout2.astype(w.dtype), w.shape[0])]}
    gw = jnp.zeros_like(w).at[ids].add(gout2.astype(w.dtype))
    return {"W@GRAD": [gw]}


# ---------------------------------------------------------------------------
# Fused multi-table embedding (FLAGS_fused_embedding; passes.py
# `fused_embedding` coalesces per-slot lookup_table ops into these —
# PERF.md round 8, the DeepFM/CTR dispatch-wall attack).  One op gathers
# every slot of a same-shape TABLE GROUP in one Pallas launch
# (kernels/embedding.py); the grad keeps the per-table SelectedRows
# contract so the sparse optimizer tier (fused or per-table) interops
# unchanged.
# ---------------------------------------------------------------------------


def _fused_lookup_infer(ctx):
    n = len(ctx.op.output("Out"))
    for i in range(n):
        ws = ctx.input_shape("W", i)
        ids = ctx.input_shape("Ids", i)
        if ws is None or ids is None:
            continue
        base = ids[:-1] if ids and ids[-1] == 1 else ids
        ctx.set_output("Out", tuple(base) + (ws[-1],),
                       ctx.input_dtype("W", i), i=i)


def _fused_lookup_table_grad_maker(op, no_grad_set, grad_sub_block_map=None):
    ws = op.input("W")
    if all(w in no_grad_set for w in ws):
        return []
    # slots whose table is in no_grad_set keep an empty output name (the
    # executor skips unnamed outputs when binding lowering results)
    g_ws = [("" if w in no_grad_set else fw.grad_var_name(w)) for w in ws]
    return [
        {
            "type": "fused_lookup_table_grad",
            "inputs": {
                "Ids": op.input("Ids"),
                "W": ws,
                "Out@GRAD": [fw.grad_var_name(n) for n in op.output("Out")],
            },
            "outputs": {"W@GRAD": g_ws},
            "attrs": dict(op.attrs, **{fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward}),
        }
    ]


def _stacked_slot_ids(id_vals):
    """The per-slot lowering re-casts int64->int32 and re-reshapes per op;
    the fused path hoists both: ONE [S, B] stack, ONE cast (the
    no-per-slot-convert regression is asserted in
    tests/test_fused_embedding.py)."""
    jnp = _jnp()

    return jnp.stack([i.reshape(-1) for i in id_vals]).astype("int32")


@register("fused_lookup_table", infer_shape=_fused_lookup_infer,
          grad_maker=_fused_lookup_table_grad_maker)
def lower_fused_lookup_table(ctx, ins):
    """Multi-table gather: Ids (S tensors) + W (S same-shape tables) ->
    S outputs, preserving each original lookup_table Out name/shape —
    the graph around a coalesced group never changes.  One Pallas launch
    gathers every slot (ids via scalar prefetch, tables HBM-resident);
    see kernels/embedding.py multi_table_gather."""
    from ..kernels.embedding import multi_table_gather

    id_vals, ws = ins["Ids"], ins["W"]
    ids = _stacked_slot_ids(id_vals)  # [S, B]
    out = multi_table_gather(ws, ids)  # [S, B, D]
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[:, :, None]
        out = out * mask.astype(out.dtype)
    _note_embed_stats(ctx, 1, int(ids.shape[0] * ids.shape[1]))
    outs = []
    for s, (iv, w) in enumerate(zip(id_vals, ws)):
        idshape = iv.shape
        base = idshape[:-1] if idshape and idshape[-1] == 1 else idshape
        outs.append(out[s].reshape(tuple(base) + (w.shape[-1],)))
    return {"Out": outs}


@register("fused_lookup_table_grad", no_grad=True)
def lower_fused_lookup_table_grad(ctx, ins):
    """Group backward, SelectedRows-compatible: is_sparse=True emits the
    IDENTICAL per-table SelectedRows the per-slot path produces (rows ARE
    the cotangent slices — no kernel needed), so sparse optimizers and
    clipping interop unchanged.  is_sparse=False runs the matching
    multi-table scatter-add kernel: duplicate rows merged (batched
    MergeAdd), then ONE launch accumulates every table's dense grad."""
    from ..core.selected_rows import SelectedRows
    from ..kernels.embedding import merge_slot_rows, multi_table_scatter_add

    jnp = _jnp()
    id_vals, ws, gouts = ins["Ids"], ins["W"], ins["Out@GRAD"]
    height = ws[0].shape[0]
    padding_idx = ctx.attr("padding_idx", -1)
    pad = padding_idx is not None and padding_idx >= 0
    if ctx.attr("is_sparse", False):
        grads = []
        for iv, w, gout in zip(id_vals, ws, gouts):
            ids_s = iv.reshape(-1).astype("int32")
            g2 = gout.reshape(-1, w.shape[-1])
            if pad:
                g2 = g2 * (ids_s != padding_idx)[:, None].astype(g2.dtype)
            grads.append(SelectedRows(ids_s, g2.astype(w.dtype), height))
        return {"W@GRAD": grads}
    ids = _stacked_slot_ids(id_vals)
    rows = jnp.stack(
        [g.reshape(-1, w.shape[-1]).astype(w.dtype)
         for w, g in zip(ws, gouts)])
    if pad:
        rows = rows * (ids != padding_idx)[:, :, None].astype(rows.dtype)
    uids, mrows = merge_slot_rows(ids, rows, height)
    zeros = [jnp.zeros_like(w) for w in ws]
    gws = multi_table_scatter_add(zeros, uids, mrows, jnp.float32(1.0))
    return {"W@GRAD": list(gws)}


# ---------------------------------------------------------------------------
# Dropout (explicit grad via saved mask — see module docstring)
# ---------------------------------------------------------------------------


def _dropout_grad_maker(op, no_grad_set, grad_sub_block_map=None):
    x = op.input("X")[0]
    if x in no_grad_set:
        return []
    inputs = {"Out@GRAD": [fw.grad_var_name(n) for n in op.output("Out")]}
    if not op.attrs.get("rng_id"):
        # legacy/programmatic dropout without a static id: mask residual
        inputs["Mask"] = op.output("Mask")
    return [
        {
            "type": "dropout_grad",
            "inputs": inputs,
            "outputs": {"X@GRAD": [fw.grad_var_name(x)]},
            "attrs": dict(op.attrs, **{fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward}),
        }
    ]


@register("dropout", infer_shape=_out_infer, grad_maker=_dropout_grad_maker,
          derives_rng=True)
def lower_dropout(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False) or ctx.is_test
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        mask = jnp.ones_like(x)
        if impl == "downgrade_in_infer":
            return {"Out": [x * (1.0 - p)], "Mask": [mask]}
        return {"Out": [x], "Mask": [mask]}
    keep = _dropout_keep_mask(ctx, jax, x.shape, p)
    # Mask rides to the backward as a 1-byte bool residual (a bf16/f32
    # multiplicative mask doubles the fwd->bwd HBM traffic of every
    # dropout site; measured +1.5% end-to-end on transformer-base)
    scale = 1.0 / (1.0 - p) if impl == "upscale_in_train" else 1.0
    out = jnp.where(keep, x * jnp.asarray(scale, x.dtype),
                    jnp.zeros((), x.dtype))
    return {"Out": [out], "Mask": [keep]}


def _dropout_keep_mask(ctx, jax, shape, p):
    """The keep mask for one dropout op.  With a static rng_id attr the
    mask is a pure function of (step base key, rng_id, element index) —
    fully deterministic within a step, so the BACKWARD op regenerates the
    identical mask instead of reading a saved residual (removes one HBM
    round-trip per dropout site; the fwd->bwd mask residuals cost ~12%
    end-to-end on transformer-base).

    With FLAGS.hash_dropout (default) the generator is the counter-based
    hash of kernels/hash_rng.py: ~10 integer ops over an iota that XLA
    fuses into the consuming select, so no random-bits tensor ever
    exists in HBM (rbg rng-bit-generator is a fusion barrier — its bits
    round-tripped ~2.5 ms/step on transformer-base)."""
    from ..flags import FLAGS
    from ..kernels import hash_rng

    seed = ctx.attr("seed", 0)
    rng_id = ctx.attr("rng_id", 0)
    if seed:
        key = jax.random.PRNGKey(seed)
    elif rng_id:
        base = getattr(ctx.executor_ctx, "base_key", None)
        if base is None:
            base = ctx.executor_ctx._base_key  # eager session
        if FLAGS.hash_dropout:
            return hash_rng.keep_mask(
                hash_rng.seed_from_key(base, rng_id), shape, p)
        key = jax.random.fold_in(base, rng_id)
    else:
        key = ctx.next_rng_key()
    if FLAGS.hash_dropout:
        return hash_rng.keep_mask(
            hash_rng.seed_from_key(key, rng_id or 1), shape, p)
    return jax.random.bernoulli(key, 1.0 - p, shape)


@register("dropout_add", infer_shape=_out_infer, derives_rng=True)
def lower_dropout_add(ctx, ins):
    """Fused dropout(X) + Residual epilogue (kernels/dropout_epilogue.py):
    one Pallas kernel whose keep-mask is regenerated in-kernel from scalar
    seeds (TPU hardware PRNG; lowbias32 hash in interpret/XLA fallbacks) —
    no mask, random-bits tensor, or fwd->bwd residual beyond the seed ever
    exists in HBM.  upscale_in_train semantics (the only mode the bundled
    models use); is_test or rate 0 lowers to a plain add, so dropout-off
    programs are bit-identical to an elementwise_add.

    The backward rides the kernel's custom VJP through the generic
    vjp-of-forward grad path: the re-trace derives the SAME seed from the
    static rng_id attr, so the regenerated mask is bit-exact."""
    x = ins["X"][0]
    res = ins["Residual"][0]
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False) or ctx.is_test
    if is_test or not p:
        return {"Out": [x + res.astype(x.dtype)]}
    import jax

    from ..flags import FLAGS
    from ..kernels import dropout_epilogue, hash_rng

    jnp = _jnp()
    rng_id = ctx.attr("rng_id", 0)
    base = getattr(ctx.executor_ctx, "base_key", None)
    if base is None:
        base = ctx.executor_ctx._base_key  # eager session
    if not FLAGS.hash_dropout:
        # honor the framework-wide generator switch (same contract as
        # _dropout_keep_mask): with hash_dropout off the mask comes from
        # jax.random.bernoulli — deterministic per (step key, rng_id), so
        # the generic-vjp re-trace still regenerates it in the backward
        keep = jax.random.bernoulli(
            jax.random.fold_in(base, rng_id or 1), 1.0 - p, x.shape)
        scaled = jnp.where(keep, x * jnp.asarray(1.0 / (1.0 - p), x.dtype),
                           jnp.zeros((), x.dtype))
        return {"Out": [scaled + res.astype(x.dtype)]}
    seed = hash_rng.seed_from_key(base, rng_id or 1)
    return {"Out": [dropout_epilogue.dropout_add(x, res, p, seed)]}


@register("dropout_grad", no_grad=True)
def lower_dropout_grad(ctx, ins):
    import jax

    jnp = _jnp()
    d = ins["Out@GRAD"][0]
    p = ctx.attr("dropout_prob", 0.5)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    scale = 1.0 / (1.0 - p) if impl == "upscale_in_train" else 1.0
    if ins.get("Mask"):
        mask = ins["Mask"][0]
        if str(mask.dtype) == "bool":
            return {"X@GRAD": [jnp.where(mask,
                                         d * jnp.asarray(scale, d.dtype),
                                         jnp.zeros((), d.dtype))]}
        return {"X@GRAD": [d * mask]}
    keep = _dropout_keep_mask(ctx, jax, d.shape, p)
    return {"X@GRAD": [jnp.where(keep, d * jnp.asarray(scale, d.dtype),
                                 jnp.zeros((), d.dtype))]}


# ---------------------------------------------------------------------------
# prelu / maxout / interpolate
# ---------------------------------------------------------------------------


@register("prelu")
def lower_prelu(ctx, ins):
    jnp = _jnp()
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = ctx.attr("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        a = alpha.reshape((1,) + x.shape[1:])
    return {"Out": [jnp.where(x > 0, x, a * x)]}


@register("maxout")
def lower_maxout(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    return {"Out": [jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2)]}


@register("bilinear_interp")
def lower_bilinear_interp(ctx, ins):
    import jax

    x = ins["X"][0]
    oh = ctx.attr("out_h")
    ow = ctx.attr("out_w")
    n, c, h, w = x.shape
    out = jax.image.resize(x, (n, c, oh, ow), method="bilinear")
    return {"Out": [out]}


@register("nearest_interp")
def lower_nearest_interp(ctx, ins):
    import jax

    x = ins["X"][0]
    oh = ctx.attr("out_h")
    ow = ctx.attr("out_w")
    n, c, h, w = x.shape
    out = jax.image.resize(x, (n, c, oh, ow), method="nearest")
    return {"Out": [out]}


@register("nce", no_grad=False, derives_rng=True)
def lower_nce(ctx, ins):
    """Noise-contrastive estimation loss (reference: operators/nce_op.cc:1,
    nce_op.h ComputeCost).

    Per sample with scores s_c = x.w_c + b_c and uniform noise q = 1/V:
      cost = sum_true -log sigma(s_y - log(k q))
           + sum_{k sampled} -log(1 - sigma(s_i - log(k q)))
    (the sigma(s - log kq) form equals the reference's o/(o + kq)).

    TPU-first: negatives are drawn inside the compiled step from the
    executor's threefry key (reproducible, no host RNG round-trip); only
    true+sampled weight rows are gathered so the [V, d] table never enters
    the matmul.  Dense grads (the reference's is_sparse variant maps to
    SelectedRows — the embedding path covers that pattern).
    Inputs: Input [b,d], Label [b,num_true], Weight [V,d], Bias [V] (opt).
    Output: Cost [b,1].
    """
    import jax
    jnp = _jnp()

    x = ins["Input"][0]
    label = ins["Label"][0]
    w = ins["Weight"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = ctx.attr("num_total_classes", w.shape[0])
    k = ctx.attr("num_neg_samples", 10)

    b = x.shape[0]
    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]
    label = label.astype(jnp.int32)

    samples = jax.random.randint(_nce_key(ctx), (b, k), 0, num_classes)
    cand = jnp.concatenate([label, samples], axis=1)  # [b, num_true + k]

    w_rows = jnp.take(w, cand.reshape(-1), axis=0).reshape(
        b, num_true + k, -1)
    logits = jnp.einsum("bd,bcd->bc", x.astype(jnp.float32),
                        w_rows.astype(jnp.float32))
    if bias is not None:
        logits = logits + jnp.take(
            bias.reshape(-1).astype(jnp.float32), cand.reshape(-1)
        ).reshape(b, num_true + k)
    # uniform sampler correction: log(k * 1/V)
    logits = logits - jnp.log(k / num_classes)
    pos = logits[:, :num_true]
    neg = logits[:, num_true:]
    # -log sigmoid(pos) + -log(1 - sigmoid(neg)), in softplus form
    cost = (jax.nn.softplus(-pos).sum(axis=1)
            + jax.nn.softplus(neg).sum(axis=1))
    if ins.get("SampleWeight"):
        cost = cost * ins["SampleWeight"][0].reshape(-1)
    return {"Cost": [cost[:, None]]}


def _nce_key(ctx):
    import jax

    seed = ctx.attr("seed", 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.next_rng_key()


@register("hierarchical_sigmoid", no_grad=False)
def lower_hierarchical_sigmoid(ctx, ins):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: operators/hierarchical_sigmoid_op.cc:1 +
    math/matrix_bit_code.h).

    Leaf for class c is heap node n = c + V; its ancestors n >> (j+1) (while
    >= 1) index rows of W ([V-1, d]); bit j of n picks the branch.  Loss is
    sum over the path of softplus((1 - 2 bit) * z) with z = x.w_row + b_row
    — all paths are walked at the static max depth with a validity mask, so
    XLA sees one fused [b, L, d] gather+einsum instead of the reference's
    per-sample bit-code loop.
    Inputs: X [b,d], Label [b,1], W [V-1,d], Bias [V-1] (opt).
    Output: Out [b,1] cost.

    CUSTOM TREES (reference custom-tree path, hierarchical_sigmoid_op.cc +
    math/matrix_bit_code.h CustomCode): optional PathTable [b, L] (row ids
    into W along each sample's root->leaf path; negative = padding) and
    PathCode [b, L] (the 0/1 branch codes) replace the heap-derived
    row/bit/valid — same masked-gather evaluation.
    """
    import jax
    jnp = _jnp()

    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    w = ins["W"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = ctx.attr("num_classes", w.shape[0] + 1)

    if ins.get("PathTable"):
        table = ins["PathTable"][0].astype(jnp.int32)
        code = ins["PathCode"][0].astype(jnp.int32)
        if table.ndim == 3:
            table = table[..., 0]
            code = code[..., 0]
        depth = table.shape[1]
        valid = table >= 0
        row = jnp.clip(table, 0, w.shape[0] - 1)
        bit = code
    else:
        n = label + num_classes  # heap leaf id, root = 1
        depth = int(2 * num_classes - 1).bit_length() - 1  # static path len

        js = jnp.arange(depth)
        anc = n[:, None] >> (js[None, :] + 1)          # [b, L]
        valid = anc >= 1
        row = jnp.clip(anc - 1, 0, num_classes - 2)
        bit = (n[:, None] >> js[None, :]) & 1

    w_rows = jnp.take(w, row.reshape(-1), axis=0).reshape(
        label.shape[0], depth, -1)
    z = jnp.einsum("bd,bld->bl", x.astype(jnp.float32),
                   w_rows.astype(jnp.float32))
    if bias is not None:
        z = z + jnp.take(
            bias.reshape(-1).astype(jnp.float32), row.reshape(-1)
        ).reshape(label.shape[0], depth)
    per_node = jax.nn.softplus((1.0 - 2.0 * bit) * z)
    cost = jnp.where(valid, per_node, 0.0).sum(axis=1)
    return {"Out": [cost[:, None]]}


# ---------------------------------------------------------------------------
# conv3d (reference: conv_op.cc Conv3D, vol2col fallback)
# ---------------------------------------------------------------------------


def _conv3d_infer(ctx):
    xs = ctx.input_shape("Input")
    ws = ctx.input_shape("Filter")
    if xs is None or ws is None:
        return
    strides = ctx.attr("strides", [1, 1, 1])
    paddings = ctx.attr("paddings", [0, 0, 0])
    dilations = ctx.attr("dilations", [1, 1, 1])
    n, _, d, h, w = xs
    oc, _, kd, kh, kw = ws

    def out(sz, p, dil, k, s):
        return (sz + 2 * p - (dil * (k - 1) + 1)) // s + 1

    ctx.set_output(
        "Output",
        (n, oc,
         out(d, paddings[0], dilations[0], kd, strides[0]),
         out(h, paddings[1], dilations[1], kh, strides[1]),
         out(w, paddings[2], dilations[2], kw, strides[2])),
        ctx.input_dtype("Input"),
    )


@register("conv3d", infer_shape=_conv3d_infer)
def lower_conv3d(ctx, ins):
    """NCDHW 3-D convolution (reference conv_op.cc:1 Conv3DOpMaker); XLA
    tiles it onto the MXU like conv2d — no vol2col needed."""
    import jax.lax as lax

    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(ctx.attr("strides", [1, 1, 1]))
    p = ctx.attr("paddings", [0, 0, 0])
    dilations = tuple(ctx.attr("dilations", [1, 1, 1]))
    groups = ctx.attr("groups", 1) or 1
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


def _pool3d_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is None:
        return
    if ctx.attr("global_pooling", False):
        ctx.set_output("Out", (xs[0], xs[1], 1, 1, 1),
                       ctx.input_dtype("X"))
        return
    ksize = ctx.attr("ksize", [2, 2, 2])
    strides = ctx.attr("strides", ksize)
    p = ctx.attr("paddings", [0, 0, 0])
    dims = tuple(
        (xs[2 + i] + 2 * p[i] - ksize[i]) // strides[i] + 1
        for i in range(3)
    )
    ctx.set_output("Out", (xs[0], xs[1]) + dims, ctx.input_dtype("X"))


@register("pool3d", infer_shape=_pool3d_infer)
def lower_pool3d(ctx, ins):
    """NCDHW max/avg 3-D pooling (reference pool_op.cc Pool3D)."""
    import jax.lax as lax

    jnp = _jnp()
    x = ins["X"][0]
    ksize = ctx.attr("ksize", [2, 2, 2])
    strides = ctx.attr("strides", ksize)
    p = ctx.attr("paddings", [0, 0, 0])
    ptype = ctx.attr("pooling_type", "max")
    global_pool = ctx.attr("global_pooling", False)
    if global_pool:
        ksize = list(x.shape[2:])
        strides = ksize
        p = [0, 0, 0]
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides_, pads)
    else:
        ones = jnp.ones_like(x)
        s = lax.reduce_window(x, 0.0, lax.add, window, strides_, pads)
        c = lax.reduce_window(ones, 0.0, lax.add, window, strides_, pads)
        out = s / c
    return {"Out": [out]}


@register("spp")
def lower_spp(ctx, ins):
    """Spatial pyramid pooling (reference spp_op.cc + spp_op.h): for level
    l in [0, pyramid_height) pool NCHW input into a 2^l x 2^l grid
    (kernel = ceil(in/bins), pad so kernel*bins covers the padded input,
    stride = kernel — the reference's formula), flatten each level and
    concat -> [N, C * sum(4^l)]."""
    import jax.lax as lax

    jnp = _jnp()
    x = ins["X"][0]
    height = ctx.attr("pyramid_height", 1)
    ptype = ctx.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for level in range(height):
        bins = 2 ** level
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        window = (1, 1, kh, kw)
        strides = (1, 1, kh, kw)
        pads = ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                (pw, kw * bins - w - pw))
        if ptype == "max":
            o = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  pads)
        else:
            ones = jnp.ones_like(x)
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                    pads)
            o = s / cnt
        outs.append(o.reshape(n, c * bins * bins))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register("max_pool3d_with_index")
def lower_max_pool3d_with_index(ctx, ins):
    """3-D max pool returning the flat argmax index within each input
    [D, H, W] map (reference pool_with_index_op.cc MaxPool3dWithIndex)."""
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    ks = ctx.attr("ksize", [2, 2, 2])
    s = ctx.attr("strides", ks)
    p = ctx.attr("paddings", [0, 0, 0])
    if ctx.attr("global_pooling", False):
        ks = list(x.shape[2:])
        s = ks
        p = [0, 0, 0]
    n, c, d, h, w = x.shape
    od = (d + 2 * p[0] - ks[0]) // s[0] + 1
    oh = (h + 2 * p[1] - ks[1]) // s[1] + 1
    ow = (w + 2 * p[2] - ks[2]) // s[2] + 1
    # source coords per output cell: [od,oh,ow,kd,kh,kw]
    zs = (jnp.arange(od) * s[0] - p[0])[:, None, None, None, None, None] + \
        jnp.arange(ks[0])[None, None, None, :, None, None]
    ys = (jnp.arange(oh) * s[1] - p[1])[None, :, None, None, None, None] + \
        jnp.arange(ks[1])[None, None, None, None, :, None]
    xs = (jnp.arange(ow) * s[2] - p[2])[None, None, :, None, None, None] + \
        jnp.arange(ks[2])[None, None, None, None, None, :]
    zs, ys, xs = jnp.broadcast_arrays(zs, ys, xs)
    inb = ((zs >= 0) & (zs < d) & (ys >= 0) & (ys < h)
           & (xs >= 0) & (xs < w))
    zc = jnp.clip(zs, 0, d - 1)
    yc = jnp.clip(ys, 0, h - 1)
    xc = jnp.clip(xs, 0, w - 1)
    vals = x[:, :, zc, yc, xc]              # [N,C,od,oh,ow,kd,kh,kw]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    vals = jnp.where(inb[None, None], vals, neg)
    flat = vals.reshape(n, c, od, oh, ow, -1)
    best = jnp.argmax(flat, axis=-1)
    out = jnp.take_along_axis(flat, best[..., None], axis=-1)[..., 0]
    gidx = (zc * h + yc) * w + xc           # flat index into [d,h,w]
    bidx = jnp.take_along_axis(
        jnp.broadcast_to(gidx[None, None], vals.shape).reshape(
            n, c, od, oh, ow, -1), best[..., None], axis=-1)[..., 0]
    return {"Out": [out], "Mask": [bidx.astype(jnp.int32)]}


@register("conv3d_transpose")
def lower_conv3d_transpose(ctx, ins):
    """3D transpose conv as input-dilated conv (reference
    conv_transpose_op.cc conv3d_transpose; filter [C_in, C_out/g, kd, kh,
    kw])."""
    import jax.lax as lax

    jnp = _jnp()
    x, w = ins["Input"][0], ins["Filter"][0]
    s = ctx.attr("strides", [1, 1, 1])
    p = ctx.attr("paddings", [0, 0, 0])
    d = ctx.attr("dilations", [1, 1, 1])
    g = ctx.attr("groups", 1) or 1
    c_in, co_g, kd, kh, kw = w.shape
    w2 = w.reshape(g, c_in // g, co_g, kd, kh, kw)
    w2 = jnp.transpose(w2, (0, 2, 1, 3, 4, 5)).reshape(
        g * co_g, c_in // g, kd, kh, kw)
    w2 = jnp.flip(w2, axis=(-3, -2, -1))
    pads = [(d[i] * (k - 1) - p[i],) * 2 for i, k in enumerate((kd, kh, kw))]
    out = lax.conv_general_dilated(
        x, w2,
        window_strides=(1, 1, 1),
        padding=pads,
        lhs_dilation=tuple(s),
        rhs_dilation=tuple(d),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=g,
    )
    return {"Output": [out]}


@register("max_pool2d_with_index")
def lower_max_pool2d_with_index(ctx, ins):
    """Max pool that also returns the flat argmax index within each input
    map (reference pool_with_index_op.cc) — the Indices feed unpool."""
    import jax.lax as lax

    jnp = _jnp()
    x = ins["X"][0]
    ks = ctx.attr("ksize", [2, 2])
    s = ctx.attr("strides", ks)
    p = ctx.attr("paddings", [0, 0])
    if ctx.attr("global_pooling", False):
        ks = list(x.shape[2:])
        s = ks
        p = [0, 0]
    n, c, h, w = x.shape
    oh = (h + 2 * p[0] - ks[0]) // s[0] + 1
    ow = (w + 2 * p[1] - ks[1]) // s[1] + 1
    # one gather window per output cell: [oh, ow, kh, kw] source coords
    ys = (jnp.arange(oh) * s[0] - p[0])[:, None, None, None] + \
        jnp.arange(ks[0])[None, None, :, None]
    xs = (jnp.arange(ow) * s[1] - p[1])[None, :, None, None] + \
        jnp.arange(ks[1])[None, None, None, :]
    inb = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
    yc = jnp.clip(ys, 0, h - 1)
    xc = jnp.clip(xs, 0, w - 1)
    vals = x[:, :, yc, xc]                          # [N, C, oh, ow, kh, kw]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    vals = jnp.where(inb[None, None], vals, neg)
    flat = vals.reshape(n, c, oh, ow, -1)
    best = jnp.argmax(flat, axis=-1)
    out = jnp.take_along_axis(flat, best[..., None], axis=-1)[..., 0]
    # flat index into the ORIGINAL [h, w] map (reference convention)
    by = jnp.take_along_axis(
        jnp.broadcast_to(yc[None, None], vals.shape).reshape(
            n, c, oh, ow, -1), best[..., None], axis=-1)[..., 0]
    bx = jnp.take_along_axis(
        jnp.broadcast_to(xc[None, None], vals.shape).reshape(
            n, c, oh, ow, -1), best[..., None], axis=-1)[..., 0]
    idx = (by * w + bx).astype(jnp.int32)
    return {"Out": [out], "Mask": [idx]}
