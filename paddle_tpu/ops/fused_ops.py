"""Fused ops backed by Pallas kernels (the TPU analogue of the reference's
operators/fused/ CPU+cuDNN fusions and operators/jit/ codegen kernels —
SURVEY.md §2.3)."""

from __future__ import annotations

from ..core.registry import register


@register("fused_attention")
def lower_fused_attention(ctx, ins):
    """Flash attention over [B,H,T,D] (fmt "bhtd") or [B,T,H,D] (fmt
    "bthd") q/k/v with optional additive bias.  "bthd" is the
    transpose-free convention — see kernels/attention.py.

    dropout_rate > 0 applies the reference's dropout-on-attention-weights
    semantics (transformer_model.py:44) INSIDE the kernels: the mask is the
    counter-based hash of (step base key, rng_id, global element index) —
    deterministic within a step, so the generic vjp re-trace regenerates
    the identical mask in the backward and the [Tq,Tk] mask never exists
    in HBM (see kernels/hash_rng.py)."""
    from ..kernels.attention import flash_attention
    from ..kernels import hash_rng

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("Bias", [None])[0]
    rate = ctx.attr("dropout_rate", 0.0)
    if ctx.attr("is_test", False) or ctx.is_test:
        rate = 0.0
    seed = None
    if rate:
        base = getattr(ctx.executor_ctx, "base_key", None)
        if base is None:
            base = ctx.executor_ctx._base_key  # eager session
        seed = hash_rng.seed_from_key(base, ctx.attr("rng_id", 1))
    # stop-gradient biases (padding/causal masks — the usual case) allow
    # the TPU hardware-PRNG dropout fast path: their dbias recompute is
    # dead-code-eliminated, so its hash-mask mismatch is unobservable.
    # A genuinely trainable bias forces the hash mask everywhere so the
    # bias cotangent sees the same mask the kernels applied.
    trainable_bias = False
    if bias is not None:
        try:
            bname = ctx.op.inputs.get("Bias", [None])[0]
            bvar = (ctx.block._find_var_recursive(bname)
                    if bname else None)
            trainable_bias = bvar is None or not bvar.stop_gradient
        except Exception:
            trainable_bias = True  # unknown provenance: stay correct
    out = flash_attention(
        q, k, v, bias,
        scale=ctx.attr("scale", 1.0),
        causal=ctx.attr("causal", False),
        block_q=ctx.attr("block_q", 512),
        block_k=ctx.attr("block_k", 512),
        fmt=ctx.attr("fmt", "bhtd"),
        dropout_rate=rate,
        dropout_seed=seed,
        trainable_bias=trainable_bias,
    )
    return {"Out": [out]}


@register("fused_layer_norm_gelu")
def lower_fused_ln_gelu(ctx, ins):
    """layer_norm + gelu epilogue; XLA fuses these — kept as one op so graph
    passes can target it (parity with fuse_elewise_add_act ideas)."""
    import jax

    from .nn_ops import layer_norm_core

    x = ins["X"][0]
    y, _, _ = layer_norm_core(
        x,
        ins.get("Scale", [None])[0],
        ins.get("Bias", [None])[0],
        ctx.attr("begin_norm_axis", x.ndim - 1),
        ctx.attr("epsilon", 1e-5),
    )
    # default matches the standalone gelu op (exact erf form)
    approx = bool(ctx.attr("approximate", False))
    return {"Out": [jax.nn.gelu(y, approximate=approx)]}


def _ring_attention_infer(ctx):
    qs = ctx.input_shape("Q")
    if qs is not None:
        ctx.set_output("Out", tuple(qs), ctx.input_dtype("Q"))


@register("ring_attention", infer_shape=_ring_attention_infer)
def lower_ring_attention(ctx, ins):
    """Context-parallel exact attention: the sequence axis is sharded over a
    mesh axis and K/V shards stream around the ring via ppermute over ICI
    (kernels/ring_attention.py; SURVEY.md §5.7 — a capability the reference
    lacks, its max context is bounded by one device's memory).

    Lowers to shard_map(ring) when the executor's mesh has the `axis_name`
    axis; otherwise (single-device trace, tests, dryrun without an sp axis)
    falls back to the numerically-identical reference attention.  Supports
    causal masking and sequence lengths that do not divide the axis (the
    sharded entry pads and masks via the ring-traveling key bias);
    additive bias is not supported on the ring path (pad-free batches or
    pure-causal decoders)."""
    from ..kernels.attention import reference_attention
    from ..kernels.ring_attention import ring_attention_sharded

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    scale = ctx.attr("scale", 1.0)
    causal = ctx.attr("causal", False)
    axis_name = ctx.attr("axis_name", "sp")
    mesh = getattr(ctx.executor_ctx, "mesh", None)
    if (
        mesh is None
        or axis_name not in getattr(mesh, "axis_names", ())
    ):
        out = reference_attention(q, k, v, None, scale=scale, causal=causal)
    else:
        out = ring_attention_sharded(
            q, k, v, mesh, axis_name=axis_name, scale=scale, causal=causal)
    return {"Out": [out]}
