#!/usr/bin/env python
"""Dump + analyze the optimized HLO of a bench workload's compiled scan
step: counts copy/transpose/custom-call instructions by shape and locates
them relative to the flash-attention custom-calls.  Perf tooling for
PERF.md leads 1-2 (attention layout copies, scan-carry copies).

Usage: python tools/hlo_diag.py [transformer|transformer_smoke
           |transformer_noflash|resnet50|deepfm] [out.txt]
           [--bn-fusion] [--sparse] [--copy-census]

--copy-census: the round-9 while-body copy-byte attribution, automated
(PERF.md's hand-done "Remaining copy inventory").  Every HLO copy is
attributed to a site class by its metadata + enclosing computation:
  projection   copies whose source metadata points into ops/math_ops.py
               (the mul lowering) — the dot-preferred<->custom-call
               relayouts.  NOTE: this keys on the DOT TIER, so any FFN/
               head mul relayouts land here too; the attention-projection
               subset is isolated by the fused-vs-unfused DIFF (the FFN
               dots are identical on both sides)
  pallas       copies sourced from kernels/ (the pallas_call operand/
               result relayouts into alternate memory)
  entry        copies living in the ENTRY computation whose operand is a
               program parameter — the donated-param entry copies ("XLA
               copies donated params at entry despite may-alias")
  other        everything else
Run with FLAGS_fused_qkv_attention=0 vs =1 and diff: the fused path must
drive the projection-site bytes to ~0 (asserted in
tests/test_fused_qkv_attention.py; the JSON lands next to the dump as
<out>.census.json so CI can archive it).

--bn-fusion (resnet50): the round-7 BN-wall attribution report — counts
the BN-statistics channel reductions (full passes over 3/4-D activations
producing per-channel vectors), the layout-dual filter copies (the same
[O,I,kh,kw] filter held in two layouts for fwd vs bwd conv — the r04
"momentum chain in two layout duals" finding), and the activation bytes
those reduction passes re-read.  Run it with FLAGS_fused_bn=0 vs =1 (env
var) and diff the counters: the A/B attribution of the fused-BN levers is
mechanical (tests/test_conv_bn.py asserts the fused path removes the
reduction passes).

--sparse (deepfm): the round-8 dispatch/launch census of the CTR step —
graph-level op counts (per-slot lookup_table / grad / optimizer chains
vs their fused_* group forms) and the HLO instruction census the sparse
tier lowers to (gather / scatter / dynamic-slice tiers + the bytes the
gathers move + int64->int32 convert count).  Run with
FLAGS_fused_embedding=0 vs =1 and diff: the fused path must show the
launch-count collapse (one fused gather per table group, the per-table
sort+segment+scatter optimizer chains collapsed to one group apply) —
asserted in tests/test_fused_embedding.py.
"""

import os
import re
import sys
import collections

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def compile_transformer(scan_steps=8, batch_size=64, seq_len=256,
                        use_flash=True):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T

    cfg = dict(n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
               d_inner_hid=2048, vocab=32000)
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
            max_length=seq_len, n_layer=cfg["n_layer"], n_head=cfg["n_head"],
            d_key=cfg["d_key"], d_value=cfg["d_value"], d_model=cfg["d_model"],
            d_inner_hid=cfg["d_inner_hid"], dropout_rate=0.1,
            src_seq_len=seq_len, trg_seq_len=seq_len, use_flash=use_flash,
        )
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    batches = [
        T.make_batch(batch_size, seq_len, seq_len, cfg["n_head"],
                     cfg["vocab"], cfg["vocab"], rng=np.random.RandomState(s))
        for s in range(scan_steps)
    ]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    return exe, prog, feed, [avg_cost], scope


def compile_transformer_smoke(scan_steps=2, batch_size=2, seq_len=64,
                              use_flash=True):
    """Tiny-but-representative transformer for the CI copy-census leg:
    d_model/head shapes keep the fused-qkv kernel plan feasible
    (d_head 64), everything else shrinks so a CPU box compiles it in
    seconds."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T

    cfg = dict(n_layer=1, n_head=2, d_key=64, d_value=64, d_model=128,
               d_inner_hid=256, vocab=512)
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
            max_length=seq_len, n_layer=cfg["n_layer"], n_head=cfg["n_head"],
            d_key=cfg["d_key"], d_value=cfg["d_value"], d_model=cfg["d_model"],
            d_inner_hid=cfg["d_inner_hid"], dropout_rate=0.1,
            src_seq_len=seq_len, trg_seq_len=seq_len, use_flash=use_flash,
        )
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    batches = [
        T.make_batch(batch_size, seq_len, seq_len, cfg["n_head"],
                     cfg["vocab"], cfg["vocab"], rng=np.random.RandomState(s))
        for s in range(scan_steps)
    ]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    return exe, prog, feed, [avg_cost], scope


def compile_resnet50(scan_steps=4, batch_size=256, image_size=224,
                     depth=50, data_format="NHWC"):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet as R

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        img, label, avg_cost, acc, _ = R.build_train_net(
            class_dim=1000, image_shape=(3, image_size, image_size),
            depth=depth, lr=0.1, data_format=data_format)
    pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {
        "image": rng.rand(scan_steps, batch_size, 3, image_size,
                          image_size).astype("float32"),
        "label": rng.randint(0, 1000,
                             (scan_steps, batch_size, 1)).astype("int64"),
    }
    return exe, prog, feed, [avg_cost], scope


def compile_deepfm(scan_steps=2, batch_size=256, hash_dim=10001,
                   embedding_size=10, optimizer="adam"):
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm as D

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, _, _ = D.build_train_net(
            hash_dim=hash_dim, embedding_size=embedding_size,
            optimizer=optimizer)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    batches = [D.make_batch(batch_size, hash_dim=hash_dim,
                            rng=np.random.RandomState(s))
               for s in range(scan_steps)]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    return exe, prog, feed, [avg_cost], scope


def lower_entry(exe, prog, feed, fetch_list, scope, return_compiled=False):
    """Compile via run_steps (populates the cache), then AOT-lower the
    cached jitted fn on the same args to get optimized HLO text (and the
    compiled object, whose memory_analysis() the --memory report
    reads)."""
    exe.run_steps(prog, feed=feed, fetch_list=fetch_list, scope=scope)
    from paddle_tpu.core.executor import latest_jitted_entry

    entry = latest_jitted_entry(exe)
    rw = [scope.find_var(n) for n in entry.rw_state]
    ro = [scope.find_var(n) for n in entry.ro_state]
    import jax

    feed_names = sorted(feed)
    feed_vals = [exe._to_device_array(prog, n, feed[n]) for n in feed_names]
    key = jax.random.PRNGKey(0)
    lowered = entry.jitted.lower(feed_vals, rw, ro, key)
    compiled = lowered.compile()
    if return_compiled:
        return compiled.as_text(), compiled
    return compiled.as_text()


INSTR_RE = re.compile(
    r"%?([\w.-]+) = ([a-z0-9]+)\[([\d,]*)\](\S*) ([\w-]+)\(")
DT_BYTES = {"bf16": 2, "f32": 4, "s32": 4, "u32": 4, "pred": 1,
            "f16": 2, "s8": 1, "u8": 1, "u64": 8, "s64": 8}


def analyze(txt):
    lines = txt.splitlines()
    copies = collections.Counter()
    copy_bytes = collections.Counter()
    copy_src = collections.Counter()
    custom_calls = collections.Counter()
    transposes = collections.Counter()
    for ln in lines:
        s = ln.strip()
        m = INSTR_RE.match(s)
        if not m:
            continue
        name, dt, dims, layout, opcode = m.groups()
        shape = f"{dt}[{dims}]{layout or ''}"
        nbytes = DT_BYTES.get(dt, 4) * int(
            np.prod([int(x) for x in dims.split(",") if x] or [1]))
        if opcode == "copy":
            copies[shape] += 1
            copy_bytes[shape] += nbytes
            sm = re.search(r'op_name="([^"]+)"', s)
            srcm = re.search(r'source_file="[^"]*/([\w.]+)" source_line=(\d+)',
                             s)
            label = (sm.group(1).split("/")[-1] if sm else "?")
            src = f"{srcm.group(1)}:{srcm.group(2)}" if srcm else "?"
            copy_src[(label, src)] += nbytes
        elif opcode == "transpose":
            transposes[shape] += 1
        elif opcode == "custom-call":
            cm = re.search(r'custom_call_target="([^"]+)"', s)
            custom_calls[(cm.group(1) if cm else "?", shape)] += 1
    out = []
    out.append("== copy instructions (count x shape, total MB) ==")
    for shape, n in copies.most_common(30):
        out.append(f"  {n:4d} x {shape}  ({copy_bytes[shape] / 1e6:.1f} MB)")
    out.append(f"  TOTAL copies: {sum(copies.values())} "
               f"({sum(copy_bytes.values()) / 1e6:.1f} MB static)")
    out.append("== copy bytes by op_name/source ==")
    for (label, src), b in copy_src.most_common(25):
        out.append(f"  {b / 1e6:8.1f} MB  {label}  {src}")
    out.append("== transpose instructions ==")
    for shape, n in transposes.most_common(15):
        out.append(f"  {n:4d} x {shape}")
    out.append(f"  TOTAL transposes: {sum(transposes.values())}")
    out.append("== custom calls ==")
    for (tgt, shape), n in custom_calls.most_common(20):
        out.append(f"  {n:4d} x {tgt} -> {shape}")
    return "\n".join(out)


# --bn-fusion: BN-statistics / layout-dual attribution ----------------------

# `%name = f32[64]{0} reduce(f32[2,8,8,64]{3,2,1,0} %op, f32[] %init), ...`
_REDUCE_RE = re.compile(
    r"= ([a-z0-9]+)\[([\d,]*)\][^ ]* reduce\(([a-z0-9]+)\[([\d,]*)\]")
_COPY_RE = re.compile(
    r"= ([a-z0-9]+)\[([\d,]*)\](\{[\d,]+\})? copy\(")
_SRC_RE = re.compile(r'source_file="([^"]*)" source_line=(\d+)')
_FILTER_KSIZES = (1, 3, 7)
_FLOAT_DTS = ("f32", "bf16", "f16", "f64")


def _dims(s):
    return tuple(int(x) for x in s.split(",") if x)


def analyze_bn_fusion(txt):
    """BN-wall counters from optimized-HLO text (the whole dump is
    scanned, so reductions inside fusion computation bodies count too):

      channel_reduces      float reduce instrs producing a 1-D per-channel
                           vector (>= 8 lanes) — the BN sum/sum²/dgamma/
                           dbeta tier, fwd AND bwd, wherever it came from
                           (XLA freely bitcasts the activation first, so
                           the rule keys on the OUTPUT shape)
      channel_reduce_read_mb  MB of inputs those reductions re-read (each
                           is a full pass over the activation it consumes)
      bn_stat_reduces      the subset whose source metadata points into
                           ops/nn_ops.py — i.e. emitted by the batch_norm
                           lowering itself; the fused path must drive this
                           to ZERO (its statistics ride the conv_bn.py
                           kernels; interpret-mode emulation attributes to
                           conv_bn.py, compiled Mosaic emits no reduce)
      filter_copies / filter_copy_mb / filter_layout_duals
                           copy instrs of 4-D [O,I,kh,kw] filter-shaped
                           tensors, and the dim-shapes held in >= 2
                           distinct layouts — the fwd/bwd layout duals of
                           the r04 momentum-chain finding
    """
    channel_reduces = 0
    read_bytes = 0
    bn_stat_reduces = 0
    bn_read_bytes = 0
    filter_copies = 0
    filter_copy_bytes = 0
    layouts_by_filter = collections.defaultdict(set)
    for ln in txt.splitlines():
        s = ln.strip()
        m = _REDUCE_RE.search(s)
        if m:
            out_dt, out_dims, in_dt, in_dims = m.groups()
            od, idm = _dims(out_dims), _dims(in_dims)
            if (out_dt in _FLOAT_DTS and len(od) == 1 and od[0] >= 8
                    and len(idm) >= 2):
                nbytes = DT_BYTES.get(in_dt, 4) * int(np.prod(idm))
                channel_reduces += 1
                read_bytes += nbytes
                src = _SRC_RE.search(s)
                if src and src.group(1).endswith("nn_ops.py"):
                    bn_stat_reduces += 1
                    bn_read_bytes += nbytes
            continue
        m = _COPY_RE.search(s)
        if m:
            dt, dims, layout = m.groups()
            d = _dims(dims)
            if (len(d) == 4 and d[2] == d[3] and d[2] in _FILTER_KSIZES
                    and d[0] >= 8 and d[1] >= 8):
                filter_copies += 1
                filter_copy_bytes += DT_BYTES.get(dt, 4) * int(np.prod(d))
                layouts_by_filter[d].add(layout or "{default}")
    duals = {d: sorted(ls) for d, ls in layouts_by_filter.items()
             if len(ls) >= 2}
    return {
        "channel_reduces": channel_reduces,
        "channel_reduce_read_mb": round(read_bytes / 1e6, 1),
        "bn_stat_reduces": bn_stat_reduces,
        "bn_stat_read_mb": round(bn_read_bytes / 1e6, 1),
        "filter_copies": filter_copies,
        "filter_copy_mb": round(filter_copy_bytes / 1e6, 1),
        "filter_layout_duals": len(duals),
        "filter_layout_dual_shapes": {
            "x".join(map(str, d)): ls for d, ls in sorted(duals.items())},
    }


def format_bn_fusion(rep):
    out = ["== BN-fusion report (PERF.md r07 attribution) =="]
    out.append(f"  channel-stat reduction passes: {rep['channel_reduces']} "
               f"(re-reading {rep['channel_reduce_read_mb']} MB)")
    out.append(f"  ... emitted by the batch_norm lowering: "
               f"{rep['bn_stat_reduces']} ({rep['bn_stat_read_mb']} MB) "
               "— 0 on the fused path")
    out.append(f"  filter-shaped copies: {rep['filter_copies']} "
               f"({rep['filter_copy_mb']} MB)")
    out.append(f"  filter layout duals: {rep['filter_layout_duals']}")
    for shape, layouts in rep["filter_layout_dual_shapes"].items():
        out.append(f"    {shape}: {', '.join(layouts)}")
    return "\n".join(out)


# --copy-census: the round-9 copy-byte attribution by site ------------------

_COMP_RE = re.compile(r"^(ENTRY\s+)?%?[\w.-]+\s*\(.*\)\s*->.*\{\s*$")
_PARAM_RE = re.compile(r"^%?([\w.-]+)\s*=\s*\S+\s+parameter\(\d+\)")
_COPY_OPND_RE = re.compile(
    r"=\s*([a-z0-9]+)\[([\d,]*)\](\{[\d,]+\})?\s+copy\(%?([\w.-]+)")
_KERNEL_FILES = ("attention.py", "conv_bn.py", "dropout_epilogue.py",
                 "embedding.py", "ring_attention.py", "matmul_stats.py")


def _census_site(src_file, op_name, in_entry, operand_is_param):
    """Site class of one copy: 'projection' (the dot tier — the mul
    lowering in ops/math_ops.py; dominated by the qkv/output projection
    dots, but FFN/head muls land here too — diff fused vs unfused to
    isolate the attention subset), 'pallas' (custom-call operand/result
    relayout, sourced from kernels/), 'entry' (ENTRY-computation copies
    of program parameters — the donated-param entry copies), 'other'."""
    if in_entry and operand_is_param:
        return "entry"
    base = src_file.rsplit("/", 1)[-1] if src_file else ""
    if base == "math_ops.py":
        return "projection"
    if base in _KERNEL_FILES or "/kernels/" in (src_file or ""):
        return "pallas"
    return "other"


def analyze_copy_census(txt):
    """Bytes-per-site copy census of one optimized-HLO dump (the
    automated form of PERF.md's hand-done 'Remaining copy inventory').
    Returns a JSON-able dict; diff a FLAGS_fused_qkv_attention=0 dump
    against =1: the fused path must drive the 'projection' site to ~0
    (there is no dot at the boundary left to relayout)."""
    sites = {k: {"count": 0, "mb": 0.0}
             for k in ("projection", "pallas", "entry", "other")}
    top = collections.Counter()
    entry_params = set()
    in_entry = False
    total = 0
    total_bytes = 0
    for ln in txt.splitlines():
        s = ln.strip()
        if _COMP_RE.match(ln):
            in_entry = ln.lstrip().startswith("ENTRY")
            continue
        if in_entry:
            pm = _PARAM_RE.match(s)
            if pm:
                entry_params.add(pm.group(1))
                continue
        m = _COPY_OPND_RE.search(s)
        if not m:
            continue
        dt, dims, _, operand = m.groups()
        nbytes = DT_BYTES.get(dt, 4) * int(
            np.prod([int(x) for x in dims.split(",") if x] or [1]))
        srcm = _SRC_RE.search(s)
        src_file = srcm.group(1) if srcm else ""
        src = (f"{src_file.rsplit('/', 1)[-1]}:{srcm.group(2)}"
               if srcm else "?")
        om = re.search(r'op_name="([^"]+)"', s)
        op_name = om.group(1).split("/")[-1] if om else "?"
        site = _census_site(src_file, op_name, in_entry,
                            operand in entry_params)
        sites[site]["count"] += 1
        sites[site]["mb"] = round(sites[site]["mb"] + nbytes / 1e6, 3)
        top[(site, op_name, src)] += nbytes
        total += 1
        total_bytes += nbytes
    return {
        "total_copies": total,
        "total_mb": round(total_bytes / 1e6, 3),
        "sites": sites,
        "top": [
            {"site": site, "op": op, "src": src, "mb": round(b / 1e6, 3)}
            for (site, op, src), b in top.most_common(15)
        ],
    }


def format_copy_census(rep):
    out = ["== copy census by site (PERF.md r09 attribution) =="]
    for site, d in rep["sites"].items():
        out.append(f"  {site:11s} {d['count']:4d} copies  {d['mb']:10.3f} MB")
    out.append(f"  {'TOTAL':11s} {rep['total_copies']:4d} copies  "
               f"{rep['total_mb']:10.3f} MB")
    out.append("  top attribution (site, op, source):")
    for t in rep["top"]:
        out.append(f"    {t['mb']:8.3f} MB  {t['site']:10s} {t['op']}  "
                   f"{t['src']}")
    return "\n".join(out)


# --sparse: the round-8 dispatch/launch census of the sparse CTR tier ------

_SPARSE_GRAPH_OPS = (
    "lookup_table", "fused_lookup_table",
    "lookup_table_grad", "fused_lookup_table_grad",
    "sgd", "adam", "fused_sparse_sgd", "fused_sparse_adam",
)
# HLO opcodes the per-slot sparse tier lowers to.  `sort` counts the
# per-table MergeAdd argsorts (the fused path runs ONE batched sort per
# group); the dynamic-slice tiers are where the fused kernels' emulated /
# compiled row DMAs land.
_SPARSE_HLO_OPS = ("gather", "scatter", "dynamic-slice",
                   "dynamic-update-slice", "convert", "sort", "while")
# tuple-result instructions (sort/while): `%x = (f32[8]{0}, ...) sort(`
_TUPLE_INSTR_RE = re.compile(r"%?[\w.-]+ = \(.*?\)(?:\{[\d,]*\})? ([a-z0-9-]+)\(")


def analyze_sparse(txt, program=None):
    """Dispatch census from optimized-HLO text (+ graph-level op counts
    when the Program is given): how many gather/scatter/optimizer
    dispatches one CTR step issues, and the bytes the gathers move.
    Diff FLAGS_fused_embedding=0 vs =1: the fused path collapses the
    52-launch lookup tier to one fused gather per table group and the
    per-table optimizer chains to one group apply."""
    hlo = {f"hlo_{k}": 0 for k in _SPARSE_HLO_OPS}
    gather_bytes = 0
    for ln in txt.splitlines():
        s = ln.strip()
        m = INSTR_RE.match(s)
        if not m:
            # sort (variadic argsort) and while carry TUPLE-shaped
            # results — `%x = (f32[8]{0}, s32[8]{0}) sort(...)` — which
            # INSTR_RE's array-shape pattern never matches
            m2 = _TUPLE_INSTR_RE.match(s)
            if m2 and m2.group(1) in _SPARSE_HLO_OPS:
                hlo[f"hlo_{m2.group(1)}"] += 1
            continue
        _, dt, dims, _, opcode = m.groups()
        if opcode in _SPARSE_HLO_OPS:
            hlo[f"hlo_{opcode}"] += 1
            if opcode == "gather":
                gather_bytes += DT_BYTES.get(dt, 4) * int(
                    np.prod([int(x) for x in dims.split(",") if x] or [1]))
    rep = {
        "graph": {},
        **hlo,
        "hlo_gather_mb": round(gather_bytes / 1e6, 3),
    }
    if program is not None:
        ops = [op.type for op in program.global_block().ops]
        rep["graph"] = {t: ops.count(t) for t in _SPARSE_GRAPH_OPS}
        g = rep["graph"]
        rep["graph"]["gather_launches"] = (
            g["lookup_table"] + g["fused_lookup_table"])
        rep["graph"]["sparse_grad_launches"] = (
            g["lookup_table_grad"] + g["fused_lookup_table_grad"])
        rep["graph"]["optimizer_launches"] = (
            g["sgd"] + g["adam"] + g["fused_sparse_sgd"]
            + g["fused_sparse_adam"])
    return rep


def format_sparse(rep):
    out = ["== sparse dispatch census (PERF.md r08 attribution) =="]
    g = rep.get("graph") or {}
    if g:
        out.append(
            f"  graph: gather launches {g['gather_launches']} "
            f"(lookup_table {g['lookup_table']} + fused "
            f"{g['fused_lookup_table']}), grad launches "
            f"{g['sparse_grad_launches']}, optimizer launches "
            f"{g['optimizer_launches']} (fused sparse "
            f"{g['fused_sparse_sgd'] + g['fused_sparse_adam']})")
    out.append(
        f"  HLO: {rep['hlo_gather']} gather ({rep['hlo_gather_mb']} MB "
        f"moved/step-call), {rep['hlo_scatter']} scatter, "
        f"{rep['hlo_sort']} sort, {rep['hlo_dynamic-slice']}/"
        f"{rep['hlo_dynamic-update-slice']} dyn-slice/update, "
        f"{rep['hlo_convert']} convert, {rep['hlo_while']} while")
    return "\n".join(out)


# --memory: planner table + memory_analysis() ground truth -----------------


def analyze_memory(prog, feed, compiled, txt, fetch_names):
    """The memory-tier report of one bench workload: the static
    planner's table next to the XLA executable's CompiledMemoryStats
    ground truth, with the long-open donated-param ENTRY-COPY bytes
    folded in as a named row (the copy census already attributes them;
    PERF.md's 'cause not yet found' aside becomes a tracked number).

    The planner models ONE step program; the compiled entry is the
    run_steps scan (leading [K] feed axis), so the delta also carries
    the K-stacked feed bytes — both recorded, labeled, never conflated.
    """
    from paddle_tpu import memory as M

    feed_names = sorted(feed)
    import numpy as _np

    first = _np.asarray(feed[feed_names[0]])
    batch = int(first.shape[1]) if first.ndim >= 2 else None
    plan = M.plan_program(prog, feed_names, fetch_names, batch_size=batch)
    stats = M.xla_memory_stats(compiled)
    census = analyze_copy_census(txt)
    entry_mb = census["sites"]["entry"]["mb"]
    rep = {
        "batch_size": batch,
        "planner": plan.to_dict(),
        "memory_analysis": stats,
        "planner_peak_bytes": plan.peak_bytes,
        "memory_analysis_peak_bytes": stats["peak_bytes"],
        "ratio": (round(plan.peak_bytes / stats["peak_bytes"], 4)
                  if stats["peak_bytes"] else None),
        # the donation question, now a named row instead of a PERF aside
        "entry_copy_mb": entry_mb,
        "entry_copy_count": census["sites"]["entry"]["count"],
        "table": plan.table(),
    }
    return rep


def format_memory(rep):
    out = ["== memory report (planner vs memory_analysis) =="]
    out.append(rep["table"])
    ma = rep["memory_analysis"]
    out.append(
        f"  XLA executable: args {ma['argument_bytes'] / 1e6:.2f} MB, "
        f"temp {ma['temp_bytes'] / 1e6:.2f} MB, out "
        f"{ma['output_bytes'] / 1e6:.2f} MB, alias "
        f"{ma['alias_bytes'] / 1e6:.2f} MB -> peak "
        f"{ma['peak_bytes'] / 1e6:.2f} MB")
    out.append(f"  planner/XLA ratio: {rep['ratio']}")
    out.append(
        f"  donated-param entry copies: {rep['entry_copy_count']} "
        f"({rep['entry_copy_mb']:.3f} MB) — the PERF.md donation row")
    return "\n".join(out)


def main():
    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    bn_fusion = "--bn-fusion" in sys.argv[1:]
    sparse = "--sparse" in sys.argv[1:]
    copy_census = "--copy-census" in sys.argv[1:]
    memory_report = "--memory" in sys.argv[1:]
    which = argv[0] if argv else "transformer"
    out_path = argv[1] if len(argv) > 1 else f"/tmp/hlo_{which}.txt"
    if which == "transformer":
        args = compile_transformer()
    elif which == "transformer_smoke":
        args = compile_transformer_smoke()
    elif which == "transformer_noflash":
        args = compile_transformer(use_flash=False)
    elif which == "resnet50":
        args = compile_resnet50()
    elif which == "deepfm":
        args = compile_deepfm()
    else:
        raise SystemExit(f"unknown workload {which}")
    txt, compiled = lower_entry(*args, return_compiled=True)
    with open(out_path, "w") as f:
        f.write(txt)
    print(f"[hlo_diag] optimized HLO -> {out_path} ({len(txt)} bytes)")
    print(analyze(txt))
    if bn_fusion:
        print(format_bn_fusion(analyze_bn_fusion(txt)))
    if sparse:
        print(format_sparse(analyze_sparse(txt, args[1])))
    if copy_census:
        import json

        rep = analyze_copy_census(txt)
        from paddle_tpu.flags import FLAGS as _FLAGS

        rep["fused_qkv_attention"] = bool(_FLAGS.fused_qkv_attention)
        rep["workload"] = which
        census_path = out_path + ".census.json"
        with open(census_path, "w") as f:
            json.dump(rep, f, indent=1)
        print(format_copy_census(rep))
        print(f"[hlo_diag] copy census -> {census_path}")
    if memory_report:
        import json

        exe_, prog_, feed_, fetch_, scope_ = args
        fetch_names = [getattr(v, "name", v) for v in fetch_]
        mrep = analyze_memory(prog_, feed_, compiled, txt, fetch_names)
        mrep["workload"] = which
        mem_path = out_path + ".memory.json"
        with open(mem_path, "w") as f:
            json.dump(mrep, f, indent=1)
        print(format_memory(mrep))
        print(f"[hlo_diag] memory report -> {mem_path}")


if __name__ == "__main__":
    main()
