"""Small host-side utilities shared across subsystems (no jax imports)."""

from .retry import RetryError, backoff_delays, retry_call  # noqa: F401
