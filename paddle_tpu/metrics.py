"""Python-side metric accumulators (reference: python/paddle/fluid/metrics.py
— MetricBase, Accuracy, Precision, Recall, Auc, EditDistance, CompositeMetric,
DetectionMAP; and average.py WeightedAverage)."""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).item()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no samples accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        for p, l in zip(preds, labels):
            if p == 1:
                if l == 1:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        for p, l in zip(preds, labels):
            if l == 1:
                if p == 1:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip(
            (pos_prob * self._num_thresholds).astype("int64"),
            0,
            self._num_thresholds,
        )
        for b, l in zip(bucket, labels):
            if l > 0:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data")
        return (
            self.total_distance / self.seq_num,
            self.instance_error / self.seq_num,
        )


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class WeightedAverage:
    """reference: python/paddle/fluid/average.py."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        self.numerator += float(np.asarray(value).item()) * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0:
            raise ValueError("WeightedAverage: nothing accumulated")
        return self.numerator / self.denominator
