"""Executor: lowers a whole Program block to ONE jitted JAX function.

Capability parity with the reference Executor/Scope (reference:
paddle/fluid/framework/executor.cc:203-457, scope.h:48,
python/paddle/fluid/executor.py:260-589), redesigned TPU-first:

  * The reference interprets ops one-by-one (hot loop executor.cc:448) with
    per-op kernel dispatch and eager GC.  Here the entire block is traced into
    a single function and compiled by XLA: fusion, scheduling, memory planning,
    rematerialization and collective insertion all happen in the compiler.
  * `Scope` holds parameter/state arrays between runs (device-resident).  A
    run is functional: (feeds, state) -> (fetches, new state); persistable
    writes (optimizer updates) come back as donated outputs, so parameters
    stay in HBM and update in place.
  * Compiled executables are cached per (program mutation-stamp, feed
    signature, fetch list) — parity with executor.py:445 program cache, but
    the cached object is an XLA executable, not a prepared op list.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import framework as fw
from . import registry

# ---------------------------------------------------------------------------
# Places (reference: platform/place.h:79).  TPU-native: places name JAX
# backends; XLA/PJRT owns real device handles.
# ---------------------------------------------------------------------------


class Place:
    backend: str = "cpu"
    device_id: int = 0

    def __eq__(self, other):
        return (
            type(self) is type(other)
            and self.backend == other.backend
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((type(self).__name__, self.backend, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    backend = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id


class TPUPlace(Place):
    backend = "tpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id


def _jax_device(place: Optional[Place]):
    import jax

    if place is None:
        return jax.devices()[0]
    try:
        devs = jax.devices(place.backend)
    except RuntimeError:
        devs = jax.devices()
    return devs[min(place.device_id, len(devs) - 1)]


def default_place() -> Place:
    import jax

    if jax.default_backend() == "tpu":
        return TPUPlace(0)
    return CPUPlace(0)


# ---------------------------------------------------------------------------
# Scope (reference: scope.h:48; hierarchical name->Variable store)
# ---------------------------------------------------------------------------


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self._vars: Dict[str, Any] = {}

    def var(self, name: str):
        if name not in self._vars:
            self._vars[name] = None
        return self._vars[name]

    def set_var(self, name: str, value):
        self._vars[name] = value

    def find_var(self, name: str):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def new_scope(self) -> "Scope":
        return Scope(self)

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def drop_kids(self):
        pass  # child scopes are plain objects; GC handles them


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib as _contextlib


@_contextlib.contextmanager
def scope_guard(scope: Scope):
    """Swap the global scope within a `with` block (reference:
    python/paddle/fluid/executor.py scope_guard) — lets user code isolate
    parameter state, e.g. train vs. loaded-inference scopes."""
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield scope
    finally:
        _global_scope = prev


# ---------------------------------------------------------------------------
# PRNG keys
# ---------------------------------------------------------------------------


def prng_key(seed: int):
    """Framework-created PRNG keys use the `rbg` implementation: threefry key
    derivation is VPU-heavy on TPU (measured ~30ms/step of pure dropout-mask
    cost on transformer-base) while rbg generates at near-memory speed and
    still supports fold_in.  Scoped here rather than flipping the global
    jax_default_prng_impl, so user jax code in the same process keeps stock
    threefry semantics."""
    import jax

    # typed key: carries its impl through fold_in/bernoulli/etc (a raw
    # uint32[4] key would be misread as threefry downstream)
    return jax.random.key(seed, impl="rbg")


# ---------------------------------------------------------------------------
# Trace context
# ---------------------------------------------------------------------------


class TraceContext:
    """Per-trace state handed to lowerings via LowerContext."""

    def __init__(self, program: fw.Program, base_key, is_test: bool = False,
                 mesh=None, check_nan_inf: bool = False):
        self.program = program
        self.base_key = base_key  # traced jax PRNG key (runtime arg)
        self.is_test = is_test
        self.mesh = mesh
        self._rng_counter = 0
        self.has_random = False
        self.amp_bf16 = bool(getattr(program, "_amp_bf16", False))
        # debug mode (reference FLAGS_check_nan_inf, operator.cc:943): record
        # one all-finite flag per op; the executor checks them on the host
        # after the step and names the first offending op
        self.check_nan_inf = check_nan_inf
        self.nan_checks: List[Tuple[str, Any]] = []
        # sparse-tier trace census (FLAGS_monitor only): the embedding
        # lowerings accumulate gather-launch / rows-touched counts here
        # (ops/nn_ops.py _note_embed_stats); trace_block publishes them as
        # per-step `embedding.*` gauges — a traced block IS one step
        self.embed_stats = {"gather_launches": 0, "sparse_rows_touched": 0}

    def next_rng_key(self, op=None):
        import jax

        self.has_random = True
        self._rng_counter += 1
        return jax.random.fold_in(self.base_key, self._rng_counter)


def trace_block(block: fw.Block, env: Dict[str, Any], tctx: TraceContext,
                ops: Optional[Sequence] = None):
    """Run every op's lowering over `env` (name -> traced value), in order.

    This is the TPU replacement for the interpreter hot loop
    (executor.cc:448): it executes at *trace time only*; the result is a
    single XLA computation.  `ops` restricts tracing to a subset (used by
    gradient accumulation to split the fwd/bwd prefix from the Optimize
    suffix).
    """
    from .. import amp as _amp
    from ..flags import FLAGS

    op_list = block.ops if ops is None else ops
    if FLAGS.record_lowered_ops:
        # executed-op recording (test flag): the op-contract gate asserts
        # every registered op reaches a trace — trace-time only, so the
        # run hot path never sees this
        from ..monitor import flight as _flight

        _flight.note_lowered_ops([op.type for op in op_list])

    for op in op_list:
        lower = registry.get_grad_lowering(op.type) if op.type.endswith("_grad") else None
        if lower is None:
            lower = registry.get(op.type).lower
        ins = {}
        for slot, names in op.inputs.items():
            ins[slot] = [env.get(n) if n else None for n in names]
        if tctx.amp_bf16:
            ins = _amp.apply_cast_policy(op.type, ins)
        ctx = registry.LowerContext(op, op.attrs, tctx)
        ctx.env = env  # control-flow ops need sub-block access
        ctx.block = block
        try:
            outs = lower(ctx, ins)
        except Exception as e:
            raise RuntimeError(
                f"Error lowering op {op.type!r} "
                f"(inputs={ {s: n for s, n in op.inputs.items() if n} }): {e}"
            ) from e
        for slot, vals in (outs or {}).items():
            names = op.output(slot)
            for name, val in zip(names, vals):
                if name and val is not None:
                    env[name] = val
        if FLAGS.chaos:
            # graph-level NaN injection (FLAGS_chaos_nan_var): poison the
            # named op output IN the compiled graph, so the numerics
            # tier's locate replay has a real in-graph origin to find —
            # unlike chaos_nan_at_step's host-side fake loss.  One flag
            # read per op at trace time only when chaos is armed.
            from ..testing import chaos as _chaos

            _chaos.poison_outputs(op, env)
        bvars = op.attrs.get("pipeline_boundary_vars")
        if bvars and getattr(tctx, "boundary_barriers", True):
            # Pipeline-annotated programs (parallel/pipeline/partition.py
            # split_program): values that cross a stage cut get an
            # optimization barrier at their producer, so XLA associates
            # the reductions CONSUMING them identically whether the value
            # is in-program (single-program run_accumulated) or a stage
            # boundary input (the pipeline schedules) — the
            # association-normalization behind the bit-parity contract.
            # Unannotated programs pay one dict miss per op, trace-time
            # only.
            import jax as _jax

            for n in bvars:
                if env.get(n) is not None:
                    env[n] = _jax.lax.optimization_barrier(env[n])
        if tctx.check_nan_inf and outs:
            flag = _all_finite_flag(outs)
            if flag is not None:
                tctx.nan_checks.append((repr(op), flag))
    if any(tctx.embed_stats.values()):
        # per-step sparse-tier gauges (trace-time writes only; the outer
        # block's publish runs last, so sub-block traces never leave a
        # partial count behind).  Guarded inside _note_embed_stats: the
        # accumulators stay zero unless FLAGS.monitor was on at trace
        # time.  The same census rides the flight ring so
        # tools/trace_report.py can surface it from a postmortem dump.
        from .. import monitor
        from ..monitor import flight as _flight

        for k, v in tctx.embed_stats.items():
            monitor.gauge(f"embedding.{k}").set(v)
        _flight.record("embedding.census", **tctx.embed_stats)
    return env


def _all_finite_flag(outs):
    """Scalar bool: every inexact-float leaf in an op's outputs is finite."""
    import jax
    import jax.numpy as jnp

    leaves = [
        leaf
        for vals in outs.values()
        for v in vals
        if v is not None
        for leaf in jax.tree_util.tree_leaves(v)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]
    if not leaves:
        return None
    flag = jnp.bool_(True)
    for leaf in leaves:
        flag = jnp.logical_and(flag, jnp.isfinite(leaf).all())
    return flag


# ---------------------------------------------------------------------------
# Program analysis: feed/state/write sets
# ---------------------------------------------------------------------------


_RANDOM_OPS = frozenset(
    {
        "dropout",
        "dropout_add",  # fused epilogue: mask seed derives from the step key
        "uniform_random",
        "gaussian_random",
        "truncated_gaussian_random",
        "sampling_id",
        "random_crop",
        "shuffle_batch",
        "nce",  # draws negative samples from the trace key
    }
)


# Ops whose randomness is attr-gated: op type -> predicate over the op.
# fused attention draws from the step key only when its in-kernel weights
# dropout is armed; sample_token only for the stochastic strategies
# (greedy decode programs stay key-free and bit-deterministic).  Each
# predicate mirrors the op's registry derives_rng declaration — the
# static verifier cross-checks the two sides per op instance.
def _dropout_armed(op) -> bool:
    return bool(op.attrs.get("dropout_rate", 0.0))


_COND_RANDOM_OPS = {
    "fused_attention": _dropout_armed,
    "fused_qkv_attention": _dropout_armed,
    "sample_token":
        lambda op: op.attrs.get("strategy", "greedy") != "greedy",
}

# Extension point for ops registered OUTSIDE the core tree: a downstream
# registry.register(..., derives_rng=True) op must also call this so the
# executor threads the step key for it — the static verifier's
# rng-unthreaded check enforces the pairing.  (In-tree ops use the
# hand-maintained sets above: keeping them independent of the registry
# metadata is deliberate defense-in-depth — the verifier cross-checks the
# two, so a random op missing from EITHER side is a named pre-compile
# error instead of a frozen-mask bug.)
_EXTRA_RANDOM_OPS: set = set()


def register_random_op(op_type: str) -> None:
    """Declare that `op_type`'s lowering draws PRNG bits from the step
    key.  Pairs with registry.register(..., derives_rng=...); the
    verifier (paddle_tpu/analysis) rejects programs whose derives_rng
    ops are not known here."""
    _EXTRA_RANDOM_OPS.add(op_type)


# ONE process-wide mutex for program verification: the verifier's shape
# re-inference temporarily mutates Variable.shape on the Program being
# verified (snapshot/restored), and a Program can be shared across
# Executor instances (train + eval executors, per-thread executors over
# default_main_program) — a per-executor lock would let two executors'
# verifies interleave on the same IR.
import threading as _threading

_VERIFY_MUTEX = _threading.Lock()


def _iter_ops_recursive(block: fw.Block):
    """Yield the block's ops, descending into sub_block attrs (while /
    conditional_block bodies)."""
    for op in block.ops:
        yield op
        sub = op.attrs.get("sub_block")
        if sub is not None:
            yield from _iter_ops_recursive(sub)


def op_threads_rng(op) -> bool:
    """Whether the executor threads the step key on account of THIS op.

    The single source of truth for step-key threading: program_uses_random
    folds it over the block, and the static verifier
    (paddle_tpu/analysis/verifier.py) cross-checks it against the
    registry's derives_rng contract metadata — an op whose lowering draws
    PRNG bits but is invisible here would reuse the trace-constant base
    key on every plain run (the PR-4 dropout_add bug class), so the
    verifier turns that mismatch into a pre-compile error."""
    cond = _COND_RANDOM_OPS.get(op.type)
    return bool(
        op.type in _RANDOM_OPS
        or op.type in _EXTRA_RANDOM_OPS
        or op.type.endswith("_grad")
        or (cond is not None and cond(op))
    )


def program_uses_random(block: fw.Block) -> bool:
    """Whether lowering may draw PRNG bits (then the compiled fn takes a key
    argument).  Grad ops count: the generic vjp re-traces forward lowerings.
    fused_attention / fused_qkv_attention count only when their in-kernel
    weights dropout is on (the mask seed derives from the step key)."""
    return any(op_threads_rng(op) for op in _iter_ops_recursive(block))


def analyze_block_io(
    block: fw.Block, feed_names: Sequence[str], scope: Scope
) -> Tuple[List[str], List[str]]:
    """Return (state_reads, state_writes): scope-resident vars the block reads
    before writing, and persistable/scope vars it writes."""
    defined = set(feed_names)
    reads: List[str] = []
    writes: List[str] = []
    seen_r, seen_w = set(), set()
    for op in block.ops:
        in_names = list(op.input_arg_names())
        sub = op.attrs.get("sub_block")
        if sub is not None:
            # while/conditional bodies read outer state (params!) from inside
            # the sub-block; those are reads of the outer op.  Names only
            # live inside the sub-block are filtered by the scope check.
            in_names += [
                n
                for inner in _iter_ops_recursive(sub)
                for n in inner.input_arg_names()
            ]
        for n in in_names:
            if n and n not in defined and n not in seen_r:
                if scope.has_var(n) and scope.find_var(n) is not None:
                    reads.append(n)
                    seen_r.add(n)
                    defined.add(n)
        for n in op.output_arg_names():
            if not n:
                continue
            defined.add(n)
            v = block._find_var_recursive(n)
            persistable = (v is not None and v.persistable) or scope.has_var(n)
            if persistable and n not in seen_w:
                writes.append(n)
                seen_w.add(n)
    return reads, writes


# ---------------------------------------------------------------------------
# Telemetry (paddle_tpu.monitor, gated on FLAGS.monitor)
# ---------------------------------------------------------------------------


# Named components of each call-mode's cache key, parallel to the key
# tuples built in run()/run_steps()/run_accumulated().  The recompile
# detector diffs consecutive keys against these names so a silent retrace
# storm logs WHICH component keeps changing (feed-signature churn from
# ragged batch shapes is the classic one).
_RUN_KEY_PARTS = (
    "program-stamp", "amp-mode", "is-test-mode", "check-nan-inf",
    "scope-signature", "feed-names", "feed-signature", "fetch-list",
)
_STEPS_KEY_PARTS = (
    "call-mode", "program-stamp", "amp-mode", "is-test-mode",
    "check-nan-inf", "scope-signature", "steps", "feed-names",
    "feed-signature", "fetch-list",
)
_ACC_KEY_PARTS = (
    "call-mode", "program-stamp", "amp-mode", "check-nan-inf",
    "scope-signature", "accumulate-steps", "feed-names", "feed-signature",
    "fetch-list",
)

# compile times are seconds-scale (XLA), run times sub-second: separate
# bucket ladders keep both histograms informative
_COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class _CompiledEntry:
    """Compiled executable + its state signature.

    State is split so parameter buffers can be donated (updated in place in
    HBM) while read-only state (e.g. a learning-rate var) survives the call:
      rw_state — read AND written (params, optimizer moments): donated
      ro_state — read only: not donated
      state_writes — all written names, in output order
    """

    __slots__ = ("fn", "rw_state", "ro_state", "state_writes", "needs_key",
                 "nan_check_ops", "jitted", "run_lock")

    def __init__(self, fn, rw_state, ro_state, state_writes, needs_key,
                 nan_check_ops=None, jitted=None, run_lock=None):
        self.fn = fn
        # the underlying jax.jit-wrapped callable, for AOT introspection
        # (profiler tooling lowers it to optimized HLO)
        self.jitted = jitted
        self.rw_state = rw_state
        self.ro_state = ro_state
        self.state_writes = state_writes
        self.needs_key = needs_key
        # op descriptions for check_nan_inf mode (parallel to the extra flag
        # outputs of fn); None when the mode is off.  The list is filled in
        # during the first trace of fn.
        self.nan_check_ops = nan_check_ops
        # A stateful entry donates its rw buffers to the executable:
        # concurrent calls would hand the SAME donated buffer to two
        # executions (use-after-donate) and interleave the scope
        # write-backs (torn state).  The lock's domain is the SHARED
        # SCOPE STATE, not the entry: different feed signatures of one
        # program donate the same scope arrays, so every stateful entry
        # of an Executor carries the executor's one stateful-run lock
        # (None for stateless entries — purely functional, serving
        # threads run those concurrently).
        self.run_lock = run_lock if state_writes else None


class Executor:
    def __init__(self, place: Optional[Place] = None,
                 check_nan_inf: Optional[bool] = None):
        import os
        import threading

        self.place = place or default_place()
        self._cache: Dict[Any, _CompiledEntry] = {}
        self._ref_names_cache: Dict[Any, tuple] = {}
        self._run_counter = 0
        # numerics failing-step replay (monitor/numerics.py): when set,
        # the next _next_run_id() returns THIS value once, without
        # advancing the counter — the replayed step folds the SAME id
        # into its PRNG key, so dropout masks come out bit-identical to
        # the step being diagnosed
        self._forced_run_id: Optional[int] = None
        # pre-compile static-verification memo: (program fingerprint,
        # scope signature, feeds, fetches) already verified by this
        # executor — verification runs at most once per signature, so a
        # warm serving process never re-walks a program
        # (paddle_tpu/analysis).  Mutation safety rides the module-level
        # _VERIFY_MUTEX (a Program can be shared across executors).
        self._verified = set()
        # Serving threads (paddle_tpu/serving dynamic batcher, user thread
        # pools over Predictor) hammer run() concurrently: the compile
        # cache uses per-key locks so N threads x M signatures compile
        # exactly M times (double-checked under the key's lock), and the
        # run counter draws under a lock so key-deriving programs never
        # fold in a duplicate counter value.
        self._counter_lock = threading.Lock()
        self._compile_locks_guard = threading.Lock()
        self._compile_locks: Dict[Any, threading.Lock] = {}
        # ONE lock for every stateful run of this executor: stateful
        # entries donate scope rw buffers, and entries of DIFFERENT feed
        # signatures (serving bucket ladder) donate the SAME scope
        # arrays — per-entry locking would let two signatures race a
        # use-after-donate.  Predictor hands this same lock to its AOT
        # bundles (inference.py), closing the JIT-vs-bundle race too.
        self._stateful_lock = threading.Lock()
        # recompile-detector state below is shared mutable: serialize
        # lookups/commits so concurrent serving threads cannot tear the
        # pending-stamp bookkeeping (recompile attribution would drift)
        self._detector_lock = threading.Lock()
        # recompile detector state: last cache key per (mode, program)
        # + the program-stamps that have compiled at least once (a later
        # miss on a seen stamp IS a recompile); pending = missed but not
        # yet committed to the cache (a retried failed compile is not a
        # recompile); only written when FLAGS.monitor is on
        self._last_key_by_program = {}
        self._compiled_stamps = set()
        self._pending_stamps = set()
        # debug mode, parity with the reference's FLAGS_check_nan_inf
        # (operator.cc:943): validate every op's outputs are finite
        if check_nan_inf is None:
            from ..flags import FLAGS  # typed flag registry w/ env override

            check_nan_inf = FLAGS.check_nan_inf
        self.check_nan_inf = check_nan_inf

    def close(self):
        self._cache.clear()

    # -- public API ------------------------------------------------------
    def run(
        self,
        program: Optional[fw.Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        # fault-injection hook (FLAGS_chaos_kill_at_run): one flag read
        # when chaos is off, SIGKILL mid-training when armed — the
        # preemption the checkpoint layer must survive
        from ..testing import chaos as _chaos

        _chaos.on_executor_run()
        # CompiledProgram / ShardedProgram delegate via their _run hook.
        # Their data-parallel/sharded paths keep private compile caches, so
        # only coarse telemetry (calls, wall time, errors) is recorded
        # here; a non-parallel CompiledProgram calls back into run() below
        # and gets the full instrumentation under a distinct namespace.
        if program is not None and hasattr(program, "_run"):
            from ..monitor import enabled as _mon_enabled

            if not _mon_enabled():
                return program._run(self, feed, fetch_list, scope,
                                    return_numpy)
            import time as _time

            from .. import monitor, profiler

            t0 = _time.perf_counter()
            try:
                outs = program._run(self, feed, fetch_list, scope,
                                    return_numpy)
            except Exception:
                # namespaced: the non-parallel path re-enters run(),
                # whose own _count_error already bumps executor.errors
                monitor.counter("executor.delegated.errors").inc()
                raise
            dt = _time.perf_counter() - t0
            monitor.counter("executor.delegated.calls").inc()
            monitor.histogram("executor.delegated_seconds").observe(dt)
            profiler.add_event("executor.delegated", dt)
            return outs

        if program is None:
            program = fw.default_main_program()
        feed = feed or {}
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v for v in (fetch_list or [])
        ]
        scope = scope or global_scope()
        # numerics-instrumented programs (analysis/numerics.py) carry
        # packed [N, 4] stats tensors that ride the user's fetch — ONE
        # device->host transfer per step, stripped before returning
        user_fetch_n, fetch_names = self._numerics_fetch(program,
                                                         fetch_names)

        feed_names = sorted(feed)
        # fingerprint (content hash, memoized on the mutation stamp) rather
        # than id(program): a GC'd program's id can be reused by a new object,
        # which would alias cache entries
        key = (
            program.fingerprint(),
            bool(getattr(program, "_amp_bf16", False)),
            bool(getattr(program, "_is_test", False)),
            bool(self.check_nan_inf),
            self._scope_signature(program, feed_names, scope),
            tuple(feed_names),
            tuple(
                (np.asarray(feed[n]).shape, str(np.asarray(feed[n]).dtype))
                if not hasattr(feed[n], "shape")
                else (tuple(feed[n].shape), str(feed[n].dtype))
                for n in feed_names
            ),
            tuple(fetch_names),
        )

        entry = self._cache.get(key) if use_program_cache else None
        compiled_now = entry is None
        # hit/miss is NOTED only once the double-check below resolves it
        # (a race-losing thread must not count a spurious miss), but t0
        # starts here so a compile's duration lands in its flight event
        mon, t0 = self._begin_monitored(_RUN_KEY_PARTS, key,
                                        not compiled_now, note=False)
        if entry is None:
            if use_program_cache:
                with self._compile_locks_guard:
                    import threading as _threading

                    klock = self._compile_locks.setdefault(
                        key, _threading.Lock())
                with klock:
                    # double-check: another thread may have compiled this
                    # signature while we waited on its lock — N concurrent
                    # callers of M signatures produce exactly M compiles
                    entry = self._cache.get(key)
                    if entry is None:
                        if mon:
                            self._note_cache_lookup(_RUN_KEY_PARTS, key,
                                                    False)
                        try:
                            entry = self._compile(program, feed, feed_names,
                                                  fetch_names, scope)
                        except Exception:
                            self._count_error(mon)
                            raise
                        self._cache[key] = entry
                        self._commit_stamp(_RUN_KEY_PARTS, key)
                    else:
                        compiled_now = False
                        if mon:
                            self._note_cache_lookup(_RUN_KEY_PARTS, key,
                                                    True)
            else:
                if mon:
                    self._note_cache_lookup(_RUN_KEY_PARTS, key, False)
                try:
                    entry = self._compile(program, feed, feed_names,
                                          fetch_names, scope)
                except Exception:
                    self._count_error(mon)
                    raise
        elif mon:
            self._note_cache_lookup(_RUN_KEY_PARTS, key, True)

        feed_vals = [self._to_device_array(program, n, feed[n]) for n in feed_names]

        import contextlib

        import jax

        # stateful entries serialize (donated rw buffers + scope
        # write-back must be atomic); stateless ones run concurrently
        with entry.run_lock if entry.run_lock is not None \
                else contextlib.nullcontext():
            rw_vals = [scope.find_var(n) for n in entry.rw_state]
            ro_vals = [scope.find_var(n) for n in entry.ro_state]
            rid = self._next_run_id()
            # locate-mode capture must happen HERE: the rw buffers are
            # donated to the executable below, so a post-hoc snapshot
            # would read deleted arrays
            self._maybe_capture_step(program, feed, fetch_names, entry,
                                     rw_vals, ro_vals, rid)
            try:
                if entry.needs_key:
                    seed = program.random_seed or 0
                    key_arr = jax.random.fold_in(prng_key(seed), rid)
                    result = entry.fn(feed_vals, rw_vals, ro_vals, key_arr)
                else:
                    result = entry.fn(feed_vals, rw_vals, ro_vals)
            except Exception:
                self._count_error(mon)
                raise
            if entry.nan_check_ops is not None:
                fetches, new_state, nan_flags = result
            else:
                fetches, new_state = result
                nan_flags = None

            # Write state back BEFORE any nan/inf raise: the rw buffers
            # were donated to the executable, so skipping this would leave
            # the scope holding deleted arrays and poison every subsequent
            # run.
            for n, v in zip(entry.state_writes, new_state):
                scope.set_var(n, v)

        if nan_flags is not None:
            bad = [
                desc
                for desc, ok in zip(entry.nan_check_ops, np.asarray(nan_flags))
                if not ok
            ]
            if bad:
                self._count_error(mon)
                raise FloatingPointError(
                    "check_nan_inf: non-finite output from op(s):\n  "
                    + "\n  ".join(bad)
                )

        outs = self._finish_monitored("run", mon, t0, compiled_now,
                                      feed_vals, fetches, return_numpy)
        return self._publish_numerics(program, fetch_names, user_fetch_n,
                                      outs)

    def run_steps(
        self,
        program: Optional[fw.Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        steps: Optional[int] = None,
        return_numpy: bool = True,
    ):
        """Run `steps` training iterations in ONE compiled XLA call.

        TPU-first replacement for the reference's prepare-once/run-many
        Executor loop (executor.cc:372 Prepare + :413 RunPreparedContext):
        the whole multi-step loop is a single `lax.scan`, so parameters stay
        in HBM across steps and there is exactly one host round-trip per
        call — host dispatch latency amortizes over `steps`.

        `feed` values must carry a leading [steps, ...] axis (one slice per
        iteration).  Returns fetches stacked along a leading [steps] axis.
        """
        if program is None:
            program = fw.default_main_program()
        feed = feed or {}
        scope = scope or global_scope()
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v
            for v in (fetch_list or [])
        ]
        user_fetch_n, fetch_names = self._numerics_fetch(program,
                                                         fetch_names)
        feed_names = sorted(feed)
        feed_stack = {
            n: self._to_device_array(program, n, feed[n])
            for n in feed_names
        }
        if steps is None:
            if not feed_names:
                raise ValueError("run_steps needs `steps` when feed is empty")
            steps = int(feed_stack[feed_names[0]].shape[0])
        for n in feed_names:
            if feed_stack[n].shape[0] != steps:
                raise ValueError(
                    f"feed {n!r} leading dim {feed_stack[n].shape[0]} != "
                    f"steps {steps}"
                )

        key = (
            "run_steps",
            program.fingerprint(),
            bool(getattr(program, "_amp_bf16", False)),
            bool(getattr(program, "_is_test", False)),
            bool(self.check_nan_inf),
            self._scope_signature(program, feed_names, scope),
            steps,
            tuple(feed_names),
            tuple(
                (tuple(feed_stack[n].shape), str(feed_stack[n].dtype))
                for n in feed_names
            ),
            tuple(fetch_names),
        )
        entry = self._cache.get(key)
        compiled_now = entry is None
        mon, t0 = self._begin_monitored(_STEPS_KEY_PARTS, key,
                                        not compiled_now)
        if entry is None:
            try:
                entry = self._compile_steps(
                    program, feed_names, fetch_names, scope, steps
                )
            except Exception:
                self._count_error(mon)
                raise
            self._cache[key] = entry
            self._commit_stamp(_STEPS_KEY_PARTS, key)

        rw_vals = [scope.find_var(n) for n in entry.rw_state]
        ro_vals = [scope.find_var(n) for n in entry.ro_state]
        feed_vals = [feed_stack[n] for n in feed_names]

        import jax

        seed = program.random_seed or 0
        base_key = jax.random.fold_in(prng_key(seed), self._next_run_id())
        try:
            result = entry.fn(feed_vals, rw_vals, ro_vals, base_key)
        except Exception:
            self._count_error(mon)
            raise
        if entry.nan_check_ops is not None:
            fetches, new_state, nan_flags = result
        else:
            fetches, new_state = result
            nan_flags = None
        # state write-back must precede any nan/inf raise (donated buffers)
        for n, v in zip(entry.state_writes, new_state):
            scope.set_var(n, v)
        if nan_flags is not None:
            per_op = np.asarray(nan_flags)
            if per_op.ndim == 2:  # [steps, n_ops] -> op is bad if ANY step was
                per_op = per_op.all(axis=0)
            bad = [
                desc
                for desc, ok in zip(entry.nan_check_ops, per_op)
                if not ok
            ]
            if bad:
                self._count_error(mon)
                raise FloatingPointError(
                    "check_nan_inf: non-finite output from op(s):\n  "
                    + "\n  ".join(bad)
                )
        outs = self._finish_monitored("run_steps", mon, t0, compiled_now,
                                      feed_vals, fetches, return_numpy)
        return self._publish_numerics(program, fetch_names, user_fetch_n,
                                      outs)

    def run_startup_missing(self, startup_program=None, scope=None):
        """Run only the startup ops whose outputs are NOT yet in the scope
        (init-on-demand).  Needed when graph surgery adds initialized state
        after the startup program already ran — e.g. slim pruning before
        optimizer.minimize(), whose learning-rate/accumulator initializers
        land in an already-executed startup program.  Returns the number
        of ops executed."""
        startup = startup_program or fw.default_startup_program()
        scope = scope or global_scope()
        src = startup.global_block()
        missing = [
            op for op in src.ops
            if any(scope.find_var(n) is None for n in op.output_arg_names())
        ]
        if not missing:
            return 0
        sub = fw.Program()
        blk = sub.global_block()
        names = set()
        for op in missing:
            names.update(op.input_arg_names())
            names.update(op.output_arg_names())
        for n in names:
            v = src._find_var_recursive(n)
            if v is not None:
                blk.create_var(name=n, shape=v.shape, dtype=v.dtype,
                               persistable=getattr(v, "persistable", True))
            else:
                blk.create_var(name=n, dtype="float32", persistable=True)
        for op in missing:
            blk.append_op(op.type, dict(op.inputs), dict(op.outputs),
                          dict(op.attrs))
        self.run(sub, scope=scope)
        return len(missing)

    def run_accumulated(
        self,
        program: Optional[fw.Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        accumulate_steps: Optional[int] = None,
        return_numpy: bool = True,
        unroll: bool = False,
    ):
        """Gradient accumulation in ONE compiled XLA call: run the
        forward+backward prefix over K micro-batches (feed arrays carry a
        leading [K, micro_bs, ...] axis) summing every parameter gradient,
        then run the Optimize-role op suffix ONCE on the averaged grads.

        unroll=True traces every micro-batch straight-line instead of
        scanning 1..K-1 — the literal shape of the reference pass (clone
        fwd/bwd K times).  Math is identical; compile time grows ~K-fold;
        the pipeline tier's strict bit-parity gates compare against this
        form because XLA may tile a reduce inside a scan body differently
        from the same reduce compiled straight-line (a fetched loss
        scalar can re-round by 1 ulp between the two — parameter updates
        are bit-identical either way, probed in tests/test_pipeline.py).

        The capability of the reference's multi_batch_merge_pass
        (ir/multi_batch_merge_pass.h:25 — clone fwd/bwd N times, average,
        optimize once), realized as a lax.scan instead of a graph clone.
        Gradient clipping/regularization ops carry the Backward role, so
        they apply per micro-batch (matching the reference pass, which
        clones everything before the optimizer).

        Fetch contract: targets produced by the fwd/bwd prefix (or
        feeds/state) come back stacked along a leading [K] axis, one
        slice per micro-batch; targets produced by the Optimize suffix
        (updated params, lr values) come back UN-stacked — the
        post-update value.  A name neither side produces raises KeyError
        at compile, naming both sets.
        """
        import jax
        import jax.numpy as jnp

        if program is None:
            program = fw.default_main_program()
        feed = feed or {}
        scope = scope or global_scope()
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v
            for v in (fetch_list or [])
        ]
        user_fetch_n, fetch_names = self._numerics_fetch(program,
                                                         fetch_names)
        feed_names = sorted(feed)
        feed_stack = {
            n: self._to_device_array(program, n, feed[n])
            for n in feed_names
        }
        if accumulate_steps is None:
            if not feed_names:
                raise ValueError("run_accumulated needs accumulate_steps "
                                 "when feed is empty")
            accumulate_steps = int(feed_stack[feed_names[0]].shape[0])
        k = accumulate_steps

        key = (
            "run_accumulated" + ("_unrolled" if unroll else ""),
            program.fingerprint(),
            bool(getattr(program, "_amp_bf16", False)),
            bool(self.check_nan_inf),
            self._scope_signature(program, feed_names, scope),
            k,
            tuple(feed_names),
            tuple(
                (tuple(feed_stack[n].shape), str(feed_stack[n].dtype))
                for n in feed_names
            ),
            tuple(fetch_names),
        )
        entry = self._cache.get(key)
        compiled_now = entry is None
        mon, t0 = self._begin_monitored(_ACC_KEY_PARTS, key,
                                        not compiled_now)
        if entry is None:
            try:
                entry = self._compile_accumulated(
                    program, feed_names, fetch_names, scope, k,
                    unroll=unroll,
                )
            except Exception:
                self._count_error(mon)
                raise
            self._cache[key] = entry
            self._commit_stamp(_ACC_KEY_PARTS, key)

        rw_vals = [scope.find_var(n) for n in entry.rw_state]
        ro_vals = [scope.find_var(n) for n in entry.ro_state]
        feed_vals = [feed_stack[n] for n in feed_names]
        seed = program.random_seed or 0
        base_key = jax.random.fold_in(prng_key(seed), self._next_run_id())
        try:
            fetches, new_state, nan_flags = entry.fn(
                feed_vals, rw_vals, ro_vals, base_key)
        except Exception:
            self._count_error(mon)
            raise
        for n, v in zip(entry.state_writes, new_state):
            scope.set_var(n, v)
        if entry.nan_check_ops:
            prefix_flags, suffix_flags = nan_flags
            per_op = np.asarray(prefix_flags)
            if per_op.ndim == 2:
                per_op = per_op.all(axis=0)
            per_op = np.concatenate([per_op, np.asarray(suffix_flags)])
            bad = [d for d, ok in zip(entry.nan_check_ops, per_op) if not ok]
            if bad:
                self._count_error(mon)
                raise FloatingPointError(
                    "check_nan_inf: non-finite output from op(s):\n  "
                    + "\n  ".join(bad))
        outs = self._finish_monitored("run_accumulated", mon, t0,
                                      compiled_now, feed_vals, fetches,
                                      return_numpy)
        return self._publish_numerics(program, fetch_names, user_fetch_n,
                                      outs)

    def _compile_accumulated(self, program, feed_names, fetch_names, scope,
                             k, unroll=False):
        import jax
        import jax.numpy as jnp

        self._maybe_verify(program, feed_names, fetch_names, scope)
        block = program.global_block()
        opt_bit = fw.OpRole.Optimize
        prefix_ops = [
            op for op in block.ops
            if not (int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, 0)) & opt_bit)
        ]
        suffix_ops = [
            op for op in block.ops
            if int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, 0)) & opt_bit
        ]
        if not suffix_ops:
            raise ValueError(
                "run_accumulated: program has no Optimize-role ops "
                "(call optimizer.minimize first)")
        # the gradients the optimizer consumes are what we accumulate
        grad_names = sorted({
            n for op in suffix_ops for n in op.inputs.get("Grad", []) if n
        })

        state_reads, state_writes = analyze_block_io(block, feed_names, scope)
        write_set = set(state_writes)
        rw_state = [n for n in state_reads if n in write_set]
        ro_state = [n for n in state_reads if n not in write_set]
        # write-only names created by the program: surfaced from the last
        # micro-batch (prefix) or from the suffix, like _compile_steps
        wo_state = [n for n in state_writes if n not in set(rw_state)]
        check = self.check_nan_inf
        nan_check_ops: List[str] = []

        # Fetch split: prefix targets are stashed per micro-batch and
        # returned stacked [K, ...]; Optimize-suffix targets (updated
        # params, lr) return their single post-suffix value — the
        # fetch-from-prefix-only restriction is gone (the pipeline
        # scheduler and plain users both fetch suffix products).
        prefix_avail = set(feed_names) | set(rw_state) | set(ro_state)
        for op in prefix_ops:
            prefix_avail.update(n for n in op.output_arg_names() if n)
        suffix_outputs = {
            n for op in suffix_ops for n in op.output_arg_names() if n
        }
        # suffix takes precedence for names it PRODUCES: fetching an
        # updated param/moment/lr returns the single post-update value
        # (matching PipelineProgram's opt-fetch classification); names
        # only the prefix covers come back stacked per micro-batch
        prefix_fetch = [n for n in fetch_names
                        if n in prefix_avail and n not in suffix_outputs]
        suffix_fetch = [n for n in fetch_names if n in suffix_outputs]
        unknown = [n for n in fetch_names
                   if n not in prefix_avail and n not in suffix_outputs]
        if unknown:
            raise KeyError(
                f"fetch target(s) {unknown} produced by neither the "
                f"fwd/bwd prefix nor the Optimize suffix of this program")

        def acc_fn(feed_vals, rw_vals, ro_vals, base_key):
            rw0 = list(rw_vals)

            def run_prefix(i_key, per_step, rw):
                tctx = TraceContext(
                    program, i_key,
                    is_test=getattr(program, "_is_test", False),
                    check_nan_inf=check,
                )
                env: Dict[str, Any] = {}
                env.update(zip(feed_names, per_step))
                env.update(zip(rw_state, rw))
                env.update(zip(ro_state, ro_vals))
                trace_block(block, env, tctx, ops=prefix_ops)
                new_rw = [env.get(n, v) for n, v in zip(rw_state, rw)]
                # fetch values are association-isolated (barrier): the
                # reduce producing a fetched loss must not fuse with its
                # scan-body packaging, or the same value compiled in a
                # pipeline stage's straight-line program can differ by an
                # ulp — the bit-parity contract of parallel/pipeline
                # (value-dependent, surfaced under a multi-device-touched
                # compiler state).  Fetch-only: env values downstream ops
                # read stay unbarriered.
                fetches = [jax.lax.optimization_barrier(env[n])
                           for n in prefix_fetch]
                wo = [env.get(n) for n in wo_state]
                flags = (
                    jnp.stack([f for _, f in tctx.nan_checks])
                    if check and tctx.nan_checks else jnp.ones((0,), bool)
                )
                return env, new_rw, fetches, wo, flags, tctx

            def body(carry, xs):
                rw, grad_sums = carry
                i, per_step = xs[0], xs[1]
                env, new_rw, fetches, wo, flags, _ = run_prefix(
                    jax.random.fold_in(base_key, i), per_step, rw)
                new_sums = [
                    s + env[g] for s, g in zip(grad_sums, grad_names)
                ]
                return (new_rw, new_sums), (fetches, wo, flags)

            # step 0 traced inline (gives grad-sum init without a
            # throwaway zeros trace), steps 1..k-1 under lax.scan
            env0, rw1, fetches0, wo0, flags0, tctx0 = run_prefix(
                jax.random.fold_in(base_key, 0),
                [v[0] for v in feed_vals], rw0)
            sums0 = [env0[g] for g in grad_names]
            nan_check_ops.clear()
            nan_check_ops.extend(d for d, _ in tctx0.nan_checks)

            if k > 1 and unroll:
                # straight-line micro-batches (the reference
                # multi_batch_merge_pass shape): identical math to the
                # scan, fusion context identical to step 0's inline trace
                rw_u, sums_u = rw1, sums0
                fetch_steps = [fetches0]
                wo_last = wo0
                flag_steps = [flags0]
                for i in range(1, k):
                    (rw_u, sums_u), (f_i, wo_i, fl_i) = body(
                        (rw_u, sums_u), (jnp.asarray(i),
                                         [v[i] for v in feed_vals]))
                    fetch_steps.append(f_i)
                    wo_last = [(wi if wi is not None else wl)
                               for wl, wi in zip(wo_last, wo_i)]
                    flag_steps.append(fl_i)
                rw_f, sums_f = rw_u, sums_u
                fetches = [jnp.stack(fs) for fs in zip(*fetch_steps)]
                all_flags = jnp.stack(flag_steps)
            elif k > 1:
                xs = (jnp.arange(1, k),
                      [v[1:] for v in feed_vals])
                (rw_f, sums_f), (rest, wo_rest, flag_rest) = jax.lax.scan(
                    body, (rw1, sums0), xs)
                fetches = [
                    jnp.concatenate([f0[None], fr], axis=0)
                    for f0, fr in zip(fetches0, rest)
                ]
                wo_last = [
                    (wr[-1] if wr is not None else w0)
                    for w0, wr in zip(wo0, wo_rest)
                ]
                all_flags = jnp.concatenate(
                    [flags0[None], flag_rest], axis=0)
            else:
                rw_f, sums_f = rw1, sums0
                fetches = [f0[None] for f0 in fetches0]
                wo_last = wo0
                all_flags = flags0[None]

            # optimizer suffix ONCE on the averaged gradients
            envf: Dict[str, Any] = {}
            envf.update(zip(rw_state, rw_f))
            envf.update(zip(ro_state, ro_vals))
            for g, s in zip(grad_names, sums_f):
                envf[g] = s / float(k)
            tctxf = TraceContext(
                program, jax.random.fold_in(base_key, k),
                is_test=getattr(program, "_is_test", False),
                check_nan_inf=check,
            )
            trace_block(block, envf, tctxf, ops=suffix_ops)
            nan_check_ops.extend(d for d, _ in tctxf.nan_checks)
            suf_flags = (
                jnp.stack([f for _, f in tctxf.nan_checks])
                if check and tctxf.nan_checks else jnp.ones((0,), bool)
            )
            by_name = dict(zip(rw_state, rw_f))
            by_name.update(zip(wo_state, wo_last))
            # suffix outputs (param updates) win over scanned values
            for n in state_writes:
                if n in envf and envf[n] is not None:
                    by_name[n] = envf[n]
            new_state = [by_name.get(n) for n in state_writes]
            # reassemble fetches in caller order: prefix targets stacked
            # [K, ...], suffix targets as their single post-update value
            fetch_by_name = dict(zip(prefix_fetch, fetches))
            fetch_by_name.update((n, envf[n]) for n in suffix_fetch)
            out_fetches = [fetch_by_name[n] for n in fetch_names]
            return out_fetches, new_state, (all_flags, suf_flags)

        jitted = jax.jit(acc_fn, donate_argnums=(1,))
        return _CompiledEntry(
            lambda f, rw, ro, key: jitted(f, rw, ro, key),
            rw_state, ro_state, state_writes, True,
            nan_check_ops=nan_check_ops if check else None,
            jitted=jitted, run_lock=self._stateful_lock,
        )

    def _compile_steps(self, program, feed_names, fetch_names, scope, steps):
        import jax
        import jax.numpy as jnp

        self._maybe_verify(program, feed_names, fetch_names, scope)
        block = program.global_block()
        state_reads, state_writes = analyze_block_io(block, feed_names, scope)
        write_set = set(state_writes)
        rw_state = [n for n in state_reads if n in write_set]
        ro_state = [n for n in state_reads if n not in write_set]
        # write-only names (created by the program): surfaced from the last
        # step's outputs rather than carried through the scan
        wo_state = [n for n in state_writes if n not in set(rw_state)]

        check = self.check_nan_inf
        nan_check_ops: List[str] = []

        def scan_fn(feed_vals, rw_vals, ro_vals, base_key):
            def body(carry, xs):
                rw, i = carry, xs[0]
                per_step = xs[1]
                tctx = TraceContext(
                    program,
                    jax.random.fold_in(base_key, i),
                    is_test=getattr(program, "_is_test", False),
                    check_nan_inf=check,
                )
                env: Dict[str, Any] = {}
                env.update(zip(feed_names, per_step))
                env.update(zip(rw_state, rw))
                env.update(zip(ro_state, ro_vals))
                trace_block(block, env, tctx)
                new_rw = [env.get(n, v) for n, v in zip(rw_state, rw)]
                fetches = []
                for n in fetch_names:
                    if n not in env:
                        raise KeyError(
                            f"fetch target {n!r} not produced by the program"
                        )
                    fetches.append(env[n])
                wo = [env.get(n) for n in wo_state]
                if check:
                    nan_check_ops.clear()
                    nan_check_ops.extend(d for d, _ in tctx.nan_checks)
                    flags = (
                        jnp.stack([f for _, f in tctx.nan_checks])
                        if tctx.nan_checks
                        else jnp.ones((0,), bool)
                    )
                    return new_rw, (fetches, wo, flags)
                return new_rw, (fetches, wo)

            xs = (jnp.arange(steps), feed_vals)
            final_rw, step_outs = jax.lax.scan(body, list(rw_vals), xs)
            if check:
                stacked, wo_stacked, flag_stack = step_outs
            else:
                stacked, wo_stacked = step_outs
            # state ordering matches state_writes: rw carries final values,
            # write-only vars take their last-step value
            by_name = dict(zip(rw_state, final_rw))
            by_name.update(
                {n: (v[-1] if v is not None else None)
                 for n, v in zip(wo_state, wo_stacked)}
            )
            new_state = [by_name.get(n) for n in state_writes]
            if check:
                return stacked, new_state, flag_stack
            return stacked, new_state

        jitted = jax.jit(scan_fn, donate_argnums=(1,))
        return _CompiledEntry(
            lambda f, rw, ro, key: jitted(f, rw, ro, key),
            rw_state, ro_state, state_writes, True,
            nan_check_ops=nan_check_ops if check else None,
            jitted=jitted, run_lock=self._stateful_lock,
        )

    # -- telemetry internals (callers gate on monitor.enabled()) ---------
    def _note_cache_lookup(self, part_names, key, hit: bool):
        """Count the executable-cache hit/miss and run the RECOMPILE
        DETECTOR.  A miss is a RECOMPILE iff this program-stamp compiled
        before (any key): a program whose keys keep missing — ragged feed
        shapes, churning fetch lists — counts one recompile per miss, and
        the cache-key delta vs the previous lookup is VLOG(1)'d naming
        the changed component.  A program's FIRST compile (startup, a new
        eval program mid-training) is never a recompile, no matter what
        the previous lookup was."""
        from .. import monitor
        from ..log import vlog, vlog_is_on

        monitor.counter(
            "executor.cache_hit" if hit else "executor.cache_miss").inc()
        if len(part_names) != len(key):
            # parallel-array drift guard: a cache-key component added
            # without updating the *_KEY_PARTS tuple would silently
            # mis-attribute recompile causes (zip truncates); telemetry
            # must not raise, so warn and skip the diff instead
            from ..log import warning

            warning("recompile detector: %d key parts named but key has "
                    "%d components — update the _*_KEY_PARTS tuple in "
                    "core/executor.py", len(part_names), len(key))
            return
        # mode-qualified stamp: run/run_steps/run_accumulated executables
        # are distinct, so each mode gets its own first compile for free
        stamp = (part_names, key[part_names.index("program-stamp")])
        with self._detector_lock:
            # per-(mode, program) history: diffing against another
            # program's (or call mode's) key would blame
            # program-stamp/call-mode and bury the component that
            # actually churned
            prev = self._last_key_by_program.get(stamp)
            self._last_key_by_program[stamp] = key
            # a fresh lookup supersedes this stamp's uncommitted pending
            # (the prior compile failed); OTHER stamps' pendings belong
            # to concurrent threads and stay
            self._pending_stamps.discard(stamp)
            if hit:
                return
            if stamp not in self._compiled_stamps:
                # first compile of this program — registered only once
                # the entry lands in the cache (_commit_stamp), so
                # retrying a failed compile is still not a recompile
                self._pending_stamps.add(stamp)
                return
        monitor.counter("executor.recompiles").inc()
        if prev is None:
            changed = ["(no prior lookup of this program)"]
        else:
            changed = [n for n, a, b in zip(part_names, prev, key)
                       if a != b] or ["(key unchanged; cache bypassed)"]
        # the flight recorder keeps the recompile CAUSE history — after a
        # retrace storm kills a run, the dump names which key component
        # churned (tools/trace_report.py aggregates these)
        from ..monitor import flight as _flight

        _flight.record("executor.recompile", changed=changed)
        if vlog_is_on(1):
            vlog(1, "executor recompile: changed key component(s): %s",
                 ", ".join(changed))

    def _commit_stamp(self, part_names, key):
        """The compiled entry reached the cache: future misses of this
        program-stamp (in this call mode) are recompiles — even if the
        first execution later fails (e.g. check_nan_inf raises)."""
        try:
            stamp = (part_names, key[part_names.index("program-stamp")])
        except ValueError:
            return
        with self._detector_lock:
            if stamp in self._pending_stamps:
                self._pending_stamps.discard(stamp)
                self._compiled_stamps.add(stamp)

    def _begin_monitored(self, part_names, key, hit: bool, note: bool = True):
        """Telemetry prologue shared by run/run_steps/run_accumulated:
        returns (enabled, t0).  Zero registry work when FLAGS.monitor is
        off — the hot path pays one flag read.  note=False skips the
        cache-lookup note (run() notes after its double-check resolves
        the true hit/miss)."""
        from ..monitor import enabled

        if not enabled():
            return False, 0.0
        import time as _time

        if note:
            self._note_cache_lookup(part_names, key, hit)
        return True, _time.perf_counter()

    def _finish_monitored(self, mode, mon, t0, compiled_now, feed_vals,
                          fetches, return_numpy):
        """Telemetry epilogue shared by the three run modes: convert the
        fetches (the device sync) and record the call's metrics.

        When monitoring, the np.asarray conversion is timed separately:
        under jax's async dispatch the Python call returns as soon as the
        computation is ENQUEUED, and the first np.asarray blocks until
        the device finishes — so the call decomposes into dispatch time
        (trace/cache-hit bookkeeping + enqueue) and device-wait time (the
        blocking fetch, which bounds actual device execution from above).
        The split is the step-time attribution the cost model's launch
        term is validated against."""
        if not return_numpy:
            outs = list(fetches)
            if mon:
                self._record_run_metrics(mode, t0, compiled_now, feed_vals,
                                         None)
            return outs
        if not mon:
            return [np.asarray(v) for v in fetches]
        import time as _time

        tc0 = _time.perf_counter()
        outs = [np.asarray(v) for v in fetches]
        device_wait_s = _time.perf_counter() - tc0
        self._record_run_metrics(mode, t0, compiled_now, feed_vals, outs,
                                 device_wait_s=device_wait_s)
        return outs

    def _count_error(self, mon):
        """Failed compile/execution: count it so cache_miss vs compiles
        divergence during an incident is explained by executor.errors."""
        if mon:
            import sys

            from .. import monitor
            from ..monitor import flight as _flight

            monitor.counter("executor.errors").inc()
            exc = sys.exc_info()[1]
            _flight.record(
                "executor.error",
                error=(f"{type(exc).__name__}: {str(exc)[:200]}"
                       if exc is not None else "unknown"))

    def _record_run_metrics(self, mode, t0, compiled_now, feed_vals,
                            np_outs, device_wait_s=None):
        """Registry writes for one finished executor call: run wall-time
        (and compile wall-time when this call traced+compiled — jax.jit
        compiles lazily, so the miss call's duration IS the compile cost),
        plus host->device feed bytes, device->host fetch bytes, and — when
        _finish_monitored timed the fetch conversion — the dispatch-vs-
        device-wait decomposition of the call."""
        import time as _time

        from .. import monitor, profiler
        from ..monitor import flight as _flight

        dt = _time.perf_counter() - t0
        # span start bridged to the epoch clock the unified timeline and
        # request traces ride (perf_counter + the import-time offset —
        # `time.time() - dt` would drift off the other spans' stamps
        # under NTP slew)
        from ..monitor import tracing as _tracing

        t0_epoch = _tracing.pc_to_epoch(t0)
        monitor.counter(f"executor.{mode}.calls").inc()
        if compiled_now:
            # the miss call's wall time IS trace+compile(+first run);
            # keep it OUT of run_seconds so run-latency percentiles are
            # not dominated by seconds-scale compile outliers
            monitor.counter("executor.compiles").inc()
            monitor.histogram(
                "executor.compile_seconds",
                buckets=_COMPILE_BUCKETS).observe(dt)
            profiler.add_event("executor.compile", dt)
            _flight.record("executor.compile", mode=mode, t0=t0_epoch,
                           dur=round(dt, 6))
        else:
            monitor.histogram("executor.run_seconds").observe(dt)
            profiler.add_event(f"executor.{mode}", dt)
            span_fields = {}
            if device_wait_s is not None:
                # dispatch = everything before the blocking fetch (Python
                # bookkeeping + XLA enqueue); device_wait = the blocking
                # np.asarray conversion.  Async dispatch means compute
                # overlaps the dispatch window, so device_wait is a LOWER
                # bound on device time and dispatch an upper bound on
                # launch overhead — exactly the pair the cost model's
                # launch term is checked against (tools/perf_report.py).
                dispatch_s = max(dt - device_wait_s, 0.0)
                monitor.histogram(
                    "executor.dispatch_seconds").observe(dispatch_s)
                monitor.histogram(
                    "executor.device_wait_seconds").observe(device_wait_s)
                span_fields = {"dispatch_s": round(dispatch_s, 6),
                               "device_wait_s": round(device_wait_s, 6)}
            _flight.record(f"executor.{mode}", t0=t0_epoch,
                           dur=round(dt, 6), **span_fields)
        fb = sum(int(getattr(v, "nbytes", 0) or 0) for v in feed_vals)
        if fb:
            monitor.counter("executor.feed_bytes").inc(fb)
        if np_outs:
            monitor.counter("executor.fetch_bytes").inc(
                sum(int(getattr(o, "nbytes", 0) or 0) for o in np_outs))
        # request-tracing hook: when a serving batcher armed this thread's
        # executor context (monitor/tracing.py), the call's compile-vs-run
        # wall time lands as a sub-span in every participating request
        # trace; one thread-local read otherwise
        _tracing.note_executor(mode, t0_epoch, dt, compiled_now)

    # -- internals -------------------------------------------------------
    def _maybe_verify(self, program, feed_names, fetch_names, scope):
        """Pre-compile static verification gate (FLAGS_verify_program).

        Runs the paddle_tpu.analysis program verifier BEFORE tracing so
        contract violations (use-before-def, shape mismatches, donation/
        fetch aliasing, unthreaded RNG ops) surface as named findings
        instead of late XLA trace errors — the TPU-side analogue of the
        reference's per-op RuntimeInferShape ENFORCE (operator.cc).

        Cost model: one flag read when off (zero hot-path cost); when on,
        one O(program) walk per (fingerprint, feeds, fetches) signature —
        compile-time only, memoized, so warm serving paths never pay it."""
        from ..flags import FLAGS

        if not FLAGS.verify_program:
            return
        # the scope signature is part of the key for the same reason it
        # is part of the compile-cache key: use-before-def / alias / dead
        # checks read the scope, so a recompile forced by a differently-
        # populated scope must re-verify, not hit the memo
        vkey = (program.fingerprint(),
                self._scope_signature(program, feed_names, scope),
                tuple(feed_names), tuple(fetch_names))
        if vkey in self._verified:
            return
        from ..analysis import verify_or_raise

        # serialized process-wide: the verifier's shape re-inference
        # mutates (then restores) the shared Program's Variable shapes
        with _VERIFY_MUTEX:
            if vkey in self._verified:
                return
            verify_or_raise(program, feed_names=feed_names,
                            fetch_names=fetch_names, scope=scope)
            self._verified.add(vkey)

    def _next_run_id(self) -> int:
        """Draw the next run-counter value under a lock: key-deriving
        programs fold this into their PRNG key, and concurrent serving
        threads must never fold in the same value twice.  A forced id
        (numerics failing-step replay) is consumed exactly once and
        does not advance the counter."""
        with self._counter_lock:
            if self._forced_run_id is not None:
                rid = self._forced_run_id
                self._forced_run_id = None
                return rid
            self._run_counter += 1
            return self._run_counter

    def _numerics_fetch(self, program, fetch_names):
        """Append the instrumented program's packed stats tensors to the
        fetch list (analysis/numerics.py) so the per-step health rows
        ride the existing device->host transfer.  Returns (user fetch
        count, possibly-extended fetch list).  Uninstrumented programs
        pay one getattr."""
        stats_vars = getattr(program, "_numerics_stats_vars", None)
        if not stats_vars:
            return len(fetch_names), fetch_names
        extra = [n for n in stats_vars if n not in fetch_names]
        return len(fetch_names), fetch_names + extra

    def _publish_numerics(self, program, fetch_names, user_n, outs):
        """Strip auto-appended stats tensors off the fetch results and
        hand them to the monitor tier.  Publication is exception-proof:
        telemetry must never fail the run."""
        if len(fetch_names) == user_n and not getattr(
                program, "_numerics_stats_vars", None):
            return outs
        try:
            from ..monitor import numerics as _mnum

            stats_vars = set(program._numerics_stats_vars)
            stats = {n: v for n, v in zip(fetch_names, outs)
                     if n in stats_vars}
            _mnum.publish_step_stats(program, stats)
        except Exception:  # pragma: no cover
            pass
        return outs[:user_n]

    def _maybe_capture_step(self, program, feed, fetch_names, entry,
                            rw_vals, ro_vals, rid):
        """FLAGS_check_numerics=locate: snapshot this step's replay
        context (feed, pre-donation rw-state copies, the PRNG run id)
        so a watchdog nan_loss trip can re-run the failing step
        bit-identically under full per-op instrumentation
        (monitor/numerics.locate_replay).  One flag read when off."""
        from ..flags import FLAGS

        if FLAGS.check_numerics != "locate":
            return
        try:
            import jax.numpy as jnp

            from ..monitor import numerics as _mnum

            if not _mnum.capture_armed():  # a replay run is in flight
                return
            state = {}
            for n, v in zip(entry.rw_state, rw_vals):
                if v is not None:
                    # rw buffers are donated: copy now or never
                    state[n] = jnp.array(v, copy=True)
            for n, v in zip(entry.ro_state, ro_vals):
                if v is not None:
                    state[n] = v
            _mnum.note_step_context({
                "program": program,
                "feed": dict(feed),
                "fetch": list(fetch_names),
                "state": state,
                "run_id": rid,
                "executor": self,
            })
        except Exception:  # pragma: no cover - capture must not fail a step
            pass

    def _scope_signature(self, program, feed_names, scope) -> frozenset:
        """Which program-referenced names resolve to a live scope var.

        analyze_block_io's rw/ro state split depends on scope contents at
        compile time, so the cache key must too — otherwise running the same
        program against a differently-populated scope reuses an executable
        with the wrong state split."""
        # The referenced-name walk is O(program size); memoize it on the
        # program fingerprint so the per-step cost is one scope probe per
        # distinct name, not a full block/op traversal.
        fp = program.fingerprint()
        names = self._ref_names_cache.get(fp)
        if names is None:
            seen = set()
            for blk in program.blocks:
                for op in blk.ops:
                    for n in op.input_arg_names() + op.output_arg_names():
                        if n:
                            seen.add(n)
            names = tuple(seen)
            self._ref_names_cache[fp] = names
        feed_set = set(feed_names)
        return frozenset(
            n
            for n in names
            if n not in feed_set and scope.find_var(n) is not None
        )

    def _to_device_array(self, program, name, value):
        import jax
        import jax.numpy as jnp

        v = program.global_block()._find_var_recursive(name)
        if isinstance(value, jax.Array):
            # already device-resident: never round-trip to host (but honor a
            # declared bfloat16 feed dtype, same as the numpy path)
            if v is not None and v.dtype == "bfloat16" and value.dtype != jnp.bfloat16:
                return value.astype(jnp.bfloat16)
            return value
        arr = np.asarray(value)
        if v is not None and v.dtype and arr.dtype != np.dtype("O"):
            target = v.dtype
            if target == "bfloat16":
                arr = arr.astype(np.float32)
                return jnp.asarray(arr).astype(jnp.bfloat16)
        return jnp.asarray(arr)

    def _compile(self, program, feed, feed_names, fetch_names, scope):
        import jax

        self._maybe_verify(program, feed_names, fetch_names, scope)
        block = program.global_block()
        state_reads, state_writes = analyze_block_io(block, feed_names, scope)

        probe_random = program_uses_random(block)

        write_set = set(state_writes)
        rw_state = [n for n in state_reads if n in write_set]
        ro_state = [n for n in state_reads if n not in write_set]

        check = self.check_nan_inf
        nan_check_ops: List[str] = []

        def run_fn(feed_vals, rw_vals, ro_vals, key=None):
            if key is None:
                key = prng_key(program.random_seed or 0)
            tctx = TraceContext(
                program, key, is_test=getattr(program, "_is_test", False),
                check_nan_inf=check,
            )
            env: Dict[str, Any] = {}
            for n, v in zip(feed_names, feed_vals):
                env[n] = v
            for n, v in zip(rw_state, rw_vals):
                env[n] = v
            for n, v in zip(ro_state, ro_vals):
                env[n] = v
            trace_block(block, env, tctx)
            fetches = []
            for n in fetch_names:
                if n not in env:
                    raise KeyError(
                        f"fetch target {n!r} was not produced by the program"
                    )
                fetches.append(env[n])
            new_state = [env.get(n) for n in state_writes]
            if check:
                nan_check_ops.clear()
                nan_check_ops.extend(d for d, _ in tctx.nan_checks)
                import jax.numpy as jnp

                flags = jnp.stack(
                    [f for _, f in tctx.nan_checks]
                ) if tctx.nan_checks else jnp.ones((0,), bool)
                return fetches, new_state, flags
            return fetches, new_state

        if probe_random:
            jitted = jax.jit(run_fn, donate_argnums=(1,))
        else:
            jitted = jax.jit(
                lambda f, rw, ro: run_fn(f, rw, ro), donate_argnums=(1,)
            )
        return _CompiledEntry(
            jitted, rw_state, ro_state, state_writes, probe_random,
            nan_check_ops=nan_check_ops if check else None,
            jitted=jitted, run_lock=self._stateful_lock,
        )


def latest_jitted_entry(exe: "Executor") -> _CompiledEntry:
    """The most recently compiled cache entry that kept its AOT handle
    (`entry.jitted`) — the ONE introspection hook for re-lowering an
    executed computation to optimized-HLO text or CompiledMemoryStats
    (tools/hlo_diag.py, bench.py memory_probe, memory.xla_cross_check,
    the kernel-fusion tests).  Dict insertion order is compile order, so
    the last entry is the caller's most recent run/run_steps compile."""
    entries = [e for e in exe._cache.values() if e.jitted is not None]
    if not entries:
        raise RuntimeError(
            "no compiled jitted entry in the executor cache — run the "
            "program once before AOT introspection")
    return entries[-1]


# ---------------------------------------------------------------------------
# feed/fetch helpers (reference: framework/feed_fetch_method.cc)
# ---------------------------------------------------------------------------


def as_numpy(value):
    if isinstance(value, (list, tuple)):
        return [as_numpy(v) for v in value]
    return np.asarray(value)
