"""CoNLL-2005 SRL dataset (reference: python/paddle/dataset/conll05.py —
word/predicate/label dicts + test() reader yielding the 9-slot SRL sample
the label_semantic_roles book model consumes).

The real corpus is license-restricted (the reference downloads only the
test split); the synthetic mode generates IOB-tagged predicate/argument
structures with learnable word->role structure."""

from __future__ import annotations

import numpy as np

_WORDS = 800
_LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]


def word_dict(synthetic=True):
    return {f"w{i}": i for i in range(_WORDS)} | {"<unk>": _WORDS}


def verb_dict(synthetic=True):
    return {f"v{i}": i for i in range(50)}


def label_dict(synthetic=True):
    return {l: i for i, l in enumerate(_LABELS)}


def get_dict(synthetic=True):
    """reference conll05.get_dict(): (word_dict, verb_dict, label_dict)."""
    return word_dict(synthetic), verb_dict(synthetic), label_dict(synthetic)


def test(synthetic=True, n_samples=300):
    """Yields (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_id,
    mark, label_ids) — the 9 feature slots of the reference SRL pipeline
    (predicate-context windows + predicate mark)."""
    if not synthetic:
        raise RuntimeError(
            "conll05: the real corpus is license-restricted and this image "
            "has no egress — only synthetic mode is available")

    def reader():
        rng = np.random.RandomState(25)
        wd = word_dict(synthetic)
        ld = label_dict(synthetic)
        for _ in range(n_samples):
            ln = int(rng.randint(5, 18))
            words = rng.randint(0, _WORDS, ln).tolist()
            v_pos = int(rng.randint(0, ln))
            verb = words[v_pos] % 50
            labels = ["O"] * ln
            labels[v_pos] = "B-V"
            # A0 span before the verb, A1 span after (when room): role
            # derivable from position relative to the predicate -> learnable
            if v_pos >= 2:
                labels[v_pos - 2] = "B-A0"
                labels[v_pos - 1] = "I-A0"
            if v_pos + 2 < ln:
                labels[v_pos + 1] = "B-A1"
                labels[v_pos + 2] = "I-A1"

            def ctx(off):
                i = min(max(v_pos + off, 0), ln - 1)
                return [words[i]] * ln

            mark = [1 if i == v_pos else 0 for i in range(ln)]
            yield (
                words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
                [verb] * ln, mark, [ld[l] for l in labels],
            )

    return reader
