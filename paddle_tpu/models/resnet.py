"""ResNet (reference: benchmark/fluid/models/resnet.py — conv_bn_layer,
shortcut, basicblock/bottleneck, resnet_imagenet/resnet_cifar10).

TPU notes: NCHW layout kept for API parity (XLA relayouts for the MXU);
bottleneck widths are multiples of 128 at most depths, mapping cleanly onto
the 128x128 systolic array."""

from __future__ import annotations

from .. import layers


def _fused_bn_site(is_train, data_format):
    """The fused conv+BN route (PERF.md r07) arms for NHWC training
    graphs under FLAGS_fused_bn (default on).  NCHW and inference keep
    the reference conv2d + batch_norm [+ elementwise_add] composition —
    with the flag off the emitted graph is op-for-op identical to the
    pre-fusion builder (asserted in tests/test_conv_bn.py)."""
    from ..flags import FLAGS

    return bool(FLAGS.fused_bn) and data_format == "NHWC" and is_train


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_train=True, data_format="NCHW", residual=None):
    """conv -> batch_norm [-> + residual] [-> act].  Fused sites emit ONE
    conv2d_bn op (1x1-conv+stats-epilogue / one-pass-stats kernels with
    the fused apply — kernels/conv_bn.py); the reference route keeps the
    separate ops, with a trailing residual handled by the same
    elementwise_add(residual, bn, act) the original blocks used."""
    if _fused_bn_site(is_train, data_format):
        return layers.conv2d_bn(
            input=input,
            num_filters=ch_out,
            filter_size=filter_size,
            stride=stride,
            padding=padding,
            act=act,
            residual=residual,
            is_test=not is_train,
            data_format=data_format,
        )
    conv1 = layers.conv2d(
        input=input,
        filter_size=filter_size,
        num_filters=ch_out,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=False,
        data_format=data_format,
    )
    bn = layers.batch_norm(
        input=conv1, act=None if residual is not None else act,
        is_test=not is_train, data_layout=data_format)
    if residual is not None:
        return layers.elementwise_add(residual, bn, act=act)
    return bn


def shortcut(input, ch_out, stride, is_train=True, data_format="NCHW"):
    ch_in = input.shape[-1 if data_format == "NHWC" else 1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_train=is_train, data_format=data_format)
    return input


def basicblock(input, ch_out, stride, is_train=True, data_format="NCHW"):
    short = shortcut(input, ch_out, stride, is_train=is_train,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_train=is_train,
                          data_format=data_format)
    return conv_bn_layer(conv1, ch_out, 3, 1, 1, act="relu",
                         residual=short, is_train=is_train,
                         data_format=data_format)


def bottleneck(input, ch_out, stride, is_train=True, data_format="NCHW"):
    short = shortcut(input, ch_out * 4, stride, is_train=is_train,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_train=is_train,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_train=is_train,
                          data_format=data_format)
    return conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act="relu",
                         residual=short, is_train=is_train,
                         data_format=data_format)


def layer_warp(block_func, input, ch_out, count, stride, is_train=True,
               data_format="NCHW"):
    res_out = block_func(input, ch_out, stride, is_train=is_train,
                         data_format=data_format)
    for i in range(count - 1):
        res_out = block_func(res_out, ch_out, 1, is_train=is_train,
                             data_format=data_format)
    return res_out


def resnet_imagenet(input, class_dim=1000, depth=50, is_train=True,
                    data_format="NCHW"):
    """data_format NHWC: input is transposed once up front and the whole
    tower runs channel-last (measured ~18%% faster conv chains on v5e;
    parameters keep their NCHW-world shapes either way)."""
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    if data_format == "NHWC":
        input = layers.transpose(input, [0, 2, 3, 1])
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2, padding=3,
                          is_train=is_train, data_format=data_format)
    pool1 = layers.pool2d(
        input=conv1, pool_type="max", pool_size=3, pool_stride=2,
        pool_padding=1, data_format=data_format,
    )
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_train=is_train,
                      data_format=data_format)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_train=is_train,
                      data_format=data_format)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_train=is_train,
                      data_format=data_format)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_train=is_train,
                      data_format=data_format)
    pool2 = layers.pool2d(input=res4, pool_type="avg", global_pooling=True,
                          data_format=data_format)
    out = layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim=10, depth=32, is_train=True):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_train=is_train)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_train=is_train)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_train=is_train)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_train=is_train)
    pool = layers.pool2d(input=res3, pool_type="avg", pool_size=8, pool_stride=1)
    out = layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def build_train_net(class_dim=1000, image_shape=(3, 224, 224), depth=50,
                    lr=0.1, with_optimizer=True, input_u8=False,
                    data_format="NCHW"):
    """End-to-end ResNet train graph (reference: resnet.py get_model).

    input_u8: declare the image feed as uint8 and normalize (/255) inside
    the compiled program — the streaming input pipeline then ships the raw
    decode output with 4x less host->device traffic and zero extra eager
    dispatches (reference pipelines feed fp32; this is the TPU-first wire
    format)."""
    from .. import optimizer as opt_mod

    if input_u8:
        img = layers.data(name="image", shape=list(image_shape),
                          dtype="uint8")
        img_f = layers.scale(layers.cast(img, "float32"),
                             scale=1.0 / 255.0)
    else:
        img = layers.data(name="image", shape=list(image_shape),
                          dtype="float32")
        img_f = img
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = resnet_imagenet(img_f, class_dim=class_dim, depth=depth,
                              data_format=data_format)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=predict, label=label)
    if with_optimizer:
        optimizer = opt_mod.Momentum(learning_rate=lr, momentum=0.9)
        optimizer.minimize(avg_cost)
    return img, label, avg_cost, acc, predict
