"""Anomaly watchdog: the framework notices a sick training run by itself.

Reference role: FLAGS_check_nan_inf validates op outputs (operator.cc:943)
and the master service marks timed-out workers dead
(go/master/service.go:313) — but nothing in the reference watched the LOSS
CURVE or the step clock.  This watchdog is fed by StepMonitor (one
`observe_step` per completed step) and detects:

  * NaN/Inf loss — the run is already dead, say so at the step it died;
  * loss spike — z-score of the new loss against a rolling window
    (mean/std over the last `window` finite losses);
  * throughput collapse — a step taking `collapse_factor`× the rolling
    median step time (feed starvation, a recompile storm, a sick host);
  * hang — NO step completed within `hang_factor`× the rolling median,
    checked from a daemon thread (the in-band checks above can only run
    when a step completes; a hang by definition never reaches them).

Trip actions (pluggable, FLAGS.watchdog_action default):
  * "log"   — one warning per trip kind (rate-limited), flight event;
  * "dump"  — "log" + dump the flight record (trigger "watchdog") so the
              black box lands on disk while the run is still sick;
  * "raise" — "dump" + raise WatchdogError in the training thread (hang
              trips interrupt the main thread instead — for tests/CI).

An `on_trip(trip)` callback overrides the action entirely (serving hosts
wire pagers there).  Cost when FLAGS.monitor is off: nothing — StepMonitor
only calls observe_step from its already-gated path, and arm() refuses to
start the hang thread.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, List, Optional

from . import flight as _flight
from . import registry as _registry


class WatchdogError(RuntimeError):
    """A watchdog trip with action='raise'."""


class Trip:
    """One detected anomaly (also the flight-event payload)."""

    __slots__ = ("kind", "step", "detail", "ts")

    def __init__(self, kind: str, step: Optional[int], detail: str):
        self.kind = kind
        self.step = step
        self.detail = detail
        self.ts = time.time()

    def __repr__(self):
        return f"Watchdog trip [{self.kind}] at step {self.step}: {self.detail}"


class Watchdog:
    def __init__(
        self,
        action: Optional[str] = None,
        window: int = 50,
        min_steps: int = 8,
        z_threshold: float = 8.0,
        collapse_factor: float = 5.0,
        hang_factor: float = 10.0,
        hang_floor_s: float = 5.0,
        on_trip: Optional[Callable[[Trip], None]] = None,
    ):
        """window: rolling horizon (losses and step times); min_steps:
        suppress spike/collapse/hang until this many steps are observed
        (compile-time steps would false-trip everything); hang_floor_s:
        never call a hang before this many wall seconds, whatever the
        median says (guards tiny-step test loops)."""
        if action is None:
            from ..flags import FLAGS

            action = FLAGS.watchdog_action
        if action not in ("log", "dump", "raise"):
            raise ValueError(f"watchdog action {action!r} "
                             "(want log|dump|raise)")
        self.action = action
        self.on_trip = on_trip
        self.min_steps = min_steps
        self.z_threshold = z_threshold
        self.collapse_factor = collapse_factor
        self.hang_factor = hang_factor
        self.hang_floor_s = hang_floor_s
        self._losses: "collections.deque[float]" = collections.deque(
            maxlen=max(4, window))
        self._dts: "collections.deque[float]" = collections.deque(
            maxlen=max(4, window))
        self._steps = 0
        self._last_step_t: Optional[float] = None
        self._lock = threading.Lock()
        self.trips: List[Trip] = []
        self._warned_kinds: set = set()
        # in-band trips latch once per kind: a run whose loss is stuck at
        # NaN must not rewrite the flight dump (and grow self.trips) on
        # every remaining step; the hang monitor has its own per-episode
        # latch (_hang_tripped) so recovered-then-hung-again still fires
        self._fired_kinds: set = set()
        self._hang_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._hang_tripped = False

    # -- in-band checks (called by StepMonitor per completed step) -------
    def observe_step(self, step: int, loss: Optional[float],
                     dt: float) -> Optional[Trip]:
        """Feed one completed step; returns the Trip if one fired (after
        the action ran — 'raise' raises instead of returning)."""
        with self._lock:
            self._steps += 1
            self._last_step_t = time.monotonic()
            self._hang_tripped = False  # progress: re-arm the hang trip
            prev_losses = list(self._losses)
            median_dt = self._median(self._dts)
            self._dts.append(dt)
            warmed = self._steps > self.min_steps

        trip = None
        if loss is not None and not math.isfinite(loss):
            trip = Trip("nan_loss", step,
                        f"non-finite loss {loss!r} at step {step}")
        elif loss is not None and warmed and len(prev_losses) >= 4:
            mean = sum(prev_losses) / len(prev_losses)
            var = sum((x - mean) ** 2
                      for x in prev_losses) / len(prev_losses)
            std = math.sqrt(var)
            if std > 0:
                z = (loss - mean) / std
                if z > self.z_threshold:
                    trip = Trip(
                        "loss_spike", step,
                        f"loss {loss:.6g} is {z:.1f} sigma above the "
                        f"rolling mean {mean:.6g} (std {std:.3g}, "
                        f"window {len(prev_losses)})")
        if (trip is None and warmed and median_dt is not None
                and median_dt > 0 and dt > self.collapse_factor * median_dt):
            trip = Trip(
                "throughput_collapse", step,
                f"step took {dt:.3f}s vs rolling median {median_dt:.3f}s "
                f"({dt / median_dt:.1f}x, threshold "
                f"{self.collapse_factor:g}x)")
        if loss is not None and math.isfinite(loss):
            with self._lock:
                self._losses.append(float(loss))
        if trip is not None:
            if trip.kind in self._fired_kinds:
                return None  # already reported this failure mode
            self._fired_kinds.add(trip.kind)
            self._fire(trip)
        return trip

    # -- hang monitor (daemon thread) ------------------------------------
    def arm(self, poll_interval_s: float = 1.0) -> bool:
        """Start the hang monitor.  Refuses (returns False) when
        FLAGS.monitor is off — the watchdog rides the telemetry gate."""
        if not _registry.enabled():
            return False
        if self._hang_thread is not None and self._hang_thread.is_alive():
            return True
        self._stop.clear()
        self._hang_thread = threading.Thread(
            target=self._hang_loop, args=(poll_interval_s,),
            name="paddle-tpu-watchdog", daemon=True)
        self._hang_thread.start()
        return True

    def disarm(self) -> None:
        self._stop.set()
        t = self._hang_thread
        if t is not None:
            t.join(timeout=2.0)
        self._hang_thread = None

    def _hang_loop(self, poll_interval_s: float) -> None:
        while not self._stop.wait(poll_interval_s):
            with self._lock:
                last_t = self._last_step_t
                median_dt = self._median(self._dts)
                steps = self._steps
                tripped = self._hang_tripped
            step = _flight.default_recorder().last_step
            if (tripped or last_t is None or steps <= self.min_steps
                    or median_dt is None):
                continue
            idle = time.monotonic() - last_t
            limit = max(self.hang_factor * median_dt, self.hang_floor_s)
            if idle > limit:
                with self._lock:
                    self._hang_tripped = True  # once per hang episode
                self._fire(Trip(
                    "hang", step,
                    f"no step completed for {idle:.1f}s (limit {limit:.1f}s "
                    f"= max({self.hang_factor:g} x median "
                    f"{median_dt:.3f}s, floor {self.hang_floor_s:g}s))"),
                    from_hang_thread=True)

    # -- trip plumbing ----------------------------------------------------
    @staticmethod
    def _median(xs) -> Optional[float]:
        s = sorted(xs)
        return s[len(s) // 2] if s else None

    def _fire(self, trip: Trip, from_hang_thread: bool = False) -> None:
        self.trips.append(trip)
        _flight.record("watchdog.trip", trip=trip.kind, step=trip.step,
                       detail=trip.detail)
        if _registry.enabled():
            _registry.counter(f"watchdog.trips.{trip.kind}").inc()
        verdict = None
        if trip.kind == "nan_loss":
            # numerics tier: localize the NaN's origin before dumping —
            # in locate mode this replays the failing step bit-identically
            # under full per-op instrumentation and names the first op in
            # topological order with a non-finite output; in summary mode
            # it falls back to the step's already-fetched stat rows.
            # Exception-proof and lazily imported: a broken replay must
            # not swallow the trip, and the off path stays import-free.
            try:
                from . import numerics as _numerics

                verdict = _numerics.handle_nan_trip(step=trip.step)
            except Exception:
                verdict = None
        if self.on_trip is not None:
            self.on_trip(trip)
            return
        from ..log import warning

        if trip.kind not in self._warned_kinds:  # one warn per trip kind
            self._warned_kinds.add(trip.kind)
            warning("%s", trip)
        if self.action in ("dump", "raise"):
            extra = {"trip": trip.kind, "trip_step": trip.step,
                     "trip_detail": trip.detail}
            if verdict is not None:
                extra["numerics"] = verdict
            _flight.dump(trigger="watchdog", extra=extra)
        if self.action == "raise":
            if from_hang_thread:
                # can't raise into the training thread from here; the
                # conventional kill-for-tests is interrupting main
                import _thread

                _thread.interrupt_main()
            else:
                raise WatchdogError(str(trip))
