#!/usr/bin/env python
"""loadgen: closed/open-loop load-test harness for the serving tier.

Drives a running InferenceServer (`python -m paddle_tpu.serving ...`)
with concurrent JSON requests of ragged batch sizes, measures
client-side latency percentiles + QPS, scrapes /metrics before/after for
the server-side story (compile counters, batch-fill, padded rows), and
emits ONE JSON artifact — the QPS/p99-vs-batching-policy record the
ROADMAP serving item asks for (tools/run_ci.sh archives it).

  closed loop:  --concurrency C workers, each firing its next request as
                soon as the previous answers (throughput-bound: measures
                the server's saturated QPS);
  open loop:    --qps R arrivals on a fixed schedule regardless of
                completions (latency-under-offered-load; reports
                schedule lag so an overloaded run is self-describing).

Feed shapes/dtypes are discovered from GET /v1/models/<name>; batch
sizes cycle through --batch-sizes so the request stream is
shape-varying (the dynamic batcher's pad-to-bucket path, not one warm
signature).

Generation mode (--generate, closed loop): drives POST :generate on a
generation model (serving/generation.py continuous token-level
batching) with synthetic prompts and reports TTFT p50/p99 (server-side,
from the response meta) plus aggregate tokens/sec alongside the usual
latency/QPS/compile-delta story.

Usage:
  python tools/loadgen.py --url http://127.0.0.1:8000 --model demo \
      --requests 300 --concurrency 8 --out loadgen.json
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time
import urllib.request
from urllib.parse import urlparse

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_retry_module():
    """utils/retry.py loaded by FILE PATH: loadgen is a lightweight
    client tool that must not import the framework (and its jax stack)
    just to back off.  retry.py's module surface is stdlib-only; its
    lazy telemetry hook degrades to a no-op outside the package."""
    import importlib.util

    path = os.path.join(REPO_ROOT, "paddle_tpu", "utils", "retry.py")
    spec = importlib.util.spec_from_file_location("_paddle_tpu_retry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_retry = _load_retry_module()


# ---------------------------------------------------------------------------
# prometheus text parsing (scrape-side metrics for the artifact)
# ---------------------------------------------------------------------------


def parse_prometheus(text: str):
    """-> (scalars {name: value}, histograms {name: {buckets, sum, count}})."""
    scalars, hists = {}, {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value = line.rsplit(None, 1)
            value = float(value)
        except ValueError:
            continue
        if "{" in name_part:
            name, rest = name_part.split("{", 1)
            if name.endswith("_bucket"):
                base = name[: -len("_bucket")]
                le = rest.split('le="', 1)[1].split('"', 1)[0]
                le = float("inf") if le == "+Inf" else float(le)
                hists.setdefault(base, {"buckets": [], "sum": 0.0,
                                        "count": 0})
                hists[base]["buckets"].append([le, value])
                continue
            scalars[name_part] = value
        elif name_part.endswith("_sum"):
            hists.setdefault(name_part[:-4], {"buckets": [], "sum": 0.0,
                                              "count": 0})["sum"] = value
        elif name_part.endswith("_count"):
            hists.setdefault(name_part[:-6], {"buckets": [], "sum": 0.0,
                                              "count": 0})["count"] = value
        else:
            scalars[name_part] = value
    return scalars, hists


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _get_json(url: str, timeout: float = 10.0):
    return json.loads(_get(url, timeout))


# ---------------------------------------------------------------------------
# request synthesis
# ---------------------------------------------------------------------------


def synth_feed(feeds: dict, rows: int, rng: np.random.RandomState) -> dict:
    """Random inputs matching the model's declared feed specs."""
    out = {}
    for name, spec in feeds.items():
        shape = spec.get("shape") or [-1]
        item = [int(d) if int(d) > 0 else 1 for d in shape[1:]]
        dtype = spec.get("dtype", "float32")
        if "int" in dtype:
            out[name] = rng.randint(0, 4, size=[rows] + item).tolist()
        else:
            out[name] = rng.randn(rows, *item).astype("float32").tolist()
    return out


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []
        self.errors = 0          # TERMINAL failures (after retries)
        self.errors_by_kind = {}  # status code / "transport" -> count
        self.sheds = 0           # 429 responses seen (incl. retried ones)
        self.retry_after_seen = 0  # 429/503s that carried a Retry-After
        self.retries = 0         # backoff sleeps performed
        self.status_counts = {}  # every non-2xx response seen, by code
        self.lag = []  # open loop: send lateness vs schedule
        self.ttfts_ms = []  # generation mode: server-side TTFT per req
        self.tokens = 0     # generation mode: tokens received
        self.traced = []    # --trace: (latency_s, trace_id) per success

    def ok(self, dt: float, lag: float = 0.0, ttft_ms=None, tokens=0,
           trace_id=None):
        with self.lock:
            self.latencies.append(dt)
            if lag:
                self.lag.append(lag)
            if ttft_ms is not None:
                self.ttfts_ms.append(float(ttft_ms))
            self.tokens += tokens
            if trace_id is not None:
                self.traced.append((dt, trace_id))

    def saw_status(self, code: int):
        with self.lock:
            k = str(code)
            self.status_counts[k] = self.status_counts.get(k, 0) + 1
            if code == 429:
                self.sheds += 1

    def retried(self):
        with self.lock:
            self.retries += 1

    def terminal(self, kind: str):
        with self.lock:
            self.errors += 1
            self.errors_by_kind[kind] = \
                self.errors_by_kind.get(kind, 0) + 1


class _Conn:
    """One persistent keep-alive connection per worker thread (the server
    speaks HTTP/1.1): connection setup is paid once per worker, not once
    per request, so the measurement sees the serving tier and not the
    client's TCP churn.  Reconnects transparently on a dropped socket."""

    def __init__(self, url: str, timeout: float):
        p = urlparse(url)
        self.host, self.port = p.hostname, p.port
        self.timeout = timeout
        self.conn = None

    def request_raw(self, target: str, body: bytes, headers=None):
        """POST; returns (status, headers dict, body bytes), or None on
        a transport failure (one transparent reconnect)."""
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        for attempt in (0, 1):
            try:
                if self.conn is None:
                    self.conn = http.client.HTTPConnection(
                        self.host, self.port, timeout=self.timeout)
                self.conn.request("POST", target, body=body,
                                  headers=hdrs)
                r = self.conn.getresponse()
                data = r.read()
                return r.status, dict(r.getheaders()), data
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt:
                    return None
        return None

    def close(self):
        if self.conn is not None:
            try:
                self.conn.close()
            except OSError:
                pass
            self.conn = None


def _retry_after_hint(headers: dict, data: bytes):
    """Server back-off hint on a 429/503: the JSON body's sub-second
    retry_after_s preferred, else the integer Retry-After header."""
    try:
        v = json.loads(data).get("retry_after_s")
        if v is not None:
            return float(v)
    except (ValueError, AttributeError):
        pass
    try:
        return float(headers.get("Retry-After", ""))
    except (TypeError, ValueError):
        return None


def make_traceparent(nonce: str, i: int) -> str:
    """Client-generated W3C traceparent for request `i`: the client KNOWS
    the trace id before sending, so the artifact can fetch the server's
    decomposition for its own slowest requests afterwards (the client <->
    server correlation loop)."""
    return (f"00-{nonce}{i & 0xFFFFFFFFFFFFFFFF:016x}"
            f"-{(i % 0xFFFFFFFFFFFFFFF) + 1:016x}-01")


def _send_with_retry(conn: _Conn, target: str, body: bytes,
                     stats: _Stats, retries: int, seed: int,
                     headers=None, deadline_s=None):
    """POST with jittered exponential backoff (utils/retry.backoff_delays
    — the shared production policy) on transport failures and 429/503,
    honoring the server's Retry-After: the sleep is
    max(jittered backoff, server hint).  `deadline_s` is the request's
    own timeout_s: the backoff generator's sleep budget (it stops
    yielding once cumulative sleep would exceed it) AND a hard clamp on
    the Retry-After hint — a client must never still be backing off a
    request whose deadline already passed.  Returns (response bytes,
    served-attempt latency seconds) on 2xx — the latency of the attempt
    the server actually SERVED, excluding backoff sleeps, so the
    artifact's percentiles measure the server and not the retry policy —
    or (None, None) after recording the terminal outcome."""
    delays = _retry.backoff_delays(max(0, retries), base_delay=0.05,
                                   max_delay=2.0, seed=seed,
                                   deadline_s=deadline_s)
    deadline = (time.perf_counter() + deadline_s
                if deadline_s is not None else None)
    while True:
        t0 = time.perf_counter()
        resp = conn.request_raw(target, body, headers=headers)
        dt = time.perf_counter() - t0
        if resp is None:
            kind, retryable, hint = "transport", True, None
        else:
            status, headers, data = resp
            if 200 <= status < 300:
                return data, dt
            stats.saw_status(status)
            kind = str(status)
            # a shed (429) or unavailable (503) is the server telling
            # us to come back — retry; 4xx/500/504 are terminal (the
            # request itself is bad, crashed, or already missed its
            # deadline — re-sending it spends capacity for nothing)
            retryable = status in (429, 503)
            hint = (_retry_after_hint(headers, data)
                    if retryable else None)
            if hint is not None:
                with stats.lock:
                    stats.retry_after_seen += 1
        if not retryable:
            stats.terminal(kind)
            return None, None
        try:
            d = next(delays)
        except StopIteration:
            stats.terminal(kind)
            return None, None
        sleep = max(d, hint or 0.0)
        if deadline is not None:
            sleep = min(sleep, max(0.0, deadline - time.perf_counter()))
        stats.retried()
        time.sleep(sleep)


def _fire(conn: _Conn, model: str, body: bytes, precision: str,
          stats: _Stats, lag: float = 0.0, retries: int = 0,
          seed: int = 0, trace_id=None, headers=None,
          deadline_s=None) -> None:
    target = f"/v1/models/{model}:predict"
    if precision != "fp32":
        target += f"?precision={precision}"
    data, dt = _send_with_retry(conn, target, body, stats, retries, seed,
                                headers=headers, deadline_s=deadline_s)
    if data is not None:
        stats.ok(dt, lag, trace_id=trace_id)


def _fire_generate(conn: _Conn, model: str, body: bytes,
                   stats: _Stats, retries: int = 0, seed: int = 0,
                   trace_id=None, headers=None,
                   deadline_s=None) -> None:
    """Prompt-in/tokens-out request: records the server-side TTFT from
    the response meta (the continuous batcher stamps time-to-first-token
    at the decode step that produced it) and the generated token count
    (client tokens/sec = sum(tokens) / wall)."""
    data, dt = _send_with_retry(conn, f"/v1/models/{model}:generate",
                                body, stats, retries, seed,
                                headers=headers, deadline_s=deadline_s)
    if data is None:
        return
    try:
        payload = json.loads(data)
        meta = payload.get("meta") or {}
        stats.ok(dt,
                 ttft_ms=meta.get("ttft_ms"),
                 tokens=len(payload.get("tokens") or ()),
                 trace_id=trace_id)
    except ValueError:
        stats.terminal("bad_json")


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", required=True,
                   help="server base url, e.g. http://127.0.0.1:8000")
    p.add_argument("--model", required=True)
    p.add_argument("--requests", type=int, default=300)
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--qps", type=float, default=100.0,
                   help="open-loop offered arrival rate")
    p.add_argument("--batch-sizes", default="1,2,3,4",
                   help="request batch sizes, cycled (shape-varying "
                        "stream)")
    p.add_argument("--precision", default="fp32")
    p.add_argument("--generate", action="store_true",
                   help="generation mode: drive POST :generate on a "
                        "generation model (prompt-in/tokens-out); "
                        "reports TTFT p50/p99 (server-side, from "
                        "response meta) and aggregate tokens/sec")
    p.add_argument("--prompt-len", type=int, default=4,
                   help="generation mode: synthetic prompt length")
    p.add_argument("--max-tokens", type=int, default=None,
                   help="generation mode: per-request token budget "
                        "(default: the model's max_tokens)")
    p.add_argument("--timeout-s", type=float, default=30.0,
                   help="per-request deadline, PROPAGATED to the server "
                        "(the body's timeout_s: the scheduler drops the "
                        "request past it instead of executing it); the "
                        "transport timeout is this + 10s")
    p.add_argument("--max-retries", type=int, default=2,
                   help="retry budget per request on transport failures "
                        "and 429/503 sheds (jittered exponential backoff "
                        "honoring the server's Retry-After)")
    p.add_argument("--max-error-rate", type=float, default=0.0,
                   help="exit nonzero when the TERMINAL error rate "
                        "(errors after retries / requests) exceeds this "
                        "(CI-gate consumable; 429s retried to success "
                        "are not errors)")
    p.add_argument("--trace", action="store_true",
                   help="send a client-generated W3C traceparent header "
                        "per request (the server must run with "
                        "FLAGS_trace_requests=1) and, after the run, "
                        "fetch the server-side latency decomposition of "
                        "the slowest requests from /v1/traces/<id> into "
                        "the artifact's slow_traces field — the client<->"
                        "server correlation loop")
    p.add_argument("--trace-top", type=int, default=5,
                   help="how many slowest requests to resolve against "
                        "/v1/traces (with --trace)")
    p.add_argument("--router", action="store_true",
                   help="the url is a serving ROUTER (serving/router.py "
                        "fleet front-end): scrape its failover/hedge/"
                        "eviction counters and the /v1/replicas fleet "
                        "snapshot into the artifact's router section "
                        "(model discovery and requests proxy through "
                        "unchanged)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="",
                   help="write the JSON artifact here (always printed to "
                        "stdout)")
    args = p.parse_args(argv)

    info = None
    for m in _get_json(f"{args.url}/v1/models")["models"]:
        if m["name"] == args.model:
            info = m
            break
    if info is None:
        print(f"loadgen: no model {args.model!r} at {args.url}",
              file=sys.stderr)
        return 2
    rng = np.random.RandomState(args.seed)
    if args.generate:
        if args.mode != "closed":
            print("loadgen: --generate supports closed loop only",
                  file=sys.stderr)
            return 2
        if info.get("type") != "generation":
            print(f"loadgen: model {args.model!r} is not a generation "
                  f"model (no :generate endpoint)", file=sys.stderr)
            return 2
        sizes = []
        vocab = int(info["vocab_size"])
        plen = min(args.prompt_len, int(info["max_prompt_len"]))
        mt = args.max_tokens or int(info["max_tokens"])
        # a handful of distinct prompts, cycled (pre-serialized)
        bodies = [
            json.dumps({
                "prompt": rng.randint(2, vocab, plen).tolist(),
                "max_tokens": mt,
                "timeout_s": args.timeout_s,
            }).encode()
            for _ in range(8)
        ]
    else:
        sizes = [int(s) for s in args.batch_sizes.split(",") if s.strip()]
        # pre-serialized bodies (one per batch size): the generator must
        # not bottleneck the measurement.  timeout_s rides in the body —
        # the deadline the server propagates through its batcher (an
        # expired request is dropped before dispatch, not executed)
        bodies = [
            json.dumps(
                {"inputs": synth_feed(info["feeds"], b, rng),
                 "timeout_s": args.timeout_s}).encode()
            for b in sizes
        ]

    # --trace: client-generated trace ids (one nonce per run keeps ids
    # unique against a long-lived server's bounded trace store)
    trace_nonce = os.urandom(8).hex() if args.trace else None

    def _trace_of(i):
        """(trace_id, headers) for request i, or (None, None)."""
        if trace_nonce is None:
            return None, None
        tp = make_traceparent(trace_nonce, i)
        return tp.split("-")[1], {"traceparent": tp}

    prom_before = parse_prometheus(_get(f"{args.url}/metrics").decode())
    stats = _Stats()
    t_start = time.perf_counter()

    if args.mode == "closed":
        counter = [0]
        lock = threading.Lock()

        def worker():
            conn = _Conn(args.url, args.timeout_s + 10.0)
            try:
                while True:
                    with lock:
                        i = counter[0]
                        if i >= args.requests:
                            return
                        counter[0] += 1
                    tid, hdrs = _trace_of(i)
                    if args.generate:
                        _fire_generate(conn, args.model,
                                       bodies[i % len(bodies)], stats,
                                       retries=args.max_retries, seed=i,
                                       trace_id=tid, headers=hdrs,
                                       deadline_s=args.timeout_s)
                    else:
                        _fire(conn, args.model, bodies[i % len(bodies)],
                              args.precision, stats,
                              retries=args.max_retries, seed=i,
                              trace_id=tid, headers=hdrs,
                              deadline_s=args.timeout_s)
            finally:
                conn.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(args.concurrency)]
    else:  # open loop: fixed arrival schedule, pool large enough to
        # absorb in-flight overlap
        interval = 1.0 / max(args.qps, 1e-6)
        sched_q = []
        for i in range(args.requests):
            sched_q.append((t_start + i * interval, i))
        qlock = threading.Lock()

        def worker():
            conn = _Conn(args.url, args.timeout_s + 10.0)
            try:
                while True:
                    with qlock:
                        if not sched_q:
                            return
                        due, i = sched_q.pop(0)
                    now = time.perf_counter()
                    if due > now:
                        time.sleep(due - now)
                    lag = max(0.0, time.perf_counter() - due)
                    tid, hdrs = _trace_of(i)
                    _fire(conn, args.model, bodies[i % len(bodies)],
                          args.precision, stats, lag,
                          retries=args.max_retries, seed=i,
                          trace_id=tid, headers=hdrs,
                          deadline_s=args.timeout_s)
            finally:
                conn.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(max(args.concurrency, 4))]

    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start

    prom_after = parse_prometheus(_get(f"{args.url}/metrics").decode())
    lat = np.asarray(sorted(stats.latencies)) if stats.latencies else None

    # --trace: resolve the slowest requests' SERVER-side decomposition
    # (the ids are client-generated, so this closes the correlation loop:
    # "my p99 request spent X ms in the queue, Y padded rows, Z in exec")
    slow_traces = None
    if args.trace:
        slow_traces = []
        for dt, tid in sorted(stats.traced, reverse=True)[:args.trace_top]:
            entry = {"trace_id": tid,
                     "client_ms": round(dt * 1e3, 3)}
            try:
                server = _get_json(f"{args.url}/v1/traces/{tid}")
                entry["server"] = {
                    "total_ms": server.get("dur_ms"),
                    "status": server.get("status"),
                    "model": server.get("model"),
                    "decomposition": server.get("decomposition"),
                }
            except Exception as e:  # noqa: BLE001 — evicted/disabled: say so
                entry["server"] = {"error": f"{type(e).__name__}: {e}"}
            slow_traces.append(entry)

    def delta(name):
        return (prom_after[0].get(name, 0.0)
                - prom_before[0].get(name, 0.0))

    mname = args.model
    fill = prom_after[1].get(f"serving_{mname}_batch_fill")
    fill_before = prom_before[1].get(f"serving_{mname}_batch_fill",
                                     {"sum": 0.0, "count": 0})
    generation = None
    if args.generate:
        ttft = (np.asarray(sorted(stats.ttfts_ms))
                if stats.ttfts_ms else None)
        generation = {
            "prompt_len": plen,
            "max_tokens": mt,
            "tokens_received": stats.tokens,
            "tokens_per_sec": (round(stats.tokens / elapsed, 2)
                               if elapsed else 0),
            "ttft_ms": None if ttft is None else {
                "p50": round(float(np.percentile(ttft, 50)), 3),
                "p99": round(float(np.percentile(ttft, 99)), 3),
                "max": round(float(ttft[-1]), 3),
            },
            "server": {
                "tokens": delta(f"serving_gen_{mname}_tokens"),
                "decode_steps": delta(
                    f"serving_gen_{mname}_decode_steps"),
                "prefills": delta(f"serving_gen_{mname}_prefills"),
            },
        }
    # --router: the fleet-level story (failovers absorbed, hedges fired,
    # replicas evicted/re-admitted/restarted) + the final fleet snapshot
    router_block = None
    if args.router:
        router_block = {
            "requests_total": delta("router_requests_total"),
            "failover_total": delta("router_failover_total"),
            "hedges_total": delta("router_hedges_total"),
            "hedges_won_total": delta("router_hedges_won_total"),
            "evictions_total": delta("router_evictions_total"),
            "readmissions_total": delta("router_readmissions_total"),
            "replica_restarts_total": delta(
                "router_replica_restarts_total"),
        }
        try:
            router_block["replicas"] = _get_json(
                f"{args.url}/v1/replicas")["replicas"]
        except Exception as e:  # noqa: BLE001 — snapshot is best-effort
            router_block["replicas"] = f"{type(e).__name__}: {e}"

    artifact = {
        "tool": "loadgen",
        "url": args.url,
        "model": mname,
        "mode": args.mode,
        "precision": args.precision,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "batch_sizes": sizes,
        "offered_qps": args.qps if args.mode == "open" else None,
        "elapsed_s": round(elapsed, 4),
        "completed": len(stats.latencies),
        "errors": stats.errors,
        "errors_by_kind": stats.errors_by_kind,
        "error_rate": round(stats.errors / max(1, args.requests), 4),
        "max_error_rate": args.max_error_rate,
        "sheds": stats.sheds,
        "retry_after_seen": stats.retry_after_seen,
        "retries": stats.retries,
        "status_counts": stats.status_counts,
        "qps": round(len(stats.latencies) / elapsed, 2) if elapsed else 0,
        "latency_ms": None if lat is None else {
            "mean": round(float(lat.mean()) * 1e3, 3),
            "p50": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "p90": round(float(np.percentile(lat, 90)) * 1e3, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "max": round(float(lat[-1]) * 1e3, 3),
        },
        "schedule_lag_ms_p99": (
            round(float(np.percentile(stats.lag, 99)) * 1e3, 3)
            if stats.lag else None),
        "generation": generation,
        "router": router_block,
        "trace": bool(args.trace),
        "slow_traces": slow_traces,
        "policy": {
            "buckets": info.get("buckets"),
            "max_batch": info.get("max_batch"),
            "max_wait_ms": info.get("max_wait_ms"),
            "use_aot": info.get("use_aot"),
        },
        "server_metrics": {
            "executor_compiles_during_load": delta("executor_compiles"),
            "executor_recompiles_during_load": delta("executor_recompiles"),
            "batches": delta(f"serving_{mname}_batches"),
            "padded_rows": delta(f"serving_{mname}_padded_rows"),
            "rows": delta(f"serving_{mname}_rows"),
            "unplanned_compiles": delta(
                f"serving_{mname}_unplanned_compiles"),
            "shed_total": delta("serving_shed_total"),
            "model_shed_total": delta(f"serving_{mname}_shed_total"),
            "expired_dropped_total": delta(
                f"serving_{mname}_expired_dropped_total"),
            "batch_errors": delta(f"serving_{mname}_batch_errors"),
            "batch_fill_mean": (
                round((fill["sum"] - fill_before["sum"])
                      / max(1, fill["count"] - fill_before["count"]), 4)
                if fill and fill["count"] > fill_before["count"] else None),
        },
    }
    out = json.dumps(artifact, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    # CI-gate contract: nonzero only when the TERMINAL error rate
    # exceeds the threshold (default 0.0 = any terminal error fails,
    # the pre-robustness behavior; retried-to-success sheds never
    # fail).  Compared UNROUNDED: one error in a huge run must not
    # round down past a zero-tolerance gate.
    rate = stats.errors / max(1, args.requests)
    return 0 if rate <= args.max_error_rate else 1


if __name__ == "__main__":
    sys.exit(main())
