"""Sharding strategies: dp / tp / zero / hybrid over a device Mesh.

This is the TPU-native replacement for the reference's parallelism machinery
(SURVEY.md §2.4): BuildStrategy.ReduceStrategy (allreduce vs reduce+bcast,
details/build_strategy.h:55) becomes a choice of parameter PartitionSpecs;
DistributeTranspiler's pserver split (slice_variable ≥8192 elems round-robin,
distribute_transpiler.py:80) becomes ZeRO-style sharded optimizer state —
XLA GSPMD inserts all-gathers/reduce-scatters over ICI.

Usage:
    plan = ShardingPlan(mesh_axes={"data": 4, "model": 2},
                        param_rules=[(r".*attn.*w", P(None, "model"))])
    compiled = ShardedProgram(prog, plan, loss_name=...)
    exe.run(compiled, feed=..., fetch_list=[...])
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import executor as exec_mod
from ..core import framework as fw
from ..core.executor import prng_key as _prng_key


class ShardingPlan:
    def __init__(
        self,
        mesh_axes: Dict[str, int],
        param_rules: Optional[List[Tuple[str, object]]] = None,
        data_axis: str = "data",
        zero_stage: int = 0,
        devices=None,
        feed_rules: Optional[List[Tuple[str, object]]] = None,
    ):
        """param_rules: [(name regex, PartitionSpec)] — first match wins.
        zero_stage >= 1 shards unmatched params' optimizer moments over the
        data axis; stage >= 2 shards the params themselves.

        ZeRO stage mapping under GSPMD (deepspeed numbering): stage 1 =
        optimizer state sharded; stages 2 and 3 COINCIDE here — once a
        param is sharded over the data axis (stage >= 2), XLA SPMD
        materializes its gradient reduce-scattered (classic stage 2) and
        all-gathers the param at its use sites on the fly (classic stage
        3); there is no separate grad/param bucketing to manage.
        zero_stage=3 is accepted as an explicit alias and behaves
        identically to 2 (parity-tested in tests/test_sharding.py).
        feed_rules: [(feed-name regex, PartitionSpec)] — overrides the
        default batch-over-data_axis feed sharding; use to shard the
        sequence dim for context parallelism, e.g.
        (r\"src_word|trg_word\", P(\"data\", \"sp\"))."""
        self.mesh_axes = dict(mesh_axes)
        self.param_rules = param_rules or []
        self.data_axis = data_axis
        self.zero_stage = zero_stage
        self.devices = devices
        self.feed_rules = feed_rules or []

    def spec_for_feed(self, name: str):
        from jax.sharding import PartitionSpec as P

        for pat, spec in self.feed_rules:
            if re.fullmatch(pat, name):
                return spec
        return P(self.data_axis)

    def build_mesh(self):
        import jax
        from jax.sharding import Mesh

        devices = self.devices if self.devices is not None else jax.devices()
        n = int(np.prod(list(self.mesh_axes.values())))
        assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
        arr = np.array(devices[:n]).reshape(tuple(self.mesh_axes.values()))
        return Mesh(arr, axis_names=tuple(self.mesh_axes))

    def _spec_fits(self, spec, shape):
        """A PartitionSpec is usable only if the array has enough dims and
        every sharded dim divides evenly by its axis size."""
        if shape is None:
            return False
        if len(spec) > len(shape):
            return False
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            n = 1
            for a in axes:
                n *= self.mesh_axes.get(a, 1)
            if shape[dim] is None or shape[dim] % n != 0:
                return False
        return True

    def spec_for_param(self, name: str, shape, is_moment=False):
        from jax.sharding import PartitionSpec as P

        for pattern, spec in self.param_rules:
            if re.fullmatch(pattern, name) or re.match(pattern + "$", name):
                if self._spec_fits(spec, shape):
                    return spec
                break  # matched but unshardable (e.g. rank-1 accumulator)
        if self.zero_stage >= 2 or (self.zero_stage >= 1 and is_moment):
            # ZeRO: shard dim0 over data axis when divisible
            if shape and shape[0] and shape[0] % self.mesh_axes.get(
                self.data_axis, 1
            ) == 0 and len(shape) >= 1 and shape[0] > 1:
                return P(self.data_axis)
        return P()


class ShardedProgram:
    """Like CompiledProgram.with_data_parallel, but with a full ShardingPlan:
    batch shards over the data axis; parameters/optimizer state follow
    param_rules (tensor parallel) or ZeRO sharding."""

    def __init__(self, program: fw.Program, plan: ShardingPlan,
                 loss_name: Optional[str] = None):
        self._program = program
        self.plan = plan
        self._loss_name = loss_name
        self._mesh = None
        self._cache = {}
        self._run_counter = 0

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = self.plan.build_mesh()
        return self._mesh

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        feed = feed or {}
        scope = scope or exec_mod.global_scope()
        program = self._program
        mesh = self.mesh
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v for v in (fetch_list or [])
        ]
        feed_names = sorted(feed)
        block = program.global_block()

        key = (
            program.fingerprint(),
            bool(getattr(program, "_amp_bf16", False)),
            bool(getattr(program, "_is_test", False)),
            tuple(feed_names),
            tuple(
                (tuple(np.asarray(feed[n]).shape), str(np.asarray(feed[n]).dtype))
                for n in feed_names
            ),
            tuple(fetch_names),
        )
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(program, feed_names, fetch_names, scope, mesh)
            self._cache[key] = entry
        (jitted, rw_state, ro_state, state_writes, needs_key, shardings) = entry

        feed_vals = [
            jax.device_put(
                np.asarray(feed[n]),
                NamedSharding(mesh, self.plan.spec_for_feed(n)),
            )
            for n in feed_names
        ]

        def place(n):
            val = scope.find_var(n)
            if val is None:
                return None
            want = shardings.get(n)
            if want is not None and getattr(val, "sharding", None) != want:
                return jax.device_put(val, want)
            return val

        rw_vals = [place(n) for n in rw_state]
        ro_vals = [place(n) for n in ro_state]

        self._run_counter += 1
        if needs_key:
            k = jax.random.fold_in(
                _prng_key(program.random_seed or 0), self._run_counter
            )
            fetches, new_state = jitted(feed_vals, rw_vals, ro_vals, k)
        else:
            fetches, new_state = jitted(feed_vals, rw_vals, ro_vals)
        for n, v in zip(state_writes, new_state):
            scope.set_var(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    def _compile(self, program, feed_names, fetch_names, scope, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        block = program.global_block()
        state_reads, state_writes = exec_mod.analyze_block_io(
            block, feed_names, scope
        )
        write_set = set(state_writes)
        rw_state = [n for n in state_reads if n in write_set]
        ro_state = [n for n in state_reads if n not in write_set]

        params = {p.name for p in program.all_parameters()}

        def sharding_for(name):
            v = scope.find_var(name)
            shape = getattr(v, "shape", None)
            spec = self.plan.spec_for_param(
                name, shape, is_moment=name not in params
            )
            return NamedSharding(mesh, spec)

        shardings = {n: sharding_for(n) for n in state_reads + state_writes}

        feed_shardings = [
            NamedSharding(mesh, self.plan.spec_for_feed(n))
            for n in feed_names
        ]
        probe_random = exec_mod.program_uses_random(block)

        def run_fn(feed_vals, rw_vals, ro_vals, key=None):
            if key is None:
                key = _prng_key(program.random_seed or 0)
            tctx = exec_mod.TraceContext(
                program, key, is_test=getattr(program, "_is_test", False),
                mesh=mesh,
            )
            env = {}
            env.update(zip(feed_names, feed_vals))
            env.update(zip(rw_state, rw_vals))
            env.update(zip(ro_state, ro_vals))
            exec_mod.trace_block(block, env, tctx)
            return (
                [env[n] for n in fetch_names],
                [env.get(n) for n in state_writes],
            )

        in_shardings = (
            feed_shardings,
            [shardings[n] for n in rw_state],
            [shardings[n] for n in ro_state],
        )
        out_shardings = (
            [None] * len(fetch_names),
            [shardings[n] for n in state_writes],
        )
        if probe_random:
            jitted = jax.jit(run_fn, donate_argnums=(1,),
                             in_shardings=in_shardings + (None,),
                             out_shardings=out_shardings)
        else:
            jitted = jax.jit(lambda f, rw, ro: run_fn(f, rw, ro),
                             donate_argnums=(1,),
                             in_shardings=in_shardings,
                             out_shardings=out_shardings)
        return (jitted, rw_state, ro_state, state_writes, probe_random,
                shardings)


def transformer_tp_rules(model_axis="model"):
    """Megatron-style tensor-parallel PartitionSpecs for the bundled
    transformer (models/transformer.py stable param names):

      * attention q/k/v projections [d_model, h*d] — column-parallel
        (split the head/output dim; each shard owns whole heads)
      * attention output projection [h*d, d_model] — row-parallel
        (split the input dim; GSPMD inserts the all-reduce)
      * ffn-in [d_model, d_ff] column-parallel + its bias sharded the
        same way; ffn-out [d_ff, d_model] row-parallel, bias replicated
      * embedding tables [vocab, d_model] split on the vocab dim;
        the tied/final vocab projection predict_w [d_model, vocab] on
        its output (vocab) dim

    Loss-parity vs single-device is asserted by
    tests/test_sharding.py::test_transformer_tp_rules_loss_parity."""
    from jax.sharding import PartitionSpec as P

    return [
        (r"(src|trg)_word_emb_table", P(model_axis, None)),
        (r"attn_qkv_w_\d+", P(None, model_axis)),
        (r"attn_[qkv]_w_\d+", P(None, model_axis)),
        (r"attn_out_w_\d+", P(model_axis, None)),
        (r"ffn_in_w_\d+", P(None, model_axis)),
        (r"ffn_in_b_\d+", P(model_axis)),
        (r"ffn_out_w_\d+", P(model_axis, None)),
        (r"predict_w", P(None, model_axis)),
    ]


def bert_tp_rules(model_axis="model"):
    """Tensor-parallel PartitionSpecs for the bundled BERT encoder
    (models/bert.py).  Its attention rides the same multi_head_attention
    as the transformer (stable attn_*_w names: qkv column-parallel, out
    row-parallel); the word/sentence embedding tables split on the vocab
    dim.  The ffn uses auto-named layers.fc weights, so it stays
    replicated under tp — its optimizer moments shard over the data axis
    via the plan's zero_stage instead (Megatron attention + ZeRO ffn)."""
    from jax.sharding import PartitionSpec as P

    return [
        (r"(word|sent)_embedding", P(model_axis, None)),
        (r"attn_qkv_w_\d+", P(None, model_axis)),
        (r"attn_[qkv]_w_\d+", P(None, model_axis)),
        (r"attn_out_w_\d+", P(model_axis, None)),
    ]
