"""Ring attention: exact attention over sequences sharded across devices.

New TPU capability beyond the reference (SURVEY.md §5.7: the reference's max
context is bounded by single-device memory; nothing shards the sequence
axis).  The sequence axis is sharded over a mesh axis; each device holds a
Q shard and streams K/V shards around the ring with `jax.lax.ppermute` over
ICI, combining per-shard partial results with the online-softmax merge that
is flash attention's native algebra.

What makes this the real long-context path (VERDICT r4 item 3):

  * **The Pallas flash kernel runs inside every ring step** (same
    `_flash_forward`/`_flash_backward` kernels as kernels/attention.py) —
    no [t_q, t_k] score matrix ever exists, in forward OR backward, so
    per-device memory is O(t_local·d), independent of total sequence
    length.  Off-TPU / unaligned shapes fall back to a chunked XLA path
    with the same algebra.
  * **Custom VJP re-rings K/V in the backward** instead of saving every
    rotated shard as a residual: the forward stores only (q, k, v, kbias,
    out, lse) — all O(t_local) — and the backward circulates K/V (and the
    traveling dK/dV accumulators) around the ring again, exactly like the
    forward.  Plain autodiff through the unrolled loop would have stored
    n shards = the full sequence per device, defeating context parallelism.
  * **Causal rings skip fully-masked steps**: a chunk strictly in the
    future of this device's queries contributes nothing; a `lax.cond`
    skips its compute (the ring ppermute still advances, so lockstep
    collectives stay aligned).  The diagonal chunk runs the kernel's
    in-block causal mask.
  * **Key-side masks ride the ring**: an optional additive key bias
    [b|1, 1, 1, t_local] travels with its K/V chunk (a few KB), which is
    how `ring_attention_sharded` supports sequence lengths that do not
    divide the mesh axis (pad keys get -inf) and, generally, padding
    masks for ragged batches.
"""

from __future__ import annotations

import functools


def _pinf_to_ninf(lse):
    """Kernel convention for rows with no visible key is lse=+inf (so the
    backward recompute exp(s - lse) is 0).  For MERGING chunk partials the
    empty chunk must contribute exp(-inf)=0 instead."""
    import jax.numpy as jnp

    return jnp.where(jnp.isposinf(lse), -jnp.inf, lse)


def _chunk_fwd_xla(q, k, v, kbias, scale, causal):
    """Pure-XLA chunk partial: returns (o, lse') with lse' = -inf on rows
    with no visible key.  Fallback for shapes the Pallas plan rejects."""
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if kbias is not None:
        s = s + kbias.astype(jnp.float32)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(mask, s, -jnp.inf)
    m = s.max(axis=-1)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    den = p.sum(axis=-1)
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    den_safe = jnp.where(den == 0.0, 1.0, den)
    o = (num / den_safe[..., None]).astype(q.dtype)
    lse = jnp.where(den == 0.0, -jnp.inf, m_safe + jnp.log(den_safe))
    return o, lse


def _chunk_bwd_xla(q, k, v, kbias, out, lse, g, scale, causal):
    """Pure-XLA chunk backward against the GLOBAL lse (+inf on globally
    empty rows): p are globally-normalized probabilities, so the standard
    flash ds = p*(dp - delta) algebra applies per chunk."""
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if kbias is not None:
        s = s + kbias.astype(jnp.float32)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])          # 0 where masked or lse=+inf
    gf = g.astype(jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v.astype(jnp.float32))
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _to_bhtd(x, fmt):
    return x.transpose(0, 2, 1, 3) if fmt == "bthd" else x


def _chunk_fwd(q, k, v, kbias, scale, causal, block_q, block_k,
               fmt="bhtd"):
    """One ring step's partial attention: Pallas flash kernel when the
    plan allows, XLA chunk otherwise.  Returns (o, lse') with the -inf
    empty-row convention.  fmt "bthd" runs the whole-head kernels on the
    SAME block specs as the single-device path (attention.py _qkv_specs)
    — the per-device shards stay [b, t_local, h, d] and no split-head
    transpose exists anywhere on the ring (the relayout-copy class the
    bthd kernels were built to kill); only the XLA fallback transposes."""
    from .attention import _flash_forward, _plan

    ok, bq, bk, interp = _plan(q, k, block_q, block_k, None, fmt)
    if not ok:
        if fmt == "bthd":
            o, lse = _chunk_fwd_xla(_to_bhtd(q, fmt), _to_bhtd(k, fmt),
                                    _to_bhtd(v, fmt), kbias, scale, causal)
            return o.transpose(0, 2, 1, 3), lse
        return _chunk_fwd_xla(q, k, v, kbias, scale, causal)
    import jax.numpy as jnp

    seed = jnp.zeros((1,), jnp.uint32)
    out, lse = _flash_forward(q, k, v, kbias, seed, scale, causal, bq, bk,
                              interp, fmt, 0.0)
    return out, _pinf_to_ninf(lse)


def _chunk_bwd(q, k, v, kbias, out, lse, g, scale, causal, block_q,
               block_k, fmt="bhtd"):
    """One ring step's backward (against global out/lse): Pallas backward
    kernels when possible, XLA otherwise.  `lse` uses the kernel's +inf
    convention for globally-empty rows."""
    from .attention import _flash_backward, _plan

    ok, bq, bk, interp = _plan(q, k, block_q, block_k, None, fmt)
    if not ok:
        if fmt == "bthd":
            dq, dk, dv = _chunk_bwd_xla(
                _to_bhtd(q, fmt), _to_bhtd(k, fmt), _to_bhtd(v, fmt),
                kbias, _to_bhtd(out, fmt), lse, _to_bhtd(g, fmt), scale,
                causal)
            return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
                    dv.transpose(0, 2, 1, 3))
        return _chunk_bwd_xla(q, k, v, kbias, out, lse, g, scale, causal)
    import jax.numpy as jnp

    seed = jnp.zeros((1,), jnp.uint32)
    return _flash_backward(q, k, v, kbias, seed, out, lse, g, scale,
                           causal, bq, bk, interp, fmt, 0.0)


def _stat_bcast(stat, fmt):
    """[b, h, t] per-row statistic -> broadcastable against the chunk
    output layout ([b, h, t, 1] bhtd / [b, t, h, 1] bthd)."""
    if fmt == "bthd":
        stat = stat.transpose(0, 2, 1)
    return stat[..., None]


def _zeros_like_chunk(q, axis_name, fmt="bhtd"):
    import jax
    import jax.numpy as jnp

    from .attention import _dims

    b, h, t, _ = _dims(q, fmt)
    # pvary: constants made inside a shard_map are unvaried over the mesh
    # axis; lax.cond demands both branches match the compute branch's
    # device-varying type
    from .jax_compat import pvary

    return (pvary(jnp.zeros(q.shape, q.dtype), axis_name),
            pvary(jnp.full((b, h, t), -jnp.inf, jnp.float32), axis_name))


def _ring_fwd(q, k, v, kbias, axis_name, scale, causal, block_q, block_k,
              fmt="bhtd"):
    """Forward ring.  Returns (out, lse) with lse=+inf on rows that saw no
    key anywhere (kernel convention, ready for _chunk_bwd).  Shards are in
    `fmt` layout; per-row statistics always ride [b, h, t]."""
    import jax
    import jax.numpy as jnp

    from .attention import _dims
    from .jax_compat import axis_size

    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    b, h, t, d = _dims(q, fmt)
    m = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    den = jnp.zeros((b, h, t), jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    k_cur, v_cur, kb_cur = k, v, kbias

    for i in range(n):
        kv_idx = (my_idx - i) % n

        def full_fn(args):
            qq, kk, vv, bb = args
            return _chunk_fwd(qq, kk, vv, bb, scale, False, block_q,
                              block_k, fmt)

        def diag_fn(args):
            qq, kk, vv, bb = args
            return _chunk_fwd(qq, kk, vv, bb, scale, True, block_q,
                              block_k, fmt)

        def skip_fn(args):
            return _zeros_like_chunk(args[0], axis_name, fmt)

        args = (q, k_cur, v_cur, kb_cur)
        if not causal:
            o_i, lse_i = full_fn(args)
        else:
            # fully-masked future chunks skip their compute entirely — the
            # causal-FLOPs saving that makes a causal ring ~half cost
            o_i, lse_i = jax.lax.cond(
                kv_idx > my_idx, skip_fn,
                lambda a: jax.lax.cond(kv_idx == my_idx, diag_fn, full_fn,
                                       a),
                args)

        m_new = jnp.maximum(m, lse_i)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_safe))
        beta = jnp.exp(jnp.where(jnp.isneginf(lse_i), -jnp.inf,
                                 lse_i - m_safe))
        acc = (acc * _stat_bcast(alpha, fmt)
               + o_i.astype(jnp.float32) * _stat_bcast(beta, fmt))
        den = den * alpha + beta
        m = m_new

        if i < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, fwd_perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, fwd_perm)
            if kb_cur is not None:
                kb_cur = jax.lax.ppermute(kb_cur, axis_name, fwd_perm)

    den_safe = jnp.where(den == 0.0, 1.0, den)
    out = jnp.where(_stat_bcast(den, fmt) == 0.0, 0.0,
                    acc / _stat_bcast(den_safe, fmt)).astype(q.dtype)
    lse = jnp.where(den == 0.0, jnp.inf,
                    jnp.where(jnp.isneginf(m), 0.0, m) + jnp.log(den_safe))
    return out, lse


def _ring_bwd(q, k, v, kbias, out, lse, g, axis_name, scale, causal,
              block_q, block_k, fmt="bhtd"):
    """Backward ring: K/V (and their traveling dK/dV accumulators)
    circulate again; residual memory stays O(t_local)."""
    import jax
    import jax.numpy as jnp

    from .jax_compat import axis_size

    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_t = jnp.zeros(k.shape, jnp.float32)
    dv_t = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur, kb_cur = k, v, kbias

    for i in range(n):
        kv_idx = (my_idx - i) % n

        def full_fn(args):
            qq, kk, vv, bb = args
            return _chunk_bwd(qq, kk, vv, bb, out, lse, g, scale, False,
                              block_q, block_k, fmt)

        def diag_fn(args):
            qq, kk, vv, bb = args
            return _chunk_bwd(qq, kk, vv, bb, out, lse, g, scale, True,
                              block_q, block_k, fmt)

        def skip_fn(args):
            qq, kk, vv, _ = args
            from .jax_compat import pvary

            pv = functools.partial(pvary, axis_name=axis_name)
            return (pv(jnp.zeros(qq.shape, qq.dtype)),
                    pv(jnp.zeros(kk.shape, kk.dtype)),
                    pv(jnp.zeros(vv.shape, vv.dtype)))

        args = (q, k_cur, v_cur, kb_cur)
        if not causal:
            dq_i, dk_i, dv_i = full_fn(args)
        else:
            dq_i, dk_i, dv_i = jax.lax.cond(
                kv_idx > my_idx, skip_fn,
                lambda a: jax.lax.cond(kv_idx == my_idx, diag_fn, full_fn,
                                       a),
                args)

        dq = dq + dq_i.astype(jnp.float32)
        dk_t = dk_t + dk_i.astype(jnp.float32)
        dv_t = dv_t + dv_i.astype(jnp.float32)

        if i < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, fwd_perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, fwd_perm)
            if kb_cur is not None:
                kb_cur = jax.lax.ppermute(kb_cur, axis_name, fwd_perm)
            dk_t = jax.lax.ppermute(dk_t, axis_name, fwd_perm)
            dv_t = jax.lax.ppermute(dv_t, axis_name, fwd_perm)

    # after n-1 rotations each traveling accumulator sits one hop before
    # its chunk's home device — one more hop brings it home
    dk_t = jax.lax.ppermute(dk_t, axis_name, fwd_perm)
    dv_t = jax.lax.ppermute(dv_t, axis_name, fwd_perm)
    return dq.astype(q.dtype), dk_t.astype(k.dtype), dv_t.astype(v.dtype)


def ring_attention(q, k, v, axis_name, scale=1.0, causal=False, kbias=None,
                   block_q=512, block_k=512, fmt="bhtd"):
    """Runs INSIDE shard_map: q,k,v are the per-device sequence shards
    [b, h, t_local, d] (fmt "bhtd") or [b, t_local, h, d] (fmt "bthd" —
    the transpose-free convention: the ring path reuses the single-device
    bthd whole-head block specs, so context parallelism does not
    re-introduce the split/merge-head transposes the bthd kernels
    deleted); optional kbias [b|1, 1, 1, t_local] is an additive key bias
    (padding mask) that travels the ring with its K/V chunk.  Exact
    softmax attention over the full (sharded) sequence."""
    import jax

    have_bias = kbias is not None

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def _ring(q, k, v, kbias):
        out, _ = _ring_fwd(q, k, v, kbias if have_bias else None,
                           axis_name, scale, causal, block_q, block_k,
                           fmt)
        return out

    def _fwd(q, k, v, kbias):
        out, lse = _ring_fwd(q, k, v, kbias if have_bias else None,
                             axis_name, scale, causal, block_q, block_k,
                             fmt)
        return out, (q, k, v, kbias, out, lse)

    def _bwd(res, g):
        q, k, v, kbias, out, lse = res
        dq, dk, dv = _ring_bwd(q, k, v, kbias if have_bias else None, out,
                               lse, g, axis_name, scale, causal, block_q,
                               block_k, fmt)
        import jax.numpy as jnp

        return dq, dk, dv, jnp.zeros_like(kbias)

    _ring.defvjp(_fwd, _bwd)

    if kbias is None:
        import jax.numpy as jnp

        t_local = q.shape[1] if fmt == "bthd" else q.shape[2]
        kbias = jnp.zeros((1, 1, 1, t_local), jnp.float32)
    return _ring(q, k, v, kbias)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", scale=1.0,
                           causal=False, fmt="bhtd"):
    """Whole-array entry: q,k,v are global [b, h, T, d] (fmt "bhtd") or
    [b, T, h, d] (fmt "bthd") arrays; the sequence dim shards over
    `axis_name` of `mesh`; returns global output with the same sharding.
    T that does not divide the axis is padded and the pad keys masked via
    the ring-traveling key bias."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .attention import _dims
    from .jax_compat import shard_map as _shard_map

    n = mesh.shape[axis_name]
    b, h, t, d = _dims(q, fmt)
    tdim = 1 if fmt == "bthd" else 2
    pad = (-t) % n
    kbias = None
    if pad:
        widths = [(0, 0)] * 4
        widths[tdim] = (0, pad)
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        pos = jnp.arange(t + pad)
        kbias = jnp.where(pos < t, 0.0, -1e30).astype(jnp.float32).reshape(
            1, 1, 1, t + pad)

    # batch stays data-parallel INSIDE the ring when the mesh has a data
    # axis: declaring it in the shard_map specs keeps the incoming
    # (data, sp)-sharded activations in place — leaving it out forces
    # the partitioner to all-gather the batch dim at the boundary
    # ("involuntary full rematerialization" in the dp x tp x sp dryrun)
    baxis = "data" if "data" in getattr(mesh, "axis_names", ()) else None
    spec = (P(baxis, axis_name, None, None) if fmt == "bthd"
            else P(baxis, None, axis_name, None))
    if kbias is None:
        fn = _shard_map(
            functools.partial(ring_attention, axis_name=axis_name,
                              scale=scale, causal=causal, fmt=fmt),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    kb_spec = P(None, None, None, axis_name)   # kbias seq dim is LAST
    fn = _shard_map(
        lambda q, k, v, kb: ring_attention(q, k, v, axis_name, scale,
                                           causal, kbias=kb, fmt=fmt),
        mesh=mesh, in_specs=(spec, spec, spec, kb_spec), out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v, kbias)
    return out[:, :t] if fmt == "bthd" else out[:, :, :t]
