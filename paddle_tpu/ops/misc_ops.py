"""Miscellaneous op lowerings — losses, similarity, shape utilities.

Closes the op-coverage gap vs the reference operator library (SURVEY.md
§2.3).  Each lowering cites its reference kernel; gradients come from the
generic vjp grad maker unless noted.
"""

from __future__ import annotations

import numpy as np

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _same_infer(out_slot="Out", in_slot="X"):
    """Output shape/dtype mirrors the (first) input; out_slot may be a
    tuple of slots."""
    slots = (out_slot,) if isinstance(out_slot, str) else tuple(out_slot)

    def infer(ctx):
        s = ctx.input_shape(in_slot)
        if s is not None:
            for slot in slots:
                ctx.set_output(slot, s, ctx.input_dtype(in_slot))

    return infer


def _smooth_l1_infer(ctx):
    s = ctx.input_shape("X")
    if s is not None:
        ctx.set_output("Diff", s, ctx.input_dtype("X"))
        ctx.set_output("Out", (s[0], 1), ctx.input_dtype("X"))


def _sql2_infer(ctx):
    s = ctx.input_shape("X")
    if s is not None:
        ctx.set_output("sub_result", s, ctx.input_dtype("X"))
        ctx.set_output("Out", (s[0], 1), ctx.input_dtype("X"))


def _cos_sim_infer(ctx):
    xs, ys = ctx.input_shape("X"), ctx.input_shape("Y")
    if xs is not None:
        dt = ctx.input_dtype("X")
        ctx.set_output("Out", (xs[0], 1), dt)
        ctx.set_output("XNorm", (xs[0], 1), dt)
        if ys is not None:
            ctx.set_output("YNorm", (ys[0], 1), dt)


def _scalar1_infer(ctx):
    ctx.set_output("Out", (1,))


def _data_norm_infer(ctx):
    s = ctx.input_shape("X")
    if s is not None:
        dt = ctx.input_dtype("X")
        ctx.set_output("Y", s, dt)
        for slot in ("Means", "Scales", "BatchSizeOut", "BatchSumOut",
                     "BatchSquareSumOut"):
            ctx.set_output(slot, (s[-1],), dt)


def _fill_infer(ctx):
    ctx.set_output("Out", tuple(ctx.attr("shape")),
                   ctx.attr("dtype", "float32"))


def _fill_bsl_infer(ctx):
    s = ctx.input_shape("Input")
    shape = list(ctx.attr("shape"))
    if s is not None:
        shape[ctx.attr("output_dim_idx", 0)] = s[ctx.attr("input_dim_idx", 0)]
        ctx.set_output("Out", tuple(shape), ctx.attr("dtype", "float32"))


def _crop_infer(ctx):
    shape = ctx.attr("shape")
    if shape:
        ctx.set_output("Out", tuple(shape), ctx.input_dtype("X"))
    else:
        ys = ctx.input_shape("Y")
        if ys is not None:
            ctx.set_output("Out", ys, ctx.input_dtype("X"))


def _mean_iou_infer(ctx):
    n = ctx.attr("num_classes")
    ctx.set_output("OutMeanIou", (), "float32")
    ctx.set_output("OutWrong", (n,), "int32")
    ctx.set_output("OutCorrect", (n,), "int32")


def _fsp_infer(ctx):
    xs, ys = ctx.input_shape("X"), ctx.input_shape("Y")
    if xs is not None and ys is not None:
        ctx.set_output("Out", (xs[0], xs[1], ys[1]), ctx.input_dtype("X"))


def _btp_infer(ctx):
    xs, ws = ctx.input_shape("X"), ctx.input_shape("Weight")
    if xs is not None and ws is not None:
        ctx.set_output("Out", (xs[0], ws[0]), ctx.input_dtype("X"))


def _unpool_infer(ctx):
    s = ctx.input_shape("X")
    if s is None:
        return
    osize = ctx.attr("output_size")
    ks = ctx.attr("ksize")
    oh, ow = tuple(osize) if osize else (ks[0] * s[2], ks[1] * s[3])
    ctx.set_output("Out", (s[0], s[1], oh, ow), ctx.input_dtype("X"))


# ---------------------------------------------------------------------------
# Pairwise / ranking losses
# ---------------------------------------------------------------------------


@register("rank_loss", infer_shape=_same_infer("Out", "Left"))
def lower_rank_loss(ctx, ins):
    """out = log(1 + exp(left-right)) - label*(left-right)
    (reference rank_loss_op.h RankLossKernel)."""
    jnp = _jnp()
    left, right, label = ins["Left"][0], ins["Right"][0], ins["Label"][0]
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@register("modified_huber_loss",
          infer_shape=_same_infer(("IntermediateVal", "Out")))
def lower_modified_huber_loss(ctx, ins):
    """reference modified_huber_loss_op.h: y in {0,1} -> z = 2y-1;
    val = x*z; loss = -4val if val<-1; (1-val)^2 if val<1; else 0."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    val = x * (2.0 * y - 1.0)
    loss = jnp.where(
        val < -1.0, -4.0 * val,
        jnp.where(val < 1.0, jnp.square(1.0 - val), 0.0),
    )
    return {"IntermediateVal": [val], "Out": [loss]}


@register("teacher_student_sigmoid_loss", infer_shape=_same_infer("Y"))
def lower_teacher_student_sigmoid_loss(ctx, ins):
    """reference teacher_student_sigmoid_loss_op.h:44-63: label encodes
    {click-only: -1, noclick+teacher: [0,1), click+teacher: [1,2)}."""
    jnp = _jnp()
    x = ins["X"][0]
    label = ins["Label"][0].astype(x.dtype)
    base = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
    y_m2 = base                                  # label < -1
    y_m1 = base - x                              # -1 <= label < 0
    y_01 = base + base - x * label               # 0 <= label < 1
    y_12 = base - x + base - x * (label - 1.0)   # label >= 1
    y = jnp.where(
        label < -1.0, y_m2,
        jnp.where(label < 0.0, y_m1, jnp.where(label < 1.0, y_01, y_12)),
    )
    return {"Y": [y]}


@register("smooth_l1_loss", infer_shape=_smooth_l1_infer)
def lower_smooth_l1_loss(ctx, ins):
    """reference smooth_l1_loss_op.h: d = inside_w*(x-y);
    per-elem: 0.5*(sigma*d)^2 if |d|<1/sigma^2 else |d|-0.5/sigma^2;
    Out = outside_w * row-sum."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    sigma = ctx.attr("sigma", 1.0)
    iw = ins.get("InsideWeight", [None])[0]
    ow = ins.get("OutsideWeight", [None])[0]
    d = x - y
    if iw is not None:
        d = d * iw
    s2 = sigma * sigma
    ad = jnp.abs(d)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * jnp.square(d), ad - 0.5 / s2)
    diff = elem.reshape(x.shape[0], -1)
    out = jnp.sum(diff, axis=1, keepdims=True)
    if ow is not None:
        out = out * ow.reshape(out.shape)
    return {"Diff": [d], "Out": [out]}


@register("squared_l2_distance", infer_shape=_sql2_infer)
def lower_squared_l2_distance(ctx, ins):
    """reference squared_l2_distance_op.h: sub = x - y (y row-broadcast);
    Out[i] = sum_j sub[i,j]^2."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    return {
        "sub_result": [sub],
        "Out": [jnp.sum(jnp.square(sub), axis=1, keepdims=True)],
    }


@register("cos_sim", infer_shape=_cos_sim_infer)
def lower_cos_sim(ctx, ins):
    """reference cos_sim_op.h: row-wise cosine similarity; Y may have one
    row (broadcast)."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    prod = jnp.sum(x * y, axis=1, keepdims=True)
    return {"Out": [prod / (xn * yn)], "XNorm": [xn], "YNorm": [yn]}


@register("l1_norm", infer_shape=_scalar1_infer)
def lower_l1_norm(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0])).reshape((1,))]}


# ---------------------------------------------------------------------------
# Elementwise / activation extras
# ---------------------------------------------------------------------------


@register("selu", infer_shape=_same_infer())
def lower_selu(ctx, ins):
    """reference selu_op.cc (scale/alpha attrs, Klambauer et al. defaults)."""
    jnp = _jnp()
    x = ins["X"][0]
    scale = ctx.attr("scale", 1.0507009873554805)
    alpha = ctx.attr("alpha", 1.6732632423543772)
    return {"Out": [scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))]}


@register("sign", infer_shape=_same_infer())
def lower_sign(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.sign(ins["X"][0])]}


@register("minus", infer_shape=_same_infer())
def lower_minus(ctx, ins):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register("label_smooth", infer_shape=_same_infer())
def lower_label_smooth(ctx, ins):
    """reference label_smooth_op.h: out = (1-eps)*x + eps*prior (prior
    defaults to uniform 1/num_classes)."""
    jnp = _jnp()
    x = ins["X"][0]
    eps = ctx.attr("epsilon", 0.0)
    prior = ins.get("PriorDist", [None])[0]
    if prior is None:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    else:
        out = (1.0 - eps) * x + eps * prior.reshape((1,) * (x.ndim - 1) + (-1,))
    return {"Out": [out]}


@register("multiplex", no_grad=True, infer_shape=_same_infer())
def lower_multiplex(ctx, ins):
    """reference multiplex_op.cc: Out[i] = X[Ids[i]][i] — per-row select
    among the N candidate tensors."""
    jnp = _jnp()
    ids = ins["Ids"][0].reshape(-1).astype("int32")
    xs = jnp.stack(ins["X"], axis=0)  # [N, B, ...]
    rows = jnp.arange(xs.shape[1])
    return {"Out": [xs[ids, rows]]}


def _where_infer(ctx):
    # static out shape = broadcast(X, Y, Condition); X alone is wrong when it
    # broadcasts up (e.g. ModelAverage's where(rotate, [1]-zero, param_sum))
    import numpy as np

    shapes = [ctx.input_shape(s) for s in ("X", "Y", "Condition")]
    known = [s for s in shapes if s is not None and -1 not in tuple(s)]
    if ctx.input_shape("X") is not None:
        out = tuple(ctx.input_shape("X"))
        for s in known:
            try:
                out = np.broadcast_shapes(out, tuple(s))
            except ValueError:
                pass
        ctx.set_output("Out", list(out), ctx.input_dtype("X"))


@register("where", infer_shape=_where_infer)
def lower_where(ctx, ins):
    """Ternary select Out = Condition ? X : Y (modern paddle.where
    semantics — a TPU-native addition used by IfElse's merge so the
    untaken branch cannot poison the output via 0*NaN and integer
    outputs keep their dtype).  Condition broadcasts against X/Y.
    Differentiable in X/Y via the vjp grad maker (grad w.r.t. the
    boolean Condition is zero/undefined, as in the reference)."""
    jnp = _jnp()
    cond = ins["Condition"][0].astype(bool)
    return {"Out": [jnp.where(cond, ins["X"][0], ins["Y"][0])]}


@register("affine_channel", infer_shape=_same_infer())
def lower_affine_channel(ctx, ins):
    """reference detection/affine_channel_op.cc: x*scale+bias per channel."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    layout = ctx.attr("data_layout", "NCHW")
    shape = (
        (1, -1) + (1,) * (x.ndim - 2) if layout == "NCHW" else
        (1,) * (x.ndim - 1) + (-1,)
    )
    return {"Out": [x * scale.reshape(shape) + bias.reshape(shape)]}


@register("data_norm", infer_shape=_data_norm_infer)
def lower_data_norm(ctx, ins):
    """reference data_norm_op.cc: normalize with accumulated batch
    statistics (size/sum/square-sum); outputs updated accumulators —
    the executor writes them back like batch_norm's running stats."""
    jnp = _jnp()
    x = ins["X"][0]
    bsize = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsq = ins["BatchSquareSum"][0]
    eps = ctx.attr("epsilon", 1e-4)
    mean = bsum / bsize
    scale = jnp.sqrt(bsize / (bsq - bsum * mean + eps * bsize))
    y = (x - mean.reshape(1, -1)) * scale.reshape(1, -1)
    import jax

    n = x.shape[0]
    xs = jax.lax.stop_gradient(x)
    return {
        "Y": [y],
        "Means": [mean],
        "Scales": [scale],
        "BatchSizeOut": [bsize + n],
        "BatchSumOut": [bsum + jnp.sum(xs, axis=0)],
        "BatchSquareSumOut": [bsq + jnp.sum(jnp.square(xs), axis=0)],
    }


# ---------------------------------------------------------------------------
# Tensor/shape utilities
# ---------------------------------------------------------------------------


@register("fill", no_grad=True, infer_shape=_fill_infer)
def lower_fill(ctx, ins):
    from .tensor_ops import _requested_dtype

    jnp = _jnp()
    shape = ctx.attr("shape")
    value = np.asarray(ctx.attr("value"), dtype="float32")
    # clamp through jax's canonical dtype (as fill_constant/cast do): an
    # int64 request with x64 off becomes int32 EXPLICITLY instead of
    # truncate-and-warn on every trace
    target = _requested_dtype(ctx.attr("dtype", "float32"))
    return {"Out": [jnp.asarray(value.reshape(shape)).astype(target)]}


@register("fill_constant_batch_size_like", no_grad=True,
          infer_shape=_fill_bsl_infer)
def lower_fill_constant_batch_size_like(ctx, ins):
    """reference fill_constant_batch_size_like_op.cc: like fill_constant but
    one dim copies the batch size of Input."""
    from .tensor_ops import _requested_dtype

    jnp = _jnp()
    x = ins["Input"][0]
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    # clamped dtype: no int64-truncation UserWarning per trace (PR 1 did
    # the same for fill_constant/cast/index outputs, tensor_ops.py)
    target = _requested_dtype(ctx.attr("dtype", "float32"))
    val = ctx.attr("value", 0.0)
    return {"Out": [jnp.full(tuple(shape), val, dtype=target)]}


@register("crop", infer_shape=_crop_infer)
def lower_crop(ctx, ins):
    """reference crop_op.cc: crop X to `shape` starting at `offsets`
    (offsets via attr or input tensor — static attr form here)."""
    import jax

    x = ins["X"][0]
    y = ins.get("Y", [None])[0]
    shape = tuple(ctx.attr("shape") or y.shape)
    offs = ins.get("Offsets", [None])[0]
    if offs is not None:
        offsets = tuple(int(v) for v in np.asarray(offs).reshape(-1))
    else:
        offsets = tuple(ctx.attr("offsets") or (0,) * x.ndim)
    return {"Out": [jax.lax.dynamic_slice(x, offsets, shape)]}


@register("is_empty", no_grad=True, infer_shape=_scalar1_infer)
def lower_is_empty(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    return {"Out": [jnp.asarray(int(np.prod(x.shape)) == 0).reshape((1,))]}


@register("mean_iou", no_grad=True, infer_shape=_mean_iou_infer)
def lower_mean_iou(ctx, ins):
    """reference mean_iou_op.h: mean IoU over classes via confusion
    counts."""
    jnp = _jnp()
    pred = ins["Predictions"][0].reshape(-1).astype("int32")
    label = ins["Labels"][0].reshape(-1).astype("int32")
    n = ctx.attr("num_classes")
    idx = label * n + pred
    cm = jnp.zeros((n * n,), "int32").at[idx].add(1).reshape(n, n)
    inter = jnp.diagonal(cm).astype("float32")
    union = (
        jnp.sum(cm, axis=0) + jnp.sum(cm, axis=1)
    ).astype("float32") - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype("float32")), 1.0)
    return {
        "OutMeanIou": [mean.reshape(())],
        "OutWrong": [(jnp.sum(cm, axis=1).astype("int32") - inter.astype("int32"))],
        "OutCorrect": [inter.astype("int32")],
    }


@register("fsp", infer_shape=_fsp_infer)
def lower_fsp(ctx, ins):
    """reference fsp_op.cc (distillation): G = (1/HW) * X_flat @ Y_flat^T
    per sample — [N, C1, C2]."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    xf = x.reshape(n, c1, h * w)
    yf = y.reshape(n, c2, h * w)
    return {"Out": [xf @ yf.transpose(0, 2, 1) / (h * w)]}


@register("conv_shift", infer_shape=_same_infer())
def lower_conv_shift(ctx, ins):
    """reference conv_shift_op.cc: circular correlation
    out[i, j] = sum_k x[i, (j+k-M/2) mod W] * y[i, k]."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    b, w = x.shape
    m = y.shape[1]
    half = m // 2
    js = jnp.arange(w)[:, None]
    ks = jnp.arange(m)[None, :]
    idx = (js + ks - half) % w  # [W, M]
    gathered = x[:, idx]  # [B, W, M]
    return {"Out": [jnp.einsum("bwm,bm->bw", gathered, y)]}


@register("bilinear_tensor_product", infer_shape=_btp_infer)
def lower_bilinear_tensor_product(ctx, ins):
    """reference bilinear_tensor_product_op.h:
    out[:, k] = sum_ij x_i W[k]_ij y_j (+ bias)."""
    jnp = _jnp()
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    bias = ins.get("Bias", [None])[0]
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return {"Out": [out]}


@register("add_position_encoding", infer_shape=_same_infer())
def lower_add_position_encoding(ctx, ins):
    """reference add_position_encoding_op.h: out = alpha*x + beta*sinusoid
    position table."""
    jnp = _jnp()
    x = ins["X"][0]
    alpha = ctx.attr("alpha", 1.0)
    beta = ctx.attr("beta", 1.0)
    b, t, d = x.shape
    pos = np.arange(t, dtype="float32")[:, None]
    dim = np.arange(d // 2, dtype="float32")[None, :]
    div = np.power(10000.0, 2.0 * dim / d)
    enc = np.zeros((t, d), "float32")
    enc[:, 0::2] = np.sin(pos / div)
    enc[:, 1::2] = np.cos(pos / div)
    return {"Out": [alpha * x + beta * jnp.asarray(enc)[None]]}


@register("similarity_focus", no_grad=True, infer_shape=_same_infer())
def lower_similarity_focus(ctx, ins):
    """reference similarity_focus_op.h: for each (indexed channel), build a
    binary mask marking max positions row/col-wise; union over indices."""
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", 1)
    indexes = ctx.attr("indexes")
    n, c, h, w = x.shape
    assert axis == 1, "similarity_focus: only axis=1 (channel) supported"
    mask = jnp.zeros_like(x)
    for idx in indexes:
        ch = x[:, idx]  # [N, H, W]
        row_max = (ch == jnp.max(ch, axis=2, keepdims=True))
        col_max = (ch == jnp.max(ch, axis=1, keepdims=True))
        m = (row_max | col_max).astype(x.dtype)[:, None]  # [N,1,H,W]
        mask = jnp.maximum(mask, jnp.broadcast_to(m, mask.shape))
    return {"Out": [mask]}


@register("get_tensor_from_selected_rows", no_grad=True)
def lower_get_tensor_from_selected_rows(ctx, ins):
    """reference get_tensor_from_selected_rows_op.cc: rows as a dense
    [K, D] tensor."""
    x = ins["X"][0]
    from ..core.selected_rows import SelectedRows

    if isinstance(x, SelectedRows):
        return {"Out": [x.rows]}
    return {"Out": [x]}


@register("merge_selected_rows", no_grad=True)
def lower_merge_selected_rows(ctx, ins):
    """reference merge_selected_rows_op.cc (MergeAdd)."""
    from ..core.selected_rows import SelectedRows

    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        uids, mrows = x.merged()
        return {"Out": [SelectedRows(uids, mrows, x.height)]}
    return {"Out": [x]}


@register("shard_index", no_grad=True, infer_shape=_same_infer())
def lower_shard_index(ctx, ins):
    """shard_index_op: map global ids to shard-local (or ignore value)."""
    jnp = _jnp()
    x = ins["X"][0]
    index_num = ctx.attr("index_num")
    nshards = ctx.attr("nshards")
    shard_id = ctx.attr("shard_id")
    ignore_value = ctx.attr("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return {"Out": [jnp.where(in_shard, x % shard_size, ignore_value)]}


@register("unpool", infer_shape=_unpool_infer)
def lower_unpool(ctx, ins):
    """reference unpool_op.cc: max-unpool using saved indices (flat within
    each [H*W] map)."""
    jnp = _jnp()
    x, idx = ins["X"][0], ins["Indices"][0]
    n, c, h, w = x.shape
    oh, ow = ctx.attr("ksize")[0] * h, ctx.attr("ksize")[1] * w
    # output size from attrs if the layer recorded it
    if ctx.attr("output_size"):
        oh, ow = ctx.attr("output_size")
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx2 = idx.reshape(n, c, h * w).astype("int32")
    vals = x.reshape(n, c, h * w)
    out = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idx2
    ].add(vals)
    return {"Out": [out.reshape(n, c, oh, ow)]}


# py_func escape hatch ------------------------------------------------------

_PY_FUNC_REGISTRY: dict = {}
_PY_FUNC_IDS: dict = {}


def register_py_func(fn) -> int:
    """Register a host Python callable; returns its id attr (the layers
    wrapper does this). Mirrors the reference's PyFuncRegistry
    (py_func_op.cc).  Dedup by identity: a dygraph loop re-calling
    layers.py_func with the same function must not leak one closure per
    step."""
    fid = _PY_FUNC_IDS.get(id(fn))
    if fid is not None and _PY_FUNC_REGISTRY.get(fid) is fn:
        return fid
    fid = len(_PY_FUNC_REGISTRY)
    _PY_FUNC_REGISTRY[fid] = fn
    _PY_FUNC_IDS[id(fn)] = fid
    return fid


@register("py_func", no_grad=True)
def lower_py_func(ctx, ins):
    """Arbitrary user Python inside the compiled program via
    jax.pure_callback (reference py_func_op.cc / layers/nn.py:9655
    py_func).  The callable must be a pure function of its inputs; it
    runs on the HOST each step (a deliberate escape hatch, not a fast
    path).  Output shapes/dtypes come from the declared out specs."""
    import jax
    import jax.numpy as jnp

    fid = ctx.attr("func_id")
    fn = _PY_FUNC_REGISTRY[fid]
    out_shapes = ctx.attr("out_shapes")
    out_dtypes = ctx.attr("out_dtypes")
    xs = [v for v in ins.get("X", []) if v is not None]
    specs = [
        jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
        for s, d in zip(out_shapes, out_dtypes)
    ]

    def host_fn(*arrays):
        import numpy as _np

        res = fn(*arrays)
        if not isinstance(res, (list, tuple)):
            res = (res,)
        return tuple(_np.asarray(r).astype(d) for r, d in zip(res, out_dtypes))

    outs = jax.pure_callback(host_fn, tuple(specs), *xs, vmap_method="sequential")
    return {"Out": list(outs)}


@register("print", no_grad=False, infer_shape=_same_infer("Out", "In"))
def lower_print(ctx, ins):
    """Debug Print (reference print_op.cc + layers.Print): logs tensor
    stats at run time and passes the value through unchanged.  Under jit
    the log rides a jax.debug.callback (the TPU-native analogue of the
    reference's CPU-side TensorFormatter); gradients pass through
    (reference forwards grads when print_phase allows).  first_n limits
    the prints via a host-side counter; summarize>0 prints that many
    leading elements."""
    import jax

    x = ins["In"][0]
    msg = ctx.attr("message", "") or ""
    summarize = ctx.attr("summarize", -1)
    first_n = ctx.attr("first_n", -1)
    if ctx.attr("print_tensor_name", True):
        name = ctx.op.input("In")[0] if ctx.op is not None else "var"
        msg = f"{msg} {name}" if msg else name
    shape = tuple(x.shape)
    n_head = x.size if summarize < 0 else min(summarize, x.size)
    counter = {"n": 0}

    def _emit(mean, lo, hi, head):
        if first_n >= 0 and counter["n"] >= first_n:
            return
        counter["n"] += 1
        # msg is plain text, never a format string (user braces are safe)
        print(f"{msg} shape={shape} mean={mean} min={lo} max={hi} "
              f"first={head}", flush=True)

    if summarize == 0:
        def _emit0():
            if first_n >= 0 and counter["n"] >= first_n:
                return
            counter["n"] += 1
            print(f"{msg} shape={shape}", flush=True)

        jax.debug.callback(lambda _: _emit0(), x.reshape(-1)[0])
    else:
        jax.debug.callback(_emit, x.mean(), x.min(), x.max(),
                           x.reshape(-1)[:max(1, n_head)])
    return {"Out": [x]}
