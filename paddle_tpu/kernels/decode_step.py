"""Fused decode megastep: ONE Pallas launch per decoder layer per token.

The per-token decode program of the generation tier is ~60 small ops for
a 6-layer model (per layer: qkv projection, cache-row write, flash
decode, two more projections, cross attention, feed-forward, three layer
norms) and the PR-16 cost model shows it launch-bound at batch 1 — 97.9%
of the predicted step is dispatch.  This module collapses one WHOLE
decoder layer into a single kernel, so the per-token program becomes
n_layer megastep launches (+ embedding and sampling) instead of ~10 ops
per layer, and q/k/v and the attention context never round-trip HBM:

  * qkv projection of the incoming [b, 1, d_model] token is computed
    in-kernel (per-head column slices of the fused attn_qkv_w weight —
    the PR-8 fused-projection recipe applied at decode time);
  * the fresh k/v row is DMA'd from VMEM scratch straight into the
    HBM-resident ring cache at the runtime counter, through the ALIASED
    output buffer (`input_output_aliases`, the embedding-tier in-place
    recipe) gated on the lane's active mask;
  * the single-query online-softmax walk then streams the length-bounded
    cache prefix exactly like kernels/decode_attention.py (scalar-
    prefetched per-sequence lengths, start-all-then-wait-all block DMA,
    [t,h,d]->[h,t,d] in-register relayout, f32 running max/sum) — the
    just-written row is part of the walk because the write lands before
    the first block fetch;
  * output projection, residual + layer-norm epilogue, the cached
    cross-attention walk, and (VMEM budget permitting, _megastep_plan
    mode "fused-ffn") the position-wise feed-forward + final layer norm
    all happen in the same launch; when the FFN weights do not fit the
    budget next to the attention working set, the FFN+norm runs as a
    SECOND launch per layer (_ffn_kernel) — still 2 launches instead of
    ~10 ops.

Off-contract shapes (plan gate: d_model/d_inner lane alignment, head
sublane alignment, d_head % 64, block divisibility, VMEM budget) and
off-TPU runs fall back to `reference_decode_step` — a pure-XLA
composition that replicates the unfused op chain (ops/math_ops.py
lower_mul reshape-matmul, ops/generation_ops.py kv_cache_update +
decode_attention, ops/nn_ops.py layer_norm_core) op for op, so the
fused_decode_step op is numerically identical to the composition it
replaces on every backend.

Forward-only by contract (generation never differentiates through the
cache); the op registration in ops/generation_ops.py is no_grad and
preserves the cache vars' read-then-write donation contract verbatim.
"""

from __future__ import annotations

import collections
import functools

MegastepPlan = collections.namedtuple(
    "MegastepPlan", ["ok", "fuse_ffn", "block_t", "cross_block_t",
                     "interpret"])

#: conservative per-launch working-set budget (bytes): weights + walk
#: scratch + score planes must fit well under the 16 MB core VMEM next
#: to the surrounding program's tiles
_VMEM_BUDGET = 12 * 1024 * 1024


def _snap_block(block_t, max_t):
    """Snap the walk block down to a divisor of max_t (the ring buffers
    are 128-row quanta, so this terminates at a sane power of two)."""
    block_t = min(block_t, max_t)
    while block_t > 8 and max_t % block_t:
        block_t //= 2
    return block_t


def _itemsize(dtype):
    import numpy as np

    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(dtype))).itemsize


def _megastep_plan(d_model, n_head, d_head, d_inner, max_t, cross_t,
                   dtype, block_t=256, interpret=None):
    """Static feasibility gate; returns a MegastepPlan.

    Contract (audited statically by analysis/kernel_lint.py):
      * d_model % 128 == 0 and d_inner % 128 == 0 (both ride the lane
        dim of the projection tiles);
      * d_head % 64 == 0 and n_head % 8 == 0 for f32 / % 16 narrower
        (the cache walk's [h, t, d] in-register view — the same
        alignment _decode_plan enforces);
      * max_t % block_t == 0 and cross_t % cross_block_t == 0 with both
        blocks % 8 == 0 (the length-masked tail is the only partial
        block);
      * the four resident attention projections + the k/v walk scratch
        (+ f32 promoted copies) + score planes fit _VMEM_BUDGET; the
        FFN weights join the same launch only if they ALSO fit
        (fuse_ffn), otherwise the plan keeps a second per-layer launch.
    Off-contract shapes return ok=False and the caller runs the XLA
    composition fallback — numerically identical.
    """
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    esize = _itemsize(dtype)
    bt = _snap_block(block_t, max_t)
    cbt = _snap_block(block_t, cross_t)
    sublane = 8 if esize >= 4 else 16
    hd = n_head * d_head
    aligned = (
        d_model % 128 == 0
        and d_inner % 128 == 0
        and d_head % 64 == 0
        and n_head % sublane == 0
        and max_t % bt == 0 and bt % 8 == 0
        and cross_t % cbt == 0 and cbt % 8 == 0
    )
    # resident attention set: wqkv + wout + wcq + wcout (6*hd*dm elems)
    # at storage precision plus one promoted f32 [dm, dh] slice; self +
    # cross walk scratch blocks with their f32 promoted copies; two f32
    # score planes
    attn_bytes = (
        6 * hd * d_model * esize + d_model * d_head * 4
        + 2 * (bt + cbt) * hd * (esize + 4)
        + 2 * n_head * max(bt, cbt) * 4
    )
    # FFN adds the two [dm, di] projections and the f32 [1, di] hidden
    ffn_bytes = 2 * d_model * d_inner * esize + d_inner * 4
    ok = aligned and attn_bytes <= _VMEM_BUDGET and ffn_bytes <= _VMEM_BUDGET
    fuse_ffn = ok and attn_bytes + ffn_bytes <= _VMEM_BUDGET
    return MegastepPlan(ok, fuse_ffn, bt, cbt, interpret)


# ---------------------------------------------------------------------------
# pure-XLA fallback: the unfused composition, op for op
# ---------------------------------------------------------------------------


def reference_decode_step(x, wqkv, wout, ln1_scale, ln1_bias, wcq, wcout,
                          ln2_scale, ln2_bias, ffn_in_w, ffn_in_b,
                          ffn_out_w, ffn_out_b, ln3_scale, ln3_bias,
                          cache_k, cache_v, cross_k, cross_v, pos,
                          lengths, cross_lengths, active=None, *, layer,
                          n_head, scale, eps=1e-5):
    """The composed decoder step as ONE jax function — the exact op
    chain cached_decoder_step emits with FLAGS_fused_decode_step off
    (lower_mul reshape-matmul, jnp.split thirds, the kv_cache_update
    write with its active keep-mask, FLAGS.flash_decode-routed decode
    attention, layer_norm_core epilogues) so flag-on/off programs stay
    numerically identical on every backend.  Returns
    (out [b, 1, d_model], cache_k', cache_v')."""
    import jax
    import jax.numpy as jnp

    from ..flags import FLAGS
    from . import decode_attention as kda

    b = x.shape[0]
    h = n_head
    dh = cache_k.shape[-1]
    hd = h * dh

    def mul(a, w):
        # ops/math_ops.py lower_mul with x_num_col_dims=2
        a2 = a.reshape((b * 1, -1))
        return (a2 @ w).reshape((b, 1, w.shape[-1]))

    def layer_norm(y, s, bias):
        # ops/nn_ops.py layer_norm_core, begin_norm_axis=2
        stat = jnp.float32 if y.dtype == jnp.bfloat16 else y.dtype
        ys = y.astype(stat)
        mean = jnp.mean(ys, axis=2, keepdims=True)
        var = jnp.mean(jnp.square(ys - mean), axis=2, keepdims=True)
        out = (ys - mean) * jax.lax.rsqrt(var + eps)
        out = out * s.reshape((1, 1, -1)).astype(stat)
        out = out + bias.reshape((1, 1, -1)).astype(stat)
        return out.astype(y.dtype)

    def write(cache, new):
        # ops/generation_ops.py lower_kv_cache_update, verbatim
        pos32 = pos.reshape(-1).astype(jnp.int32)

        def upd(c, n, p):
            return jax.lax.dynamic_update_slice(
                c, n.astype(c.dtype), (p, 0, 0))

        updated = jax.vmap(upd)(cache[layer], new.reshape(b, 1, h, dh),
                                pos32)
        if active is not None:
            keep = active.reshape(-1).astype(jnp.bool_)
            updated = jnp.where(keep[:, None, None, None], updated,
                                cache[layer])
        return cache.at[layer].set(updated)

    def attend(q, kc, vc, lens):
        # ops/generation_ops.py lower_decode_attention routing
        q3 = q.reshape(b, h, dh)
        lens32 = lens.reshape(-1).astype(jnp.int32)
        if FLAGS.flash_decode:
            o = kda.flash_decode(q3, kc, vc, lens32, scale=scale)
        else:
            o = kda.reference_decode(q3, kc, vc, lens32, scale=scale)
        return o.reshape(b, 1, h, dh)

    qkv = mul(x, wqkv)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    cache_k = write(cache_k, k)
    cache_v = write(cache_v, v)
    ctx = attend(q, cache_k[layer], cache_v[layer], lengths)
    attn_out = mul(ctx.reshape(b, 1, hd), wout)
    x = layer_norm(x + attn_out, ln1_scale, ln1_bias)
    cq = mul(x, wcq)
    cctx = attend(cq, cross_k[layer], cross_v[layer], cross_lengths)
    cross_out = mul(cctx.reshape(b, 1, hd), wcout)
    x = layer_norm(x + cross_out, ln2_scale, ln2_bias)
    hid = jax.nn.relu(mul(x, ffn_in_w) + ffn_in_b.reshape((1, 1, -1)))
    ffd = mul(hid, ffn_out_w) + ffn_out_b.reshape((1, 1, -1))
    x = layer_norm(x + ffd, ln3_scale, ln3_bias)
    return x, cache_k, cache_v


# ---------------------------------------------------------------------------
# the megastep kernel
# ---------------------------------------------------------------------------


def _megastep_kernel(pos_ref, lens_ref, clens_ref, act_ref, *refs, layer,
                     scale, eps, block_t, cross_block_t, n_head, d_head,
                     d_model, fuse_ffn):
    """One grid step = one sequence: project qkv, DMA the fresh k/v row
    into the aliased HBM cache at the runtime counter, walk the
    length-bounded cache prefix (online softmax), project + normalize,
    repeat the walk against the cross cache, and (fuse_ffn) finish the
    layer's feed-forward — all without leaving the core."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    f32 = jnp.float32
    dh = d_head
    hd = n_head * d_head
    n_w = 15 if fuse_ffn else 9  # x + weight refs
    x_ref = refs[0]
    (wqkv, wout, ln1s, ln1b, wcq, wcout, ln2s, ln2b) = refs[1:9]
    ffn_refs = refs[9:n_w]
    # refs[n_w:n_w + 2] are the ALIASED cache inputs — reads and the
    # row write go through the output refs (same buffers)
    xk_ref, xv_ref = refs[n_w + 2:n_w + 4]
    o_ref, cko_ref, cvo_ref = refs[n_w + 4:n_w + 7]
    (q_scr, krow, vrow, kblk, vblk, ckblk, cvblk,
     sem_w, sem_k, sem_v) = refs[n_w + 7:]

    i = pl.program_id(0)
    p = pos_ref[i]
    length = lens_ref[i]
    clen = clens_ref[i]
    act = act_ref[i]

    x0 = x_ref[0].astype(f32)  # [1, d_model]

    # fused qkv projection, per-head column slices of the packed weight
    # (columns [0, hd) are q — the jnp.split third the composition
    # takes).  q lands pre-scaled in f32 scratch; the k/v row lands in
    # cache-dtype scratch, the DMA source for the in-place row write.
    for hi in range(n_head):
        q_scr[hi, :] = jnp.dot(
            x0, wqkv[:, hi * dh:(hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0] * scale
        krow[0, hi, :] = jnp.dot(
            x0, wqkv[:, hd + hi * dh:hd + (hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0].astype(krow.dtype)
        vrow[0, hi, :] = jnp.dot(
            x0, wqkv[:, 2 * hd + hi * dh:2 * hd + (hi + 1) * dh]
            .astype(f32),
            preferred_element_type=f32)[0].astype(vrow.dtype)

    # in-place cache row write at the runtime counter, through the
    # aliased output buffer; inactive lanes keep their rows (the
    # kv_cache_update active mask).  The walk below reads the same
    # buffer, so its window includes this row (lengths == pos + 1 for
    # active lanes).
    @pl.when(act != 0)
    def _write_row():
        wk = pltpu.make_async_copy(
            krow, cko_ref.at[layer, i, pl.ds(p, 1)], sem_w)
        wv = pltpu.make_async_copy(
            vrow, cvo_ref.at[layer, i, pl.ds(p, 1)], sem_w)
        wk.start()
        wv.start()
        wk.wait()
        wv.wait()

    def walk(src_k, src_v, kscr, vscr, n_valid, blk):
        """decode_attention's online-softmax cache walk against this
        sequence's [max_t, h, dh] slice; q rides q_scr (pre-scaled)."""
        q = q_scr[...]
        m0 = jnp.full((n_head,), -jnp.inf, f32)
        l0 = jnp.zeros((n_head,), f32)
        acc0 = jnp.zeros((n_head, d_head), f32)
        n_blk = jax.lax.div(n_valid + (blk - 1), blk)

        def body(t, carry):
            m, l, acc = carry
            ck = pltpu.make_async_copy(
                src_k.at[layer, i, pl.ds(t * blk, blk)], kscr, sem_k)
            cv = pltpu.make_async_copy(
                src_v.at[layer, i, pl.ds(t * blk, blk)], vscr, sem_v)
            ck.start()
            cv.start()
            ck.wait()
            cv.wait()
            kb = jnp.transpose(kscr[...].astype(f32), (1, 0, 2))
            vb = jnp.transpose(vscr[...].astype(f32), (1, 0, 2))
            s = jax.lax.dot_general(
                q[:, None, :], kb,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )[:, 0, :]
            k_pos = t * blk + jax.lax.broadcasted_iota(
                jnp.int32, (n_head, blk), 1)
            s = jnp.where(k_pos < n_valid, s, -1e30)
            m_new = jnp.maximum(m, s.max(axis=1))
            pexp = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + pexp.sum(axis=1)
            pv = jax.lax.dot_general(
                pexp[:, None, :], vb,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=f32,
            )[:, 0, :]
            acc_new = acc * alpha[:, None] + pv
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, n_blk, body, (m0, l0, acc0))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return acc / l_safe[:, None]  # [h, dh] f32

    def proj_heads(ctx, w_ref):
        # output projection as a per-head sum (sublane-aligned slices
        # of the [hd, dm] weight) — ctx never round-trips HBM
        out = jnp.zeros((1, d_model), f32)
        for hi in range(n_head):
            out = out + jnp.dot(
                ctx[hi:hi + 1, :],
                w_ref[hi * dh:(hi + 1) * dh, :].astype(f32),
                preferred_element_type=f32)
        return out

    def layer_norm(y, s_ref, b_ref):
        mean = jnp.mean(y, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(y - mean), axis=1, keepdims=True)
        return ((y - mean) * jax.lax.rsqrt(var + eps)
                * s_ref[...].astype(f32) + b_ref[...].astype(f32))

    # self-attention over the ring cache (incl. the fresh row)
    ctx = walk(cko_ref, cvo_ref, kblk, vblk, length, block_t)
    x1 = layer_norm(x0 + proj_heads(ctx, wout), ln1s, ln1b)

    # cached cross-attention: fresh query, prefilled K/V
    for hi in range(n_head):
        q_scr[hi, :] = jnp.dot(
            x1, wcq[:, hi * dh:(hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0] * scale
    cctx = walk(xk_ref, xv_ref, ckblk, cvblk, clen, cross_block_t)
    x2 = layer_norm(x1 + proj_heads(cctx, wcout), ln2s, ln2b)

    if fuse_ffn:
        fiw, fib, fow, fob, ln3s, ln3b = ffn_refs
        hid = jnp.maximum(
            jnp.dot(x2, fiw[...].astype(f32),
                    preferred_element_type=f32)
            + fib[...].astype(f32), 0.0)
        ffd = jnp.dot(hid, fow[...].astype(f32),
                      preferred_element_type=f32) + fob[...].astype(f32)
        x2 = layer_norm(x2 + ffd, ln3s, ln3b)

    o_ref[0] = x2.astype(o_ref.dtype)


def _ffn_kernel(x_ref, fiw, fib, fow, fob, ln3s, ln3b, o_ref, *, eps):
    """Split-mode second launch: the position-wise feed-forward +
    residual + final layer norm over the whole [b, 1, d_model] batch
    (the FFN weights did not fit VMEM next to the attention set)."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    x0 = x_ref[:, 0, :].astype(f32)  # [b, d_model]
    hid = jnp.maximum(
        jnp.dot(x0, fiw[...].astype(f32), preferred_element_type=f32)
        + fib[...].astype(f32), 0.0)
    ffd = jnp.dot(hid, fow[...].astype(f32),
                  preferred_element_type=f32) + fob[...].astype(f32)
    y = x0 + ffd
    mean = jnp.mean(y, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(y - mean), axis=1, keepdims=True)
    y = ((y - mean) * jax.lax.rsqrt(var + eps) * ln3s[...].astype(f32)
         + ln3b[...].astype(f32))
    o_ref[:, 0, :] = y.astype(o_ref.dtype)


def fused_decode_step(x, wqkv, wout, ln1_scale, ln1_bias, wcq, wcout,
                      ln2_scale, ln2_bias, ffn_in_w, ffn_in_b, ffn_out_w,
                      ffn_out_b, ln3_scale, ln3_bias, cache_k, cache_v,
                      cross_k, cross_v, pos, lengths, cross_lengths,
                      active=None, *, layer, n_head, scale, eps=1e-5,
                      block_t=256, interpret=None):
    """One fused decoder layer over a single embedded token.

    x [b, 1, d_model]; wqkv [d_model, 3*h*dh] (packed q|k|v columns —
    attn_qkv_w); wout/wcout [h*dh, d_model]; wcq [d_model, h*dh]; layer
    norm scale/bias [d_model]; ffn_in_w [d_model, d_inner] (+ bias),
    ffn_out_w [d_inner, d_model] (+ bias); cache_k/cache_v
    [L, b, max_t, h, dh] ring buffers (returned updated — the caller
    aliases them back into scope state); cross_k/cross_v the prefilled
    cross caches (read-only); pos/lengths/cross_lengths [b] int32
    counters; active [b] 0/1 write gate or None.

    Returns (out [b, 1, d_model], cache_k', cache_v').  Off-contract
    shapes (or off-TPU without an explicit interpret=True) run
    reference_decode_step — the numerically-identical composition.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, _, d_model = x.shape
    h = n_head
    dh = cache_k.shape[-1]
    max_t = cache_k.shape[2]
    cross_t = cross_k.shape[2]
    d_inner = ffn_in_w.shape[-1]
    plan = _megastep_plan(d_model, h, dh, d_inner, max_t, cross_t,
                          x.dtype, block_t, interpret)
    if not plan.ok or (plan.interpret and interpret is None):
        # off-TPU the XLA composition beats interpret-mode emulation;
        # tests drive the kernel explicitly with interpret=True
        return reference_decode_step(
            x, wqkv, wout, ln1_scale, ln1_bias, wcq, wcout, ln2_scale,
            ln2_bias, ffn_in_w, ffn_in_b, ffn_out_w, ffn_out_b,
            ln3_scale, ln3_bias, cache_k, cache_v, cross_k, cross_v,
            pos, lengths, cross_lengths, active, layer=layer,
            n_head=n_head, scale=scale, eps=eps)

    def scal(a):
        return jnp.asarray(a).reshape(-1).astype(jnp.int32)

    def row2d(a):
        return jnp.asarray(a).reshape(1, -1)

    act32 = (jnp.ones((b,), jnp.int32) if active is None
             else scal(active))
    weights = [wqkv, wout, row2d(ln1_scale), row2d(ln1_bias), wcq,
               wcout, row2d(ln2_scale), row2d(ln2_bias)]
    if plan.fuse_ffn:
        weights += [ffn_in_w, row2d(ffn_in_b), ffn_out_w,
                    row2d(ffn_out_b), row2d(ln3_scale), row2d(ln3_bias)]

    kernel = functools.partial(
        _megastep_kernel, layer=layer, scale=scale, eps=eps,
        block_t=plan.block_t, cross_block_t=plan.cross_block_t,
        n_head=h, d_head=dh, d_model=d_model, fuse_ffn=plan.fuse_ffn)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # pos, lengths, cross_lengths, active
        grid=(b,),
        in_specs=(
            [pl.BlockSpec((1, 1, d_model), lambda i, *_: (i, 0, 0))]
            + [pl.BlockSpec(w.shape, lambda i, *_: (0, 0))
               for w in weights]
            + [pl.BlockSpec(memory_space=pltpu.ANY)] * 4  # caches
        ),
        out_specs=[
            pl.BlockSpec((1, 1, d_model), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, dh), jnp.float32),        # q (pre-scaled)
            pltpu.VMEM((1, h, dh), cache_k.dtype),   # fresh k row
            pltpu.VMEM((1, h, dh), cache_v.dtype),   # fresh v row
            pltpu.VMEM((plan.block_t, h, dh), cache_k.dtype),
            pltpu.VMEM((plan.block_t, h, dh), cache_v.dtype),
            pltpu.VMEM((plan.cross_block_t, h, dh), cross_k.dtype),
            pltpu.VMEM((plan.cross_block_t, h, dh), cross_v.dtype),
            pltpu.SemaphoreType.DMA,  # row write
            pltpu.SemaphoreType.DMA,  # k walk
            pltpu.SemaphoreType.DMA,  # v walk
        ],
    )
    # input indexing for the aliases counts the 4 prefetch scalars, x,
    # and the weight blocks; each cache buffer IS its output (in-place
    # HBM row write, the scatter-add recipe)
    cache_k_idx = 4 + 1 + len(weights)
    out, cache_k, cache_v = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, d_model), x.dtype),
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
        ],
        input_output_aliases={cache_k_idx: 1, cache_k_idx + 1: 2},
        interpret=bool(plan.interpret),
    )(scal(pos), scal(lengths), scal(cross_lengths), act32, x,
      *weights, cache_k, cache_v, cross_k, cross_v)

    if not plan.fuse_ffn:
        ffn_kernel = functools.partial(_ffn_kernel, eps=eps)
        out = pl.pallas_call(
            ffn_kernel,
            out_shape=jax.ShapeDtypeStruct((b, 1, d_model), x.dtype),
            interpret=bool(plan.interpret),
        )(out, ffn_in_w, row2d(ffn_in_b), ffn_out_w, row2d(ffn_out_b),
          row2d(ln3_scale), row2d(ln3_bias))
    return out, cache_k, cache_v


# ---------------------------------------------------------------------------
# paged-cache variants (FLAGS_paged_kv_cache) — the ring path above is
# untouched so flag-off graphs stay byte-stable
# ---------------------------------------------------------------------------


def _paged_megastep_plan(d_model, n_head, d_head, d_inner, block_t,
                         cross_block_t, batch, max_blocks,
                         cross_max_blocks, dtype, interpret=None):
    """Static feasibility gate for the paged megastep; returns a
    MegastepPlan.  Unlike _megastep_plan the walk blocks are FIXED by
    the pool geometry (misaligned block_t is a build error → reject, no
    snapping), and both flattened block tables must fit the scalar-
    prefetch budget (_PAGED_TABLE_CAP entries) since every walk
    iteration reads its DMA address from SMEM."""
    import jax

    from .decode_attention import _PAGED_TABLE_CAP

    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    esize = _itemsize(dtype)
    bt = int(block_t)
    cbt = int(cross_block_t)
    sublane = 8 if esize >= 4 else 16
    hd = n_head * d_head
    aligned = (
        d_model % 128 == 0
        and d_inner % 128 == 0
        and d_head % 64 == 0
        and n_head % sublane == 0
        and bt % 8 == 0 and bt > 0
        and cbt % 8 == 0 and cbt > 0
        and batch * max_blocks <= _PAGED_TABLE_CAP
        and batch * cross_max_blocks <= _PAGED_TABLE_CAP
    )
    attn_bytes = (
        6 * hd * d_model * esize + d_model * d_head * 4
        + 2 * (bt + cbt) * hd * (esize + 4)
        + 2 * n_head * max(bt, cbt) * 4
    )
    ffn_bytes = 2 * d_model * d_inner * esize + d_inner * 4
    ok = aligned and attn_bytes <= _VMEM_BUDGET and ffn_bytes <= _VMEM_BUDGET
    fuse_ffn = ok and attn_bytes + ffn_bytes <= _VMEM_BUDGET
    return MegastepPlan(ok, fuse_ffn, bt, cbt, interpret)


def reference_decode_step_paged(x, wqkv, wout, ln1_scale, ln1_bias, wcq,
                                wcout, ln2_scale, ln2_bias, ffn_in_w,
                                ffn_in_b, ffn_out_w, ffn_out_b, ln3_scale,
                                ln3_bias, cache_k, cache_v, cross_k,
                                cross_v, pos, lengths, cross_lengths,
                                self_table, cross_table, active=None, *,
                                layer, n_head, scale, eps=1e-5):
    """The composed decoder step over PAGED caches — the exact op chain
    cached_decoder_step emits with FLAGS_paged_kv_cache on and
    FLAGS_fused_decode_step off (paged_kv_cache_update's shared scatter
    core, paged_decode_attention's table-gathered walk), so fused/
    unfused paged programs stay numerically identical on every backend.
    cache_k/cache_v are [L, num_blocks, block_t, h, dh] pools; the
    tables are [b, max_blocks] int32.  Returns (out, cache_k',
    cache_v')."""
    import jax
    import jax.numpy as jnp

    from ..flags import FLAGS
    from . import decode_attention as kda

    b = x.shape[0]
    h = n_head
    dh = cache_k.shape[-1]
    hd = h * dh

    def mul(a, w):
        a2 = a.reshape((b * 1, -1))
        return (a2 @ w).reshape((b, 1, w.shape[-1]))

    def layer_norm(y, s, bias):
        stat = jnp.float32 if y.dtype == jnp.bfloat16 else y.dtype
        ys = y.astype(stat)
        mean = jnp.mean(ys, axis=2, keepdims=True)
        var = jnp.mean(jnp.square(ys - mean), axis=2, keepdims=True)
        out = (ys - mean) * jax.lax.rsqrt(var + eps)
        out = out * s.reshape((1, 1, -1)).astype(stat)
        out = out + bias.reshape((1, 1, -1)).astype(stat)
        return out.astype(y.dtype)

    def write(cache, new):
        return kda.paged_scatter_rows(cache, new.reshape(b, 1, h, dh),
                                      self_table, pos, active, layer)

    def attend(q, kc, vc, tab, lens):
        q3 = q.reshape(b, h, dh)
        lens32 = lens.reshape(-1).astype(jnp.int32)
        if FLAGS.flash_decode:
            o = kda.flash_decode_paged(q3, kc[layer], vc[layer], tab,
                                       lens32, scale=scale)
        else:
            o = kda.reference_decode_paged(q3, kc[layer], vc[layer], tab,
                                           lens32, scale=scale)
        return o.reshape(b, 1, h, dh)

    qkv = mul(x, wqkv)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    cache_k = write(cache_k, k)
    cache_v = write(cache_v, v)
    ctx = attend(q, cache_k, cache_v, self_table, lengths)
    attn_out = mul(ctx.reshape(b, 1, hd), wout)
    x = layer_norm(x + attn_out, ln1_scale, ln1_bias)
    cq = mul(x, wcq)
    cctx = attend(cq, cross_k, cross_v, cross_table, cross_lengths)
    cross_out = mul(cctx.reshape(b, 1, hd), wcout)
    x = layer_norm(x + cross_out, ln2_scale, ln2_bias)
    hid = jax.nn.relu(mul(x, ffn_in_w) + ffn_in_b.reshape((1, 1, -1)))
    ffd = mul(hid, ffn_out_w) + ffn_out_b.reshape((1, 1, -1))
    x = layer_norm(x + ffd, ln3_scale, ln3_bias)
    return x, cache_k, cache_v


def _paged_megastep_kernel(pos_ref, lens_ref, clens_ref, act_ref,
                           stab_ref, ctab_ref, *refs, layer, scale, eps,
                           block_t, cross_block_t, n_head, d_head,
                           d_model, fuse_ffn, max_blocks,
                           cross_max_blocks):
    """The megastep with table-hopped cache traffic: the fresh k/v row
    lands at pool block stab[i, pos // bt] row pos % bt, and both walks
    DMA [block_t, h, dh] pool blocks at scalar-prefetched table
    addresses instead of contiguous ring windows."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    f32 = jnp.float32
    dh = d_head
    hd = n_head * d_head
    n_w = 15 if fuse_ffn else 9
    x_ref = refs[0]
    (wqkv, wout, ln1s, ln1b, wcq, wcout, ln2s, ln2b) = refs[1:9]
    ffn_refs = refs[9:n_w]
    xk_ref, xv_ref = refs[n_w + 2:n_w + 4]
    o_ref, cko_ref, cvo_ref = refs[n_w + 4:n_w + 7]
    (q_scr, krow, vrow, kblk, vblk, ckblk, cvblk,
     sem_w, sem_k, sem_v) = refs[n_w + 7:]

    i = pl.program_id(0)
    p = pos_ref[i]
    length = lens_ref[i]
    clen = clens_ref[i]
    act = act_ref[i]

    x0 = x_ref[0].astype(f32)  # [1, d_model]

    for hi in range(n_head):
        q_scr[hi, :] = jnp.dot(
            x0, wqkv[:, hi * dh:(hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0] * scale
        krow[0, hi, :] = jnp.dot(
            x0, wqkv[:, hd + hi * dh:hd + (hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0].astype(krow.dtype)
        vrow[0, hi, :] = jnp.dot(
            x0, wqkv[:, 2 * hd + hi * dh:2 * hd + (hi + 1) * dh]
            .astype(f32),
            preferred_element_type=f32)[0].astype(vrow.dtype)

    # in-place row write through the table: the covering block's pool
    # address comes from SMEM, the row offset is pos % block_t
    @pl.when(act != 0)
    def _write_row():
        wblk = stab_ref[i * max_blocks + p // block_t]
        woff = p % block_t
        wk = pltpu.make_async_copy(
            krow, cko_ref.at[layer, wblk, pl.ds(woff, 1)], sem_w)
        wv = pltpu.make_async_copy(
            vrow, cvo_ref.at[layer, wblk, pl.ds(woff, 1)], sem_w)
        wk.start()
        wv.start()
        wk.wait()
        wv.wait()

    def walk(src_k, src_v, tab_ref, mb, kscr, vscr, n_valid, blk):
        """The online-softmax walk, block t streaming from pool block
        tab[i * mb + t] of layer `layer`."""
        q = q_scr[...]
        m0 = jnp.full((n_head,), -jnp.inf, f32)
        l0 = jnp.zeros((n_head,), f32)
        acc0 = jnp.zeros((n_head, d_head), f32)
        n_blk = jax.lax.div(n_valid + (blk - 1), blk)

        def body(t, carry):
            m, l, acc = carry
            pb = tab_ref[i * mb + t]
            ck = pltpu.make_async_copy(
                src_k.at[layer, pb], kscr, sem_k)
            cv = pltpu.make_async_copy(
                src_v.at[layer, pb], vscr, sem_v)
            ck.start()
            cv.start()
            ck.wait()
            cv.wait()
            kb = jnp.transpose(kscr[...].astype(f32), (1, 0, 2))
            vb = jnp.transpose(vscr[...].astype(f32), (1, 0, 2))
            s = jax.lax.dot_general(
                q[:, None, :], kb,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )[:, 0, :]
            k_pos = t * blk + jax.lax.broadcasted_iota(
                jnp.int32, (n_head, blk), 1)
            s = jnp.where(k_pos < n_valid, s, -1e30)
            m_new = jnp.maximum(m, s.max(axis=1))
            pexp = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + pexp.sum(axis=1)
            pv = jax.lax.dot_general(
                pexp[:, None, :], vb,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=f32,
            )[:, 0, :]
            acc_new = acc * alpha[:, None] + pv
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, n_blk, body, (m0, l0, acc0))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return acc / l_safe[:, None]

    def proj_heads(ctx, w_ref):
        out = jnp.zeros((1, d_model), f32)
        for hi in range(n_head):
            out = out + jnp.dot(
                ctx[hi:hi + 1, :],
                w_ref[hi * dh:(hi + 1) * dh, :].astype(f32),
                preferred_element_type=f32)
        return out

    def layer_norm(y, s_ref, b_ref):
        mean = jnp.mean(y, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(y - mean), axis=1, keepdims=True)
        return ((y - mean) * jax.lax.rsqrt(var + eps)
                * s_ref[...].astype(f32) + b_ref[...].astype(f32))

    ctx = walk(cko_ref, cvo_ref, stab_ref, max_blocks, kblk, vblk,
               length, block_t)
    x1 = layer_norm(x0 + proj_heads(ctx, wout), ln1s, ln1b)

    for hi in range(n_head):
        q_scr[hi, :] = jnp.dot(
            x1, wcq[:, hi * dh:(hi + 1) * dh].astype(f32),
            preferred_element_type=f32)[0] * scale
    cctx = walk(xk_ref, xv_ref, ctab_ref, cross_max_blocks, ckblk,
                cvblk, clen, cross_block_t)
    x2 = layer_norm(x1 + proj_heads(cctx, wcout), ln2s, ln2b)

    if fuse_ffn:
        fiw, fib, fow, fob, ln3s, ln3b = ffn_refs
        hid = jnp.maximum(
            jnp.dot(x2, fiw[...].astype(f32),
                    preferred_element_type=f32)
            + fib[...].astype(f32), 0.0)
        ffd = jnp.dot(hid, fow[...].astype(f32),
                      preferred_element_type=f32) + fob[...].astype(f32)
        x2 = layer_norm(x2 + ffd, ln3s, ln3b)

    o_ref[0] = x2.astype(o_ref.dtype)


def fused_decode_step_paged(x, wqkv, wout, ln1_scale, ln1_bias, wcq,
                            wcout, ln2_scale, ln2_bias, ffn_in_w,
                            ffn_in_b, ffn_out_w, ffn_out_b, ln3_scale,
                            ln3_bias, cache_k, cache_v, cross_k, cross_v,
                            pos, lengths, cross_lengths, self_table,
                            cross_table, active=None, *, layer, n_head,
                            scale, eps=1e-5, interpret=None):
    """One fused decoder layer over paged caches.

    Same weight operands as fused_decode_step; cache_k/cache_v and
    cross_k/cross_v are [L, num_blocks, block_t, h, dh] pools and
    self_table/cross_table [b, max_blocks] int32 block tables (graph-
    read-only — the host owns allocation).  Returns (out, cache_k',
    cache_v').  Off-contract shapes (or off-TPU without an explicit
    interpret=True) run reference_decode_step_paged."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, _, d_model = x.shape
    h = n_head
    dh = cache_k.shape[-1]
    d_inner = ffn_in_w.shape[-1]
    plan = _paged_megastep_plan(
        d_model, h, dh, d_inner, cache_k.shape[2], cross_k.shape[2], b,
        self_table.shape[1], cross_table.shape[1], x.dtype, interpret)
    if not plan.ok or (plan.interpret and interpret is None):
        return reference_decode_step_paged(
            x, wqkv, wout, ln1_scale, ln1_bias, wcq, wcout, ln2_scale,
            ln2_bias, ffn_in_w, ffn_in_b, ffn_out_w, ffn_out_b,
            ln3_scale, ln3_bias, cache_k, cache_v, cross_k, cross_v,
            pos, lengths, cross_lengths, self_table, cross_table,
            active, layer=layer, n_head=n_head, scale=scale, eps=eps)

    def scal(a):
        return jnp.asarray(a).reshape(-1).astype(jnp.int32)

    def row2d(a):
        return jnp.asarray(a).reshape(1, -1)

    act32 = (jnp.ones((b,), jnp.int32) if active is None
             else scal(active))
    weights = [wqkv, wout, row2d(ln1_scale), row2d(ln1_bias), wcq,
               wcout, row2d(ln2_scale), row2d(ln2_bias)]
    if plan.fuse_ffn:
        weights += [ffn_in_w, row2d(ffn_in_b), ffn_out_w,
                    row2d(ffn_out_b), row2d(ln3_scale), row2d(ln3_bias)]

    kernel = functools.partial(
        _paged_megastep_kernel, layer=layer, scale=scale, eps=eps,
        block_t=plan.block_t, cross_block_t=plan.cross_block_t,
        n_head=h, d_head=dh, d_model=d_model, fuse_ffn=plan.fuse_ffn,
        max_blocks=int(self_table.shape[1]),
        cross_max_blocks=int(cross_table.shape[1]))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        # pos, lengths, cross_lengths, active, self table, cross table
        num_scalar_prefetch=6,
        grid=(b,),
        in_specs=(
            [pl.BlockSpec((1, 1, d_model), lambda i, *_: (i, 0, 0))]
            + [pl.BlockSpec(w.shape, lambda i, *_: (0, 0))
               for w in weights]
            + [pl.BlockSpec(memory_space=pltpu.ANY)] * 4  # pools
        ),
        out_specs=[
            pl.BlockSpec((1, 1, d_model), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, dh), jnp.float32),
            pltpu.VMEM((1, h, dh), cache_k.dtype),
            pltpu.VMEM((1, h, dh), cache_v.dtype),
            pltpu.VMEM((plan.block_t, h, dh), cache_k.dtype),
            pltpu.VMEM((plan.block_t, h, dh), cache_v.dtype),
            pltpu.VMEM((plan.cross_block_t, h, dh), cross_k.dtype),
            pltpu.VMEM((plan.cross_block_t, h, dh), cross_v.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    cache_k_idx = 6 + 1 + len(weights)
    out, cache_k, cache_v = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, d_model), x.dtype),
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
        ],
        input_output_aliases={cache_k_idx: 1, cache_k_idx + 1: 2},
        interpret=bool(plan.interpret),
    )(scal(pos), scal(lengths), scal(cross_lengths), act32,
      scal(self_table), scal(cross_table), x, *weights, cache_k,
      cache_v, cross_k, cross_v)

    if not plan.fuse_ffn:
        ffn_kernel = functools.partial(_ffn_kernel, eps=eps)
        out = pl.pallas_call(
            ffn_kernel,
            out_shape=jax.ShapeDtypeStruct((b, 1, d_model), x.dtype),
            interpret=bool(plan.interpret),
        )(out, ffn_in_w, row2d(ffn_in_b), ffn_out_w, row2d(ffn_out_b),
          row2d(ln3_scale), row2d(ln3_bias))
    return out, cache_k, cache_v
