#!/usr/bin/env bash
# CI entry (reference role: paddle/scripts/paddle_build.sh — cmake_gen:58,
# run_test:408).  Runs the full validation ladder on a plain CPU host:
#   1. full test suite on the virtual 8-device CPU mesh
#   2. bench smoke (real chip if present, else CPU)
#   3. compile-check + multichip dryrun (the driver's graft contract)
# Usage: tools/run_ci.sh [fast]   — "fast" skips the bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/3] test suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

if [[ "${1:-}" != "fast" ]]; then
  echo "== [2/3] bench smoke (telemetry on; snapshot artifact)"
  mkdir -p ci_artifacts
  rm -f ci_artifacts/bench_steps.jsonl  # StepMonitor appends; keep one run
  FLAGS_monitor=1 FLAGS_monitor_jsonl=ci_artifacts/bench_steps.jsonl \
    python bench.py --smoke --monitor-snapshot ci_artifacts/metrics.prom
  echo "-- metrics snapshot:"
  head -40 ci_artifacts/metrics.prom || true
fi

echo "== [3/3] entry compile-check + multichip dryrun"
python __graft_entry__.py

echo "CI OK"
