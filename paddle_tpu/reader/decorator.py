"""Reader decorators (reference: python/paddle/reader/decorator.py:36-215 —
map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers,
multiprocess_reader; and python/paddle/fluid/reader/ batch).

A reader is a zero-arg callable returning an iterator over samples.  The
decorators compose exactly as in the reference; `buffered` runs a background
thread so host-side preprocessing overlaps TPU steps (the role of
operators/reader/buffered_reader.cc)."""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, List

ReaderCreator = Callable[[], Iterable[Any]]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise RuntimeError("readers have different lengths")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch buffer.  Reader exceptions propagate to the
    consumer (not swallowed as end-of-data)."""

    class _End:
        pass

    class _Error:
        def __init__(self, exc):
            self.exc = exc

    def data_reader():
        r = reader()
        q: queue.Queue = queue.Queue(maxsize=size)

        def read_worker():
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                q.put(_Error(e))

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, _Error):
                raise e.exc
            yield e

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists (reference: paddle.batch)."""

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads."""

    class _End:
        pass

    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _End:
                        break
                    i, d = item
                    out_q.put((i, mapper(d)))
                out_q.put(_End)
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                out_q.put(e)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is _End:
                finished += 1
                continue
            if isinstance(item, BaseException):
                raise item
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return data_reader


def cache(reader):
    all_data: List[Any] = []
    filled = [False]

    def data_reader():
        if not filled[0]:
            for d in reader():
                all_data.append(d)
            filled[0] = True
        yield from all_data

    return data_reader


def device_put_chunked(v):
    """Host->device copy; large slabs chunk along dim 0 and transfer on a
    small thread pool — concurrent puts parallelize the host->device link
    (on tunneled chips a single big transfer degrades ~40x; measured
    13 MB/s single vs ~1.1 GB/s with 4 threads x ~32MB chunks)."""
    import numpy as np
    import jax.numpy as jnp

    if hasattr(v, "devices"):  # already a device array
        return v
    from ..flags import FLAGS

    chunk_bytes = FLAGS.prefetch_chunk_mb << 20
    arr = np.asarray(v)
    if arr.nbytes > chunk_bytes and arr.shape and arr.shape[0] > 1:
        import concurrent.futures as cf

        n = min(arr.shape[0], max(2, arr.nbytes // chunk_bytes))
        chunks = np.array_split(arr, n, axis=0)
        with cf.ThreadPoolExecutor(FLAGS.prefetch_threads) as pool:
            parts = list(pool.map(jnp.asarray, chunks))
        return jnp.concatenate(parts, axis=0)
    return jnp.asarray(arr)


def double_buffer(batch_reader, capacity=2):
    """Device-prefetch double buffering (reference:
    operators/reader/buffered_reader.cc — pre-copies batches to the device
    on a side stream; layers/io.py:1002 double_buffer).

    A daemon thread converts upcoming batches to device arrays
    (jnp.asarray = host->HBM copy) while the main thread's current step
    computes; Executor._to_device_array passes device-resident feeds
    through untouched, so the copy never lands on the critical path.
    Works on feed dicts ({name: ndarray}) and tuples/lists of ndarrays.
    """

    def _put(item):
        if isinstance(item, dict):
            return {k: device_put_chunked(v) for k, v in item.items()}
        if isinstance(item, (tuple, list)):
            return type(item)(device_put_chunked(v) for v in item)
        return device_put_chunked(item)

    class _Err:
        def __init__(self, exc):
            self.exc = exc

    def reader():
        import queue
        import threading

        q = queue.Queue(maxsize=capacity)
        end = object()

        def work():
            try:
                for item in batch_reader():
                    q.put(_put(item))
            except Exception as e:  # propagate into the consuming thread
                q.put(_Err(e))
            q.put(end)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            if isinstance(item, _Err):
                raise item.exc
            yield item

    return reader


class StatefulReader:
    """A reader creator with a RESUMABLE epoch/offset cursor (checkpoint v2
    state provider — io.CheckpointManager.register_state).

    Wraps any reader creator; each __call__ yields one epoch while the
    cursor tracks (epoch, items yielded this epoch).  After
    load_state_dict, the NEXT epoch iterated fast-forwards past `offset`
    items, so a resumed run consumes exactly the samples the killed run
    never saw — required for bit-exact kill/resume (the underlying reader
    must be deterministic for a given epoch, as shuffle(seeded) readers
    are).

        sreader = StatefulReader(my_creator)
        mgr.register_state("reader", sreader)
        for feed in sreader():       # one epoch, cursor maintained
            ...
    """

    def __init__(self, reader_creator: ReaderCreator):
        self.creator = reader_creator
        self.epoch = 0
        self.offset = 0
        self._pending_skip = 0

    def __call__(self):
        skip, self._pending_skip = self._pending_skip, 0
        n = 0
        for item in self.creator():
            n += 1
            if n <= skip:
                continue
            self.offset = n
            yield item
        self.epoch += 1
        self.offset = 0

    def state_dict(self) -> dict:
        return {"epoch": int(self.epoch), "offset": int(self.offset)}

    def load_state_dict(self, d: dict) -> None:
        self.epoch = int(d["epoch"])
        self.offset = int(d["offset"])
        self._pending_skip = self.offset
