"""RecordIO: chunked, seekable, CRC-checked record files (reference:
paddle/fluid/recordio/ header.h:39, chunk.h:27, writer.h:22, scanner.h;
python writer recordio_writer.py).

The data plane is native C++ (native/recordio.cc, built on demand with g++
and bound via ctypes — the image has no pybind11), with a byte-compatible
pure-Python fallback so the format works everywhere.  Chunk-level
seekability is what enables sharded reads (`Scanner(path, shard_id,
num_shards)` — the reference's master dispatches chunk tasks the same way,
go/master/service.go).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import zlib
from typing import Iterator, Optional

MAGIC = 0x43525450
_HEADER = struct.Struct("<IIII")

_NATIVE = None
_NATIVE_TRIED = False


def _native_lib():
    """Compile-once-and-cache native/recordio.cc; None if no toolchain."""
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    src = os.path.join(os.path.dirname(__file__), "native", "recordio.cc")
    cache = os.path.join(
        os.path.expanduser(
            os.environ.get("PADDLE_TPU_CACHE", "~/.cache/paddle_tpu")),
        "native",
    )
    so = os.path.join(cache, "librecordio.so")
    try:
        if not os.path.exists(so) or (
            os.path.getmtime(so) < os.path.getmtime(src)
        ):
            os.makedirs(cache, exist_ok=True)
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", src, "-o", so + ".tmp",
                 "-lz"],
                check=True, capture_output=True,
            )
            os.replace(so + ".tmp", so)
        lib = ctypes.CDLL(so)
    except Exception:
        _NATIVE = None
        return None
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    lib.rio_write.restype = ctypes.c_int
    lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_open.restype = ctypes.c_void_p
    lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.rio_num_chunks.restype = ctypes.c_int64
    lib.rio_num_chunks.argtypes = [ctypes.c_void_p]
    lib.rio_seek_chunk.restype = ctypes.c_int
    lib.rio_seek_chunk.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rio_next_in_chunk.restype = ctypes.c_int64
    lib.rio_next_in_chunk.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.rio_load_next_chunk.restype = ctypes.c_int
    lib.rio_load_next_chunk.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_close.restype = None
    lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
    _NATIVE = lib
    return lib


def native_available() -> bool:
    return _native_lib() is not None


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class Writer:
    """Append records (bytes) to a recordio file; chunks auto-flush at
    max_chunk_bytes.  Context-manager."""

    def __init__(self, path: str, max_chunk_bytes: int = 1 << 20,
                 use_native: Optional[bool] = None):
        self._native = (
            _native_lib() if use_native in (None, True) else None
        )
        if use_native is True and self._native is None:
            raise RuntimeError("native recordio unavailable (no g++?)")
        self._path = path
        self._max = max_chunk_bytes
        if self._native is not None:
            self._h = self._native.rio_writer_open(
                path.encode(), max_chunk_bytes)
            if not self._h:
                raise OSError(f"cannot open {path} for writing")
        else:
            self._f = open(path, "wb")
            self._lens = []
            self._payload = bytearray()

    def write(self, record: bytes):
        if self._native is not None:
            rc = self._native.rio_write(self._h, record, len(record))
            if rc != 0:
                raise OSError(f"recordio write failed on {self._path}")
            return
        self._lens.append(len(record))
        self._payload.extend(record)
        if len(self._payload) >= self._max:
            self._flush_py()

    def _flush_py(self):
        if not self._lens:
            return
        body = b"".join(
            [struct.pack("<%dI" % len(self._lens), *self._lens),
             bytes(self._payload)]
        )
        self._f.write(_HEADER.pack(MAGIC, len(self._lens), len(body),
                                   zlib.crc32(body) & 0xFFFFFFFF))
        self._f.write(body)
        self._lens = []
        self._payload = bytearray()

    def close(self):
        if self._native is not None:
            if self._h is not None:
                rc = self._native.rio_writer_close(self._h)
                self._h = None
                if rc != 0:
                    raise OSError(f"recordio close failed on {self._path}")
            return
        self._flush_py()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Scanner
# ---------------------------------------------------------------------------


class Scanner:
    """Iterate records; with (shard_id, num_shards) reads only chunks
    `i % num_shards == shard_id` — the sharded-file-reader capability."""

    def __init__(self, path: str, shard_id: int = 0, num_shards: int = 1,
                 use_native: Optional[bool] = None):
        self._path = path
        self._shard = (shard_id, num_shards)
        self._native = (
            _native_lib() if use_native in (None, True) else None
        )
        if use_native is True and self._native is None:
            raise RuntimeError("native recordio unavailable (no g++?)")

    def __iter__(self) -> Iterator[bytes]:
        shard_id, num_shards = self._shard
        if self._native is not None:
            lib = self._native
            h = lib.rio_scanner_open(self._path.encode())
            if not h:
                raise OSError(f"cannot open/corrupt recordio {self._path}")
            try:
                n = lib.rio_num_chunks(h)
                out = ctypes.c_char_p()
                for ci in range(shard_id, n, num_shards):
                    lib.rio_seek_chunk(h, ci)
                    rc = lib.rio_load_next_chunk(h)
                    if rc == -2:
                        raise OSError(f"crc/corrupt chunk {ci} in "
                                      f"{self._path}")
                    if rc != 0:
                        raise OSError(f"io error reading {self._path}")
                    while True:
                        ln = lib.rio_next_in_chunk(h, ctypes.byref(out))
                        if ln == -3:
                            break
                        yield ctypes.string_at(out, ln)
            finally:
                lib.rio_scanner_close(h)
            return

        with open(self._path, "rb") as f:
            offsets = []
            data = f.read()
            off = 0
            while off + 16 <= len(data):
                magic, num, plen, crc = _HEADER.unpack_from(data, off)
                if magic != MAGIC:
                    raise OSError(f"corrupt recordio {self._path}")
                offsets.append((off, num, plen, crc))
                off += 16 + plen
            if off != len(data):
                raise OSError(f"truncated recordio {self._path}")
            for ci in range(shard_id, len(offsets), num_shards):
                off, num, plen, crc = offsets[ci]
                body = data[off + 16: off + 16 + plen]
                if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                    raise OSError(f"crc mismatch chunk {ci} {self._path}")
                lens = struct.unpack_from("<%dI" % num, body, 0)
                pos = num * 4
                for ln in lens:
                    yield bytes(body[pos:pos + ln])
                    pos += ln

    def num_chunks(self) -> int:
        if self._native is not None:
            lib = self._native
            h = lib.rio_scanner_open(self._path.encode())
            if not h:
                raise OSError(f"cannot open {self._path}")
            try:
                return int(lib.rio_num_chunks(h))
            finally:
                lib.rio_scanner_close(h)
        count = 0
        with open(self._path, "rb") as f:
            while True:
                hdr = f.read(16)
                if not hdr:
                    return count
                magic, num, plen, crc = _HEADER.unpack(hdr)
                if magic != MAGIC:
                    raise OSError(f"corrupt recordio {self._path}")
                f.seek(plen, 1)
                count += 1


def reader_creator(path: str, shard_id: int = 0, num_shards: int = 1):
    """Reader-decorator-style creator yielding raw record bytes."""
    def reader():
        yield from Scanner(path, shard_id, num_shards)
    return reader
