"""Data-parallel tests over the virtual 8-device CPU mesh (reference test
strategy: parallel_executor_test_base.py compares single-device vs
multi-device losses over seeded runs — SURVEY.md §4.4)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_model(seed):
    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = seed
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            img = layers.data(name="img", shape=[32], dtype="float32")
            label = layers.data(name="label", shape=[1], dtype="int64")
            h = layers.fc(input=img, size=64, act="relu")
            pred = layers.fc(input=h, size=10, act="softmax")
            loss = layers.mean(layers.cross_entropy(input=pred, label=label))
            pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return prog, startup, loss


def _batch(rng, n=64):
    x = rng.rand(n, 32).astype("float32")
    y = rng.randint(0, 10, (n, 1)).astype("int64")
    return {"img": x, "label": y}


def test_data_parallel_loss_parity():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"

    losses = {}
    for mode in ("single", "parallel"):
        prog, startup, loss = _build_model(seed=5)
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        target = prog
        if mode == "parallel":
            target = pt.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name
            )
        rng = np.random.RandomState(7)
        ls = []
        for _ in range(5):
            (l,) = exe.run(target, feed=_batch(rng), fetch_list=[loss],
                           scope=scope)
            ls.append(float(np.asarray(l)))
        losses[mode] = ls
    np.testing.assert_allclose(losses["single"], losses["parallel"],
                               rtol=1e-4, atol=1e-5)


def test_data_parallel_grads_synchronized():
    """After one DP step, replicated params must be identical across devices
    and equal to the single-device update."""
    prog, startup, loss = _build_model(seed=9)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    compiled = pt.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    rng = np.random.RandomState(1)
    exe.run(compiled, feed=_batch(rng), fetch_list=[loss], scope=scope)
    # every param is a replicated global array; value must be consistent
    for p in prog.all_parameters():
        v = scope.find_var(p.name)
        assert v is not None
        arr = np.asarray(v)
        assert np.isfinite(arr).all()
