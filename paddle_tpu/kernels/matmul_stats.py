"""Fused matmul + per-column statistics — the Pallas kernel behind the
conv1x1+BN-statistics fusion (PERF.md: ResNet's wall is the BN-stats tier,
a separate roofline-bound HBM pass over every conv output; reference
analogue: the cuDNN fused BN ops the reference reaches through
batch_norm_op.cu).

y = x @ w written as usual, and the per-column sum / sum-of-squares of y
accumulate in VMEM as the M-grid walks — the conv output is never re-read
from HBM to compute batch-norm statistics.  A 1x1 stride-1 NHWC conv IS
this matmul with M = N*H*W (x reshaped for free), which covers the
expand-projections that produce ~2/3 of ResNet's activation volume.

Backward (custom vjp): the stats outputs are linear/quadratic in y, so
their cotangents fold into an effective dY:
    dY_eff = dY + dSum[None, :] + 2 * y * dSqSum[None, :]
then dx = dY_eff @ w^T, dw = x^T @ dY_eff (XLA matmuls; y is already
retained as the BN input residual so the fold costs one fused pass).
"""

from __future__ import annotations

import functools


def _mm_stats_kernel(x_ref, w_ref, y_ref, stats_ref, *, block_m, n_k):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    mi = pl.program_id(1)

    x = x_ref[...]
    w = w_ref[...]
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y_ref[...] = acc.astype(y_ref.dtype)
    # stats of the STORED dtype (bf16-rounded y is what BN's backward
    # recompute sees)
    ys = y_ref[...].astype(jnp.float32)
    s1 = jnp.sum(ys, axis=0)
    s2 = jnp.sum(ys * ys, axis=0)
    tile = jnp.concatenate(
        [jnp.broadcast_to(s1[None, :], (4, s1.shape[0])),
         jnp.broadcast_to(s2[None, :], (4, s2.shape[0]))], axis=0) / 4.0

    @pl.when(mi == 0)
    def _init():
        stats_ref[...] = tile

    @pl.when(mi != 0)
    def _acc():
        stats_ref[...] += tile


def matmul_col_stats(x, w, block_m=512, block_n=512, interpret=None):
    """(y, sum, sqsum) with y = x @ w (x [M, K], w [K, N]); sum/sqsum are
    f32 [N] column statistics of y.  Falls back to plain XLA when shapes
    don't fit the kernel plan.  Differentiable: the custom vjp folds the
    stats cotangents into an effective dY (see module docstring)."""
    import functools as ft

    import jax

    @ft.partial(jax.custom_vjp)
    def _mm(x, w):
        return _matmul_col_stats_fwd_impl(x, w, block_m, block_n,
                                          interpret)

    def _fwd(x, w):
        y, s1, s2 = _matmul_col_stats_fwd_impl(x, w, block_m, block_n,
                                               interpret)
        return (y, s1, s2), (x, w, y)

    def _bwd(res, gs):
        import jax.numpy as jnp

        x, w, y = res
        gy, gsum, gsq = gs
        gy_eff = (gy.astype(jnp.float32) + gsum[None, :]
                  + 2.0 * y.astype(jnp.float32) * gsq[None, :])
        gy_eff = gy_eff.astype(x.dtype)
        dx = jnp.dot(gy_eff, w.T,
                     preferred_element_type=jnp.float32).astype(x.dtype)
        dw = jnp.dot(x.T, gy_eff,
                     preferred_element_type=jnp.float32).astype(w.dtype)
        return dx, dw

    _mm.defvjp(_fwd, _bwd)
    return _mm(x, w)


def _matmul_col_stats_fwd_impl(x, w, block_m, block_n, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    ok = (m % block_m == 0 and n % block_n == 0
          and block_m % 8 == 0 and block_n % 128 == 0)
    if not ok or (not on_tpu and not interpret):
        y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
        ys = y.astype(jnp.float32)
        return y, ys.sum(0), (ys * ys).sum(0)

    grid = (n // block_n, m // block_m)  # m fastest: stats accumulate
    kern = functools.partial(_mm_stats_kernel, block_m=block_m,
                             n_k=k)
    y, stats = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda ni, mi: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda ni, mi: (0, ni)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda ni, mi: (mi, ni)),
            pl.BlockSpec((8, block_n), lambda ni, mi: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
        ],
        interpret=interpret,
    )(x, w)
    return y, stats[:4].sum(0), stats[4:].sum(0)
