"""Program IR: the Python-visible intermediate representation.

Capability parity with the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc
(reference: paddle/fluid/framework/framework.proto:43-188, program_desc.h:30,
block_desc.h:38, op_desc.h:29 and python/paddle/fluid/framework.py:231-1505),
redesigned TPU-first:

  * The IR exists for *introspection and transformation* (autodiff, transpilers,
    pruning, serialization) — NOT for per-op interpretation.  Execution lowers a
    whole block to a single JAX function which XLA compiles for TPU; there is no
    op-by-op runtime loop (contrast executor.cc:448 in the reference).
  * Every registered op carries a JAX lowering; gradients come from grad-op
    makers that default to `jax.vjp` of the forward lowering (see registry.py),
    so the IR stays honest while XLA owns execution.
"""

from __future__ import annotations

import collections
import contextlib
import copy
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# Versioning (reference: framework.proto:24 `Version`, framework/version.h)
# ---------------------------------------------------------------------------

PROGRAM_IR_VERSION = 1


def is_program_version_supported(version: int) -> bool:
    return 0 <= version <= PROGRAM_IR_VERSION


# ---------------------------------------------------------------------------
# unique_name (reference: python/paddle/fluid/unique_name.py)
# ---------------------------------------------------------------------------


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: Dict[str, int] = collections.defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


_name_generator = UniqueNameGenerator()

# When set (by paddle_tpu.imperative.guard), every op appended to any block
# is also executed eagerly: hook(block, op).  Mirrors the reference's
# dygraph Tracer intercepting trace calls (imperative/tracer.cc:42).
_eager_op_hook = None


def unique_name(key: str) -> str:
    return _name_generator(key)


_rng_id_counter = [0]


def unique_rng_id() -> int:
    """Static per-op rng stream id (offset far above the trace-time
    sequential counters next_rng_key hands out)."""
    _rng_id_counter[0] += 1
    return 1_000_000 + _rng_id_counter[0]


@contextlib.contextmanager
def guard_unique_name(new_generator: Optional[UniqueNameGenerator] = None):
    global _name_generator
    old = _name_generator
    _name_generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        _name_generator = old


# ---------------------------------------------------------------------------
# Var types (reference: framework.proto:105-164 VarType; 19 kinds)
# ---------------------------------------------------------------------------


class VarType:
    """Variable kinds.  DENSE_TENSOR subsumes the reference's LOD_TENSOR —
    ragged sequences are represented TPU-idiomatically as dense padding +
    segment ids (see SURVEY.md §5.7) rather than LoD offset tables."""

    DENSE_TENSOR = "dense_tensor"
    SELECTED_ROWS = "selected_rows"  # sparse row-set gradients (embedding)
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


class OpRole:
    """Op role attrs used by transpilers/optimizer passes (reference:
    framework.py OpRole / op_proto_maker.h OpRole)."""

    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256

    ROLE_ATTR_NAME = "op_role"
    ROLE_VAR_ATTR_NAME = "op_role_var"


_dtype_aliases = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": "bfloat16",
    "int8": np.int8,
    "uint8": np.uint8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def convert_dtype(dtype) -> str:
    """Normalize dtype spec to a canonical string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        if dtype in _dtype_aliases:
            return dtype
        return np.dtype(dtype).name
    try:
        import jax.numpy as jnp

        if dtype == jnp.bfloat16:
            return "bfloat16"
    except Exception:  # pragma: no cover
        pass
    return np.dtype(dtype).name


# ---------------------------------------------------------------------------
# Variable (reference: framework.py:231 Variable, var_desc.h)
# ---------------------------------------------------------------------------


class Variable:
    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        type: str = VarType.DENSE_TENSOR,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        initializer=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer

    # -- introspection --------------------------------------------------
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, dtype={self.dtype},"
            f" persistable={self.persistable})"
        )

    __str__ = __repr__

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": self.type,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
        }

    @staticmethod
    def from_dict(block, d):
        return Variable(
            block,
            d["name"],
            shape=d["shape"],
            dtype=d["dtype"],
            type=d.get("type", VarType.DENSE_TENSOR),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            is_data=d.get("is_data", False),
        )


class Parameter(Variable):
    """Trainable variable (reference: framework.py Parameter).  Carries
    optimize/regularization attributes consumed by Optimizer."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", False)
        kwargs.setdefault("persistable", True)
        super().__init__(block, name, shape=shape, dtype=dtype, **kwargs)


# ---------------------------------------------------------------------------
# Operator (reference: framework.py:545 Operator, op_desc.h:29)
# ---------------------------------------------------------------------------


class Operator:
    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        from . import registry

        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, Any] = dict(attrs or {})

        def _norm(io):
            out = {}
            for slot, vs in (io or {}).items():
                if vs is None:
                    out[slot] = []
                    continue
                if isinstance(vs, (Variable, str)):
                    vs = [vs]
                out[slot] = [v.name if isinstance(v, Variable) else v for v in vs]
            return out

        self.inputs = _norm(inputs)
        self.outputs = _norm(outputs)

        opdef = registry.lookup(type)
        if opdef is not None and opdef.infer_shape is not None:
            try:
                opdef.infer_shape(InferShapeContext(self))
            except Exception as e:
                # Best-effort when shapes are genuinely unknown (a None input
                # shape legitimately trips inference); a failure with fully
                # known input shapes is a real graph bug — surface it at the
                # build site with op context instead of as a late XLA trace
                # error (reference: operator.cc RuntimeInferShape ENFORCE).
                shapes = {}
                all_known = True
                for slot, names in self.inputs.items():
                    for n in names:
                        if not n:
                            continue
                        v = block._find_var_recursive(n)
                        s = v.shape if v is not None else None
                        shapes[n] = s
                        if s is None:
                            all_known = False
                if all_known and shapes:
                    raise ValueError(
                        f"infer_shape failed for op {type!r} "
                        f"(input shapes: {shapes}): {e}"
                    ) from e

    # -- slot access -----------------------------------------------------
    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def input_arg_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_arg_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def set_attr(self, name: str, val):
        self.attrs[name] = val

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items() if v}
        outs = {k: v for k, v in self.outputs.items() if v}
        return f"Op(type={self.type}, inputs={ins}, outputs={outs})"

    __str__ = __repr__

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if isinstance(v, np.ndarray):
                attrs[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            elif isinstance(v, Block):
                attrs[k] = {"__block__": v.idx}
            else:
                attrs[k] = v
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": attrs,
        }

    @staticmethod
    def from_dict(block, d):
        attrs = {}
        for k, v in d.get("attrs", {}).items():
            if isinstance(v, dict) and "__ndarray__" in v:
                attrs[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
            elif isinstance(v, dict) and "__block__" in v:
                attrs[k] = block.program.blocks[v["__block__"]]
            else:
                attrs[k] = v
        op = Operator.__new__(Operator)
        op.block = block
        op.type = d["type"]
        op.inputs = {k: list(v) for k, v in d.get("inputs", {}).items()}
        op.outputs = {k: list(v) for k, v in d.get("outputs", {}).items()}
        op.attrs = attrs
        return op


class InferShapeContext:
    """Build-time shape/dtype inference context handed to op defs
    (reference: shape_inference.h InferShapeContext)."""

    def __init__(self, op: Operator):
        self.op = op
        self.block = op.block

    def input_var(self, slot, i=0) -> Optional[Variable]:
        names = self.op.input(slot)
        if i >= len(names):
            return None
        return self.block._find_var_recursive(names[i])

    def input_shape(self, slot, i=0):
        v = self.input_var(slot, i)
        return v.shape if v is not None else None

    def input_dtype(self, slot, i=0):
        v = self.input_var(slot, i)
        return v.dtype if v is not None else None

    def set_output(self, slot, shape, dtype=None, i=0):
        names = self.op.output(slot)
        if i >= len(names):
            return
        v = self.block._find_var_recursive(names[i])
        if v is None:
            return
        if shape is not None:
            v.shape = tuple(int(s) for s in shape)
        if dtype is not None:
            v.dtype = convert_dtype(dtype)

    def attr(self, name, default=None):
        return self.op.attr(name, default)


# ---------------------------------------------------------------------------
# Block (reference: framework.py:986 Block, block_desc.h:38)
# ---------------------------------------------------------------------------


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = collections.OrderedDict()
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- vars -------------------------------------------------------------
    def create_var(self, name=None, **kwargs) -> Variable:
        if name is None:
            name = unique_name("_generated_var")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        # Parameters live in the top-most (global) block, like the reference.
        global_block = self.program.global_block()
        p = Parameter(global_block, name, shape, dtype, **kwargs)
        global_block.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise KeyError(f"Variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def has_var_recursive(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    # -- ops ----------------------------------------------------------------
    def _bump(self):
        self.program._mod_count += 1

    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self._bump()
        if _eager_op_hook is not None:
            _eager_op_hook(self, op)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self._bump()
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self._bump()
        return op

    def remove_op(self, index):
        del self.ops[index]
        self._bump()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def __repr__(self):
        lines = [f"Block {self.idx} (parent {self.parent_idx})"]
        for v in self.vars.values():
            lines.append(f"  {v}")
        for op in self.ops:
            lines.append(f"  {op}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Program (reference: framework.py:1505 Program, program_desc.h:30)
# ---------------------------------------------------------------------------


class Program:
    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.version = PROGRAM_IR_VERSION
        self.random_seed = 0
        self._is_test = False
        self._mod_count = 0  # mutation stamp; part of the executor cache key
        # feed/fetch metadata for inference serialization
        self.feed_var_names: List[str] = []
        self.fetch_var_names: List[str] = []

    # -- blocks -----------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # -- introspection / transforms ----------------------------------------
    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        p = copy.deepcopy(self)
        # the clone is a distinct (possibly further-mutated) program: drop the
        # memoized fingerprint and bump the stamp so no cache aliases the
        # original's executables (e.g. a for_test clone hitting the train
        # entry would keep dropout live and run optimizer ops during eval)
        p._fp_cache = None
        p._mod_count += 1
        if for_test:
            p._is_test = True
            for blk in p.blocks:
                # reference clone(for_test=True) drops backward/optimize/
                # lr-sched ops (framework.py Program.clone + _inference_
                # optimize): an eval program must not update parameters
                # roles are bit flags (a loss-grad fill_constant is
                # Backward|Loss): mask-test like the reference's
                # op_role & (Backward|Optimize) checks, don't compare exactly
                drop_mask = OpRole.Backward | OpRole.Optimize | OpRole.LRSched
                blk.ops = [
                    op for op in blk.ops
                    if not (
                        int(op.attrs.get(OpRole.ROLE_ATTR_NAME, OpRole.Forward))
                        & drop_mask
                        or op.type.endswith("_grad")
                    )
                ]
                for op in blk.ops:
                    if "is_test" in op.attrs or op.type in ("dropout", "batch_norm"):
                        op.attrs["is_test"] = True
        return p

    def prune(self, targets: Sequence[str]) -> "Program":
        """Backward-slice the program to ops needed for `targets`
        (reference: Program._prune / prune_impl framework.py)."""
        p = self.clone()
        blk = p.global_block()
        needed = set(targets)

        def _sub_block_reads(op, seen=None):
            """All names read anywhere inside an op's sub-blocks (while /
            conditional_block bodies) — those vars must survive the slice
            even though the parent op doesn't list them as inputs."""
            seen = seen if seen is not None else set()
            reads = set()
            for a in op.attrs.values():
                if isinstance(a, Block) and a.idx not in seen:
                    seen.add(a.idx)
                    for sub_op in a.ops:
                        reads.update(sub_op.input_arg_names())
                        reads.update(_sub_block_reads(sub_op, seen))
            return reads

        kept = []
        sub_reads_cache = {}
        for op in reversed(blk.ops):
            if any(o in needed for o in op.output_arg_names()):
                kept.append(op)
                needed.update(op.input_arg_names())
                reads = _sub_block_reads(op)
                sub_reads_cache[id(op)] = reads
                needed.update(reads)
        blk.ops = list(reversed(kept))
        p._fp_cache = None
        p._mod_count += 1
        # drop unreferenced non-persistable vars (sub-block reads count:
        # a global-block var consumed only inside a while body stays)
        referenced = set()
        for op in blk.ops:
            referenced.update(op.input_arg_names())
            referenced.update(op.output_arg_names())
            referenced.update(sub_reads_cache[id(op)])
        blk.vars = collections.OrderedDict(
            (n, v)
            for n, v in blk.vars.items()
            if n in referenced or v.persistable or n in targets
        )
        return p

    # -- serialization -------------------------------------------------------
    def to_dict(self):
        return {
            "version": self.version,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
            "feed_var_names": self.feed_var_names,
            "fetch_var_names": self.fetch_var_names,
        }

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @staticmethod
    def parse_from_string(s: bytes) -> "Program":
        d = json.loads(s.decode("utf-8"))
        if not is_program_version_supported(d.get("version", 0)):
            raise ValueError(f"unsupported program version {d.get('version')}")
        p = Program()
        p.version = d.get("version", PROGRAM_IR_VERSION)
        p.random_seed = d.get("random_seed", 0)
        p.feed_var_names = d.get("feed_var_names", [])
        p.fetch_var_names = d.get("fetch_var_names", [])
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(b)
        for b, bd in zip(p.blocks, d["blocks"]):
            for vd in bd["vars"]:
                v = Variable.from_dict(b, vd)
                b.vars[v.name] = v
            for od in bd["ops"]:
                b.ops.append(Operator.from_dict(b, od))
        return p

    def fingerprint(self) -> str:
        import hashlib

        # memoized on the mutation stamp: cheap enough for executor cache keys
        cached = getattr(self, "_fp_cache", None)
        if cached is not None and cached[0] == self._mod_count:
            return cached[1]
        fp = hashlib.sha256(self.serialize_to_string()).hexdigest()
        self._fp_cache = (self._mod_count, fp)
        return fp

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)


# ---------------------------------------------------------------------------
# default programs + guards (reference: framework.py program_guard)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


def grad_var_name(name: str) -> str:
    return name + "@GRAD"
