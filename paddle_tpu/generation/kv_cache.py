"""KVCache: the generation tier's device-resident attention cache.

Ring-buffer layout, ONE buffer per cache side across all layers:

    <prefix>_k / <prefix>_v : [num_layers, batch, max_t, n_head, d_head]
    <prefix>_len            : [batch] int32 valid-row counters

The buffers are persistable scope vars every decode program reads before
writing, so the executor's analyze_block_io classifies them rw-state and
DONATES them to the compiled executable (core/executor.py): cache updates
are in-place HBM writes across steps, the scope write-back is the same
buffer, and nothing about a step depends on how long the sequences have
grown — the compile-cache key is length-independent (fixed max_t shapes,
dynamic-slice writes at the runtime counters).

A KVCache object owns the NAMES and shapes; programs reference the vars
via `vars_in(program)` (declared on demand per program) and the host owns
allocation via `allocate(scope)`.  Graph-side helpers (`write`, `attend`,
`reorder`, `advance`) append the generation ops (ops/generation_ops.py)
against those vars.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper


class KVCache:
    """Names + shapes of one ring-buffer cache (self- or cross-attention).

    For cross-attention the "cache" is filled once at prefill (the
    encoder's projected K/V, lengths = true source lengths) and only read
    during decode — same contract, the write just never recurs.
    """

    def __init__(self, prefix: str, num_layers: int, batch: int,
                 max_t: int, n_head: int, d_head: int,
                 dtype: str = "float32"):
        self.prefix = prefix
        self.num_layers = num_layers
        self.batch = batch
        self.max_t = max_t
        self.n_head = n_head
        self.d_head = d_head
        self.dtype = dtype
        self.k_name = f"{prefix}_k"
        self.v_name = f"{prefix}_v"
        self.len_name = f"{prefix}_len"

    @property
    def shape(self):
        return (self.num_layers, self.batch, self.max_t, self.n_head,
                self.d_head)

    @property
    def hbm_bytes(self) -> int:
        """Resident HBM footprint of the allocated cache: K + V buffers
        plus the int32 length counters — the denominator of the
        generation tier's tokens/sec-per-HBM-GB efficiency gauge."""
        from ..memory.planner import _DTYPE_BYTES

        n = 1
        for d in self.shape:
            n *= int(d)
        return 2 * n * _DTYPE_BYTES.get(self.dtype, 4) + 4 * self.batch

    # -- program side ----------------------------------------------------
    def vars_in(self, program=None, persistable=True):
        """(k_var, v_var, len_var) declared in `program`'s global block
        (default main program), creating the declarations on first
        reference — the same var names in every program that touches
        this cache, so they all resolve to ONE scope buffer.

        persistable=False builds a PROGRAM-LOCAL cache (the build_decoder
        While route: the buffers are zero-filled in-program and carried
        through the loop, never scope-resident — a scope-signature-stable
        single program)."""
        from ..core import framework as fw

        block = (program or fw.default_main_program()).global_block()

        def declare(name, shape, dtype):
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name, shape=list(shape),
                                     dtype=dtype, persistable=persistable,
                                     stop_gradient=True)
            return v

        return (declare(self.k_name, self.shape, self.dtype),
                declare(self.v_name, self.shape, self.dtype),
                # program-local caches derive lengths from the loop
                # counter; declaring an unreferenced counter var would
                # only feed the verifier's dead-var sweep
                declare(self.len_name, (self.batch,), "int32")
                if persistable else None)

    def write(self, k, v, pos, layer: int, active=None):
        """Append a kv_cache_update op: K/V [b, t, h, dh] land at row
        `pos` [b] of cache layer `layer` (rows of inactive sequences are
        kept when `active` [b] is given)."""
        ck, cv, _ = self.vars_in()
        helper = LayerHelper("kv_cache_update")
        ins = {"K": [k], "V": [v], "CacheK": [ck], "CacheV": [cv],
               "Pos": [pos]}
        if active is not None:
            ins["Active"] = [active]
        helper.append_op(
            "kv_cache_update", inputs=ins,
            outputs={"CacheKOut": [ck], "CacheVOut": [cv]},
            attrs={"layer": layer})

    def attend(self, q, lengths, layer: int, scale: float = 1.0):
        """Append a decode_attention op: Q [b, 1, h, dh] against the
        first `lengths` [b] rows of cache layer `layer` -> [b, 1, h, dh]."""
        ck, cv, _ = self.vars_in()
        helper = LayerHelper("decode_attention")
        out = helper.create_variable_for_type_inference(q.dtype)
        helper.append_op(
            "decode_attention",
            inputs={"Q": [q], "CacheK": [ck], "CacheV": [cv],
                    "Lengths": [lengths]},
            outputs={"Out": [out]},
            attrs={"layer": layer, "scale": float(scale)})
        return out

    def reorder(self, parents):
        """Append a kv_cache_reorder op: gather batch slots by the flat
        beam-parent indices `parents` [b] (all layers, both sides)."""
        ck, cv, _ = self.vars_in()
        helper = LayerHelper("kv_cache_reorder")
        helper.append_op(
            "kv_cache_reorder",
            inputs={"CacheK": [ck], "CacheV": [cv], "Parents": [parents]},
            outputs={"CacheKOut": [ck], "CacheVOut": [cv]})

    # -- host side -------------------------------------------------------
    def allocate(self, scope) -> None:
        """Zero-fill the cache buffers + counters into `scope` (device
        arrays; the first donated run takes ownership in HBM)."""
        import jax.numpy as jnp

        target = jnp.bfloat16 if self.dtype == "bfloat16" else self.dtype
        scope.set_var(self.k_name, jnp.zeros(self.shape, target))
        scope.set_var(self.v_name, jnp.zeros(self.shape, target))
        scope.set_var(self.len_name, jnp.zeros((self.batch,), jnp.int32))

    def lengths(self, scope):
        import numpy as np

        return np.asarray(scope.find_var(self.len_name))
