"""Sharded / host-offloaded embedding tables.

Capability parity with the reference's distributed lookup_table path
(reference: operators/lookup_table_op.cc:92 `remote_prefetch`,
operators/distributed/parameter_prefetch.cc — ids split across pservers,
rows pulled over RPC, grads pushed as SelectedRows;
transpiler/distribute_transpiler.py:1334 distributed lookup table),
redesigned TPU-first:

  * **Mesh-sharded table (the default)**: the table lives in HBM,
    vocab-sharded over a mesh axis (`P(axis, None)`).  The in-step gather
    w[ids] on a sharded operand compiles to XLA GSPMD collective gathers
    over ICI — the pserver RPC round-trip becomes compiler-scheduled
    all-to-all traffic.  Use `vocab_sharded_rules()` to produce the
    ShardingPlan param_rules; nothing else changes (same `layers.embedding`
    call, same sparse optimizer path).
  * **Host-offloaded table** (`HostEmbeddingTable`): for tables larger than
    HBM (the reference's pserver-resident case).  The table lives in host
    RAM; each step the caller looks rows up on host, feeds them as a dense
    [K, D] input, and applies the fetched row gradients back on host —
    mirroring the Downpour-style pull/push split
    (python/paddle/fluid/distributed/downpour.py:25) without an RPC layer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def vocab_sharded_rules(
    patterns, axis: str = "model"
) -> List[Tuple[str, object]]:
    """ShardingPlan param_rules entries that shard embedding tables' vocab
    dim over `axis`.  `patterns`: iterable of param-name regexes."""
    from jax.sharding import PartitionSpec as P

    return [(pat, P(axis, None)) for pat in patterns]


class HostEmbeddingTable:
    """Host-RAM embedding table with sparse lookup/update.

    Usage per step (see tests/test_sparse_embedding.py):
        rows = table.lookup(ids)            # host gather -> feed
        ... run program with a dense [K, D] input var, fetch rows_grad ...
        table.apply_grad(ids, rows_grad)    # host sparse update
    """

    def __init__(self, vocab_size: int, dim: int, *, optimizer: str = "sgd",
                 lr: float = 0.01, seed: int = 0, init_scale: float = 0.01,
                 dtype: str = "float32"):
        rng = np.random.RandomState(seed)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.lr = float(lr)
        self.optimizer = optimizer
        self.table = (
            rng.uniform(-init_scale, init_scale, (vocab_size, dim))
            .astype(dtype)
        )
        if optimizer == "adagrad":
            self._moment = np.zeros((vocab_size, dim), dtype)
        elif optimizer != "sgd":
            raise ValueError(f"unsupported host optimizer {optimizer!r}")

    def lookup(self, ids) -> np.ndarray:
        """Gather rows for a batch of ids (any shape; returns
        [..., dim])."""
        ids = np.asarray(ids)
        flat = ids.reshape(-1).astype(np.int64)
        rows = self.table[flat]
        return rows.reshape(ids.shape + (self.dim,))

    def apply_grad(self, ids, rows_grad) -> None:
        """Sparse update from the fetched gradient of the looked-up rows.
        Duplicate ids accumulate (np.add.at), matching SelectedRows
        merge semantics."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        g = np.asarray(rows_grad, dtype=self.table.dtype)
        g = g.reshape(len(ids), self.dim)
        if self.optimizer == "sgd":
            np.add.at(self.table, ids, -self.lr * g)
        else:  # adagrad (merged like SparseAdagradFunctor, adagrad_op.h:24)
            uids, inv = np.unique(ids, return_inverse=True)
            merged = np.zeros((len(uids), self.dim), self.table.dtype)
            np.add.at(merged, inv, g)
            self._moment[uids] += np.square(merged)
            self.table[uids] -= (
                self.lr * merged / (np.sqrt(self._moment[uids]) + 1e-6)
            )

    def save(self, path: str) -> None:
        state = {"table": self.table}
        if self.optimizer == "adagrad":
            state["moment"] = self._moment
        np.savez(path, **state)

    def load(self, path: str) -> None:
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        self.table = data["table"]
        if self.optimizer == "adagrad" and "moment" in data:
            self._moment = data["moment"]
