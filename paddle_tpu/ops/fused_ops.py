"""Fused ops backed by Pallas kernels (the TPU analogue of the reference's
operators/fused/ CPU+cuDNN fusions and operators/jit/ codegen kernels —
SURVEY.md §2.3)."""

from __future__ import annotations

from ..core.registry import register


def _attn_dropout_seed(ctx):
    """(rate, seed) for an attention op's in-kernel weights dropout: 0 in
    is_test, else the step-key-derived (1,) uint32 stream seed keyed by
    the op's static rng_id — shared by fused_attention and
    fused_qkv_attention so the two ops can never diverge in seeding."""
    from ..kernels import hash_rng

    rate = ctx.attr("dropout_rate", 0.0)
    if ctx.attr("is_test", False) or ctx.is_test:
        rate = 0.0
    if not rate:
        return 0.0, None
    base = getattr(ctx.executor_ctx, "base_key", None)
    if base is None:
        base = ctx.executor_ctx._base_key  # eager session
    return rate, hash_rng.seed_from_key(base, ctx.attr("rng_id", 1))


def _bias_is_trainable(ctx, bias):
    """Whether the op's Bias input needs a gradient.  Stop-gradient
    biases (padding/causal masks — the usual case) keep the TPU
    hardware-PRNG dropout fast path: their dbias recompute is
    dead-code-eliminated, so its hash-mask mismatch is unobservable.  A
    genuinely trainable bias forces the hash mask everywhere so the bias
    cotangent sees the same mask the kernels applied."""
    if bias is None:
        return False
    try:
        bname = ctx.op.inputs.get("Bias", [None])[0]
        bvar = ctx.block._find_var_recursive(bname) if bname else None
        return bvar is None or not bvar.stop_gradient
    except Exception:
        return True  # unknown provenance: stay correct


# attr-gated randomness: in-kernel weights dropout draws its mask seed from
# the step key only when dropout_rate is armed — the SAME predicate the
# executor's step-key threading uses (executor._COND_RANDOM_OPS), and what
# the static verifier cross-checks (paddle_tpu/analysis/verifier.py)
def _attn_derives_rng(op) -> bool:
    return bool(op.attrs.get("dropout_rate", 0.0))


@register("fused_attention", derives_rng=_attn_derives_rng)
def lower_fused_attention(ctx, ins):
    """Flash attention over [B,H,T,D] (fmt "bhtd") or [B,T,H,D] (fmt
    "bthd") q/k/v with optional additive bias.  "bthd" is the
    transpose-free convention — see kernels/attention.py.

    dropout_rate > 0 applies the reference's dropout-on-attention-weights
    semantics (transformer_model.py:44) INSIDE the kernels: the mask is the
    counter-based hash of (step base key, rng_id, global element index) —
    deterministic within a step, so the generic vjp re-trace regenerates
    the identical mask in the backward and the [Tq,Tk] mask never exists
    in HBM (see kernels/hash_rng.py)."""
    from ..kernels.attention import flash_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("Bias", [None])[0]
    rate, seed = _attn_dropout_seed(ctx)
    trainable_bias = _bias_is_trainable(ctx, bias)
    out = flash_attention(
        q, k, v, bias,
        scale=ctx.attr("scale", 1.0),
        causal=ctx.attr("causal", False),
        block_q=ctx.attr("block_q", 512),
        block_k=ctx.attr("block_k", 512),
        fmt=ctx.attr("fmt", "bhtd"),
        dropout_rate=rate,
        dropout_seed=seed,
        trainable_bias=trainable_bias,
    )
    return {"Out": [out]}


def _fused_qkv_infer(ctx):
    xs = ctx.input_shape("X")
    ws = ctx.input_shape("WOut")
    if xs is not None and ws is not None:
        ctx.set_output("Out", tuple(xs[:-1]) + (ws[1],),
                       ctx.input_dtype("X"))


@register("fused_qkv_attention", infer_shape=_fused_qkv_infer,
          derives_rng=_attn_derives_rng)
def lower_fused_qkv_attention(ctx, ins):
    """Self-attention with the qkv/output projections fused INTO the flash
    kernels (kernels/attention.py flash_qkv_attention): X [b, t, d_model],
    WQkv [d_model, 3*n_head*d_head] (the layers.fc packed layout), WOut
    [n_head*d_head, d_model], optional additive Bias.  One op replaces the
    flag-off mul + split + fused_attention + reshape + mul chain — q/k/v
    never exist in HBM and the projection-boundary relayout copies
    (PERF.md round 9 lead 1) go with them.  Dropout semantics/seeding
    follow fused_attention (in-kernel weights dropout, step-key-derived
    seed); shapes the kernel plan rejects run the numerically-identical
    composed path."""
    from ..kernels.attention import flash_qkv_attention

    x, w_qkv, w_out = ins["X"][0], ins["WQkv"][0], ins["WOut"][0]
    bias = ins.get("Bias", [None])[0]
    rate, seed = _attn_dropout_seed(ctx)
    trainable_bias = _bias_is_trainable(ctx, bias)
    out = flash_qkv_attention(
        x, w_qkv, w_out, bias,
        n_head=ctx.attr("n_head", 1),
        scale=ctx.attr("scale", 1.0),
        causal=ctx.attr("causal", False),
        block_q=ctx.attr("block_q", 512),
        block_k=ctx.attr("block_k", 512),
        dropout_rate=rate,
        dropout_seed=seed,
        trainable_bias=trainable_bias,
    )
    return {"Out": [out]}


@register("fused_layer_norm_gelu")
def lower_fused_ln_gelu(ctx, ins):
    """layer_norm + gelu epilogue; XLA fuses these — kept as one op so graph
    passes can target it (parity with fuse_elewise_add_act ideas)."""
    import jax

    from .nn_ops import layer_norm_core

    x = ins["X"][0]
    y, _, _ = layer_norm_core(
        x,
        ins.get("Scale", [None])[0],
        ins.get("Bias", [None])[0],
        ctx.attr("begin_norm_axis", x.ndim - 1),
        ctx.attr("epsilon", 1e-5),
    )
    # default matches the standalone gelu op (exact erf form)
    approx = bool(ctx.attr("approximate", False))
    return {"Out": [jax.nn.gelu(y, approximate=approx)]}


def _ring_attention_infer(ctx):
    qs = ctx.input_shape("Q")
    if qs is not None:
        ctx.set_output("Out", tuple(qs), ctx.input_dtype("Q"))


@register("ring_attention", infer_shape=_ring_attention_infer)
def lower_ring_attention(ctx, ins):
    """Context-parallel exact attention: the sequence axis is sharded over a
    mesh axis and K/V shards stream around the ring via ppermute over ICI
    (kernels/ring_attention.py; SURVEY.md §5.7 — a capability the reference
    lacks, its max context is bounded by one device's memory).

    Lowers to shard_map(ring) when the executor's mesh has the `axis_name`
    axis; otherwise (single-device trace, tests, dryrun without an sp axis)
    falls back to the numerically-identical reference attention.  Supports
    causal masking and sequence lengths that do not divide the axis (the
    sharded entry pads and masks via the ring-traveling key bias);
    additive bias is not supported on the ring path (pad-free batches or
    pure-causal decoders)."""
    from ..kernels.attention import _reference_bthd, reference_attention
    from ..kernels.ring_attention import ring_attention_sharded

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    scale = ctx.attr("scale", 1.0)
    causal = ctx.attr("causal", False)
    axis_name = ctx.attr("axis_name", "sp")
    fmt = ctx.attr("fmt", "bhtd")
    mesh = getattr(ctx.executor_ctx, "mesh", None)
    if (
        mesh is None
        or axis_name not in getattr(mesh, "axis_names", ())
    ):
        if fmt == "bthd":
            out = _reference_bthd(q, k, v, None, scale, causal)
        else:
            out = reference_attention(q, k, v, None, scale=scale,
                                      causal=causal)
    else:
        out = ring_attention_sharded(
            q, k, v, mesh, axis_name=axis_name, scale=scale, causal=causal,
            fmt=fmt)
    return {"Out": [out]}
