"""MovieLens 1M (reference: python/paddle/dataset/movielens.py — user/movie
feature readers for the recommender_system book model; samples are
[user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
score]).

Offline fallback: synthetic users/movies with a low-rank preference
structure, so factorization models actually learn."""

from __future__ import annotations

import os
import zipfile

import numpy as np

from . import common

URL = "https://files.grouplens.org/datasets/movielens/ml-1m.zip"

MAX_USER_ID = 6040
MAX_MOVIE_ID = 3952
MAX_JOB_ID = 20
AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]
CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
_TITLE_VOCAB = 1000


def max_user_id():
    return MAX_USER_ID


def max_movie_id():
    return MAX_MOVIE_ID


def max_job_id():
    return MAX_JOB_ID


def age_table():
    return list(AGE_TABLE)


def movie_categories():
    return list(CATEGORIES)


def _use_synth(synthetic):
    return common.use_synthetic(synthetic)


def _synthetic_samples(seed, n=2000, n_users=200, n_movies=300):
    rng = np.random.RandomState(seed)
    d = 4
    uf = rng.randn(n_users + 1, d)
    mf = rng.randn(n_movies + 1, d)
    user_meta = {
        u: (int(rng.randint(0, 2)), int(rng.randint(0, len(AGE_TABLE))),
            int(rng.randint(0, MAX_JOB_ID)))
        for u in range(1, n_users + 1)
    }
    movie_meta = {
        m: (sorted(rng.choice(len(CATEGORIES), rng.randint(1, 4),
                              replace=False).tolist()),
            rng.randint(0, _TITLE_VOCAB, rng.randint(1, 6)).tolist())
        for m in range(1, n_movies + 1)
    }
    for _ in range(n):
        u = int(rng.randint(1, n_users + 1))
        m = int(rng.randint(1, n_movies + 1))
        raw = uf[u] @ mf[m]
        score = float(np.clip(np.round(3.0 + raw), 1, 5))
        g, a, j = user_meta[u]
        cats, title = movie_meta[m]
        yield [u, g, a, j, m, cats, title, score]


def _real_samples(is_test):
    path = common.download(URL, "movielens", None)
    cat_idx = {c: i for i, c in enumerate(CATEGORIES)}
    age_idx = {a: i for i, a in enumerate(AGE_TABLE)}
    users, movies, title_vocab = {}, {}, {}
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/users.dat") as f:
            for line in f.read().decode("latin1").splitlines():
                uid, gender, age, job, _ = line.split("::")
                users[int(uid)] = (
                    0 if gender == "F" else 1, age_idx[int(age)], int(job))
        with z.open("ml-1m/movies.dat") as f:
            for line in f.read().decode("latin1").splitlines():
                mid, title, cats = line.split("::")
                words = title.lower().split()
                for w in words:
                    title_vocab.setdefault(w, len(title_vocab) % _TITLE_VOCAB)
                movies[int(mid)] = (
                    [cat_idx[c] for c in cats.split("|") if c in cat_idx],
                    [title_vocab[w] for w in words],
                )
        with z.open("ml-1m/ratings.dat") as f:
            lines = f.read().decode("latin1").splitlines()
    for i, line in enumerate(lines):
        if (i % 10 == 9) != is_test:  # 90/10 split
            continue
        uid, mid, score, _ = line.split("::")
        uid, mid = int(uid), int(mid)
        if uid not in users or mid not in movies:
            continue
        g, a, j = users[uid]
        cats, title = movies[mid]
        yield [uid, g, a, j, mid, cats, title, float(score)]


def train(synthetic=False):
    def reader():
        if _use_synth(synthetic):
            yield from _synthetic_samples(21)
        else:
            yield from _real_samples(is_test=False)
    return reader


def test(synthetic=False):
    def reader():
        if _use_synth(synthetic):
            yield from _synthetic_samples(22)
        else:
            yield from _real_samples(is_test=True)
    return reader
