"""Automatic mixed precision (bf16) for traced programs.

Capability parity with the reference's float16 support (reference:
paddle/fluid/platform/float16.h — a software half type that op kernels can
compute in), redesigned TPU-first:

  * TPU MXU peak throughput is bf16; fp32 matmuls run at a fraction of peak.
    Instead of per-kernel half-precision variants, we apply an **autocast
    policy at trace time**: matmul/conv-family ops compute in bf16,
    numerically sensitive ops (norms, softmax, losses, optimizer updates)
    compute in fp32.
  * Parameters remain fp32 **master weights** in HBM; the fp32->bf16 cast of
    each weight happens inside the compiled step and XLA fuses it into the
    convolution/matmul (one extra HBM read of the fp32 weight, no extra
    round-trip).
  * Gradients: grad ops re-trace the forward lowering under jax.vjp, so a
    white-listed op's backward also computes in bf16.  Optimizer ops are
    black-listed, so gradients are cast back to fp32 before moment/param
    updates — fp32 accumulation, the standard mixed-precision recipe.
  * bf16 keeps fp32's exponent range, so no loss scaling is required
    (unlike fp16).

Usage::

    prog = pt.default_main_program()
    pt.amp.enable(prog)          # all subsequent Executor.run calls use bf16
    # or: with pt.amp.bf16_guard(prog): exe.run(...)
"""

from __future__ import annotations

import contextlib

# Ops whose FLOPs dominate and map onto the MXU: compute in bf16.
WHITE_OPS = frozenset({
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "conv3d",
    "mul",
    "matmul",
    "fused_attention",
    "fused_qkv_attention",
    "ring_attention",
})

# Numerically sensitive ops: compute in fp32 (reductions over many elements,
# exponentials, running statistics, parameter updates).
BLACK_OPS = frozenset({
    # batch_norm/layer_norm are NOT black-listed: their lowerings accumulate
    # statistics in fp32 internally while producing outputs in the input
    # dtype, so bf16 conv/residual chains stay bf16 without precision loss
    # in the stats.
    "group_norm",
    "data_norm",
    "lrn",
    "softmax",
    "log_softmax",
    # softmax_with_cross_entropy is NOT black-listed: its lowering does the
    # exp-sum/loss in fp32 internally while the [N, V] logits stay bf16 —
    # black-listing it would materialize a ~2 GB fp32 logits copy per
    # transformer-base step (see ops/nn_ops.py lower_softmax_with_ce).
    "cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "bpr_loss",
    "huber_loss",
    "log_loss",
    "hinge_loss",
    "margin_rank_loss",
    "mean",
    "sum",
    "reduce_sum",
    "reduce_mean",
    "reduce_prod",
    "exp",
    "log",
    "cumsum",
    "accuracy",
    "auc",
    "fused_layer_norm_gelu",
    # optimizer ops: fp32 master-weight updates
    "sgd",
    "momentum",
    "lars_momentum",
    "adam",
    "adamax",
    "adagrad",
    "decayed_adagrad",
    "adadelta",
    "rmsprop",
    "ftrl",
    "proximal_gd",
    "proximal_adagrad",
})


def enable(program=None) -> None:
    """Mark `program` (default: the default main program) for bf16 autocast."""
    from .core import framework as fw

    program = program or fw.default_main_program()
    program._amp_bf16 = True
    program._mod_count += 1  # invalidate _mod_count-keyed compile caches


def disable(program=None) -> None:
    from .core import framework as fw

    program = program or fw.default_main_program()
    program._amp_bf16 = False
    program._mod_count += 1


def is_enabled(program) -> bool:
    return bool(getattr(program, "_amp_bf16", False))


@contextlib.contextmanager
def bf16_guard(program=None):
    from .core import framework as fw

    program = program or fw.default_main_program()
    prev = getattr(program, "_amp_bf16", False)
    program._amp_bf16 = True
    try:
        yield
    finally:
        program._amp_bf16 = prev


def _cast_value(v, dtype):
    import jax.numpy as jnp

    if v is None or not hasattr(v, "dtype"):
        return v
    if v.dtype == jnp.float32 and dtype == jnp.bfloat16:
        return v.astype(jnp.bfloat16)
    if v.dtype == jnp.bfloat16 and dtype == jnp.float32:
        return v.astype(jnp.float32)
    return v


# Slot-wise policies: ops that mix MXU compute with fp32 master state in
# ONE op.  conv2d_bn's conv operands and residual stream run bf16 exactly
# as the unfused conv2d + elementwise_add would, but Scale/Bias/Mean/
# Variance are the BN's fp32 running-stat state — a plain WHITE listing
# would downcast the stateful MeanOut/VarianceOut writebacks, BLACK would
# forfeit the MXU (the in-op statistics already accumulate in fp32, same
# as the batch_norm lowering).
SLOT_WHITE_OPS = {
    "conv2d_bn": frozenset({"Input", "Filter", "Residual"}),
}

# Multi-input elementwise ops follow their activations: if any float input is
# already bf16, cast the rest down instead of promoting the bf16 side to fp32
# (an fp32 bias would otherwise drag every post-matmul activation back to
# fp32, forfeiting the bf16 memory/fusion win on matmul-heavy chains).
GRAY_FOLLOW_OPS = frozenset({
    "dropout_add",  # dropout + residual add: follow the activation dtype
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
})


def apply_cast_policy(op_type: str, ins: dict) -> dict:
    """Cast the float inputs of one op per the autocast policy.  Grad ops
    (`X_grad`) inherit X's policy so forward and backward agree."""
    import jax.numpy as jnp

    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    slots = SLOT_WHITE_OPS.get(base)
    if slots is not None:
        return {
            slot: ([_cast_value(v, jnp.bfloat16) for v in vals]
                   if slot in slots else list(vals))
            for slot, vals in ins.items()
        }
    if base in WHITE_OPS:
        target = jnp.bfloat16
    elif base in BLACK_OPS:
        target = jnp.float32
    elif base in GRAY_FOLLOW_OPS:
        if any(
            getattr(v, "dtype", None) == jnp.bfloat16
            for vals in ins.values()
            for v in vals
        ):
            target = jnp.bfloat16
        else:
            return ins
    else:
        return ins
    return {
        slot: [_cast_value(v, target) for v in vals]
        for slot, vals in ins.items()
    }


class LossScaler:
    """Dynamic loss scaling (reference: fluid.contrib.mixed_precision
    DynamicLossScale).  bf16 autocast does not need it — bf16 keeps
    fp32's exponent range — but fp16-style recipes and user-driven
    scaling do, and the numerics tier needs a place to route overflow
    verdicts: monitor/numerics.publish_step_stats calls `update(found)`
    once per step with whether any low-precision grad held Inf/NaN.

    Host-side state only: the user multiplies the loss by `scale` (and
    un-scales grads) in their own graph or feed; this object just runs
    the grow/backoff policy and exports the `amp.loss_scale` gauge.
    Skipped steps (overflow -> caller should drop the update) are
    counted in `amp.overflow_steps`.
    """

    def __init__(self, init_scale: float = 2.0 ** 15,
                 growth_factor: float = 2.0, backoff_factor: float = 0.5,
                 growth_interval: int = 2000,
                 min_scale: float = 1.0, max_scale: float = 2.0 ** 24):
        self.scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)
        self.good_steps = 0
        self.overflow_steps = 0

    def update(self, found_overflow: bool) -> float:
        """Advance the policy one step; returns the new scale."""
        if found_overflow:
            self.overflow_steps += 1
            self.good_steps = 0
            self.scale = max(self.scale * self.backoff_factor,
                             self.min_scale)
        else:
            self.good_steps += 1
            if self.good_steps >= self.growth_interval:
                self.good_steps = 0
                self.scale = min(self.scale * self.growth_factor,
                                 self.max_scale)
        self._export()
        return self.scale

    def _export(self):
        from .monitor import registry as _registry

        if _registry.enabled():
            reg = _registry.default_registry()
            reg.gauge("amp.loss_scale").set(self.scale)
            reg.gauge("amp.overflow_steps").set(self.overflow_steps)


_loss_scaler = None


def set_loss_scaler(scaler) -> None:
    """Install (or clear, with None) the process-wide dynamic loss
    scaler consulted by the numerics tier's overflow publication."""
    global _loss_scaler
    _loss_scaler = scaler
    if scaler is not None:
        scaler._export()


def active_loss_scaler():
    return _loss_scaler
