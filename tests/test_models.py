"""Model-zoo integration tests: DeepFM, BERT, RNN/sequence layers."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


@pytest.mark.slow
def test_deepfm_trains():
    from paddle_tpu.models import deepfm

    avg_cost, auc_var, predict, feeds = deepfm.build_train_net(
        embedding_size=4, hash_dim=101, lr=1e-2
    )
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    batch = deepfm.make_batch(64, hash_dim=101, rng=rng)
    losses = []
    for _ in range(10):
        l, auc = exe.run(feed=batch, fetch_list=[avg_cost, auc_var])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0]
    assert 0.0 <= float(np.asarray(auc)) <= 1.0


def test_bert_trains():
    from paddle_tpu.models import bert

    avg_loss, enc = bert.build_pretrain_net(
        vocab_size=211, seq_len=32, n_layer=2, n_head=2, d_model=32, d_ff=64,
        lr=5e-3,
    )
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    batch = bert.make_batch(4, 32, 211)
    losses = []
    for _ in range(12):
        (l,) = exe.run(feed=batch, fetch_list=[avg_loss])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0], losses


def test_dynamic_lstm_matches_manual():
    b, t, d = 2, 5, 3
    rng = np.random.RandomState(0)
    x_np = rng.randn(b, t, 4 * d).astype("float32") * 0.5
    w_np = rng.randn(d, 4 * d).astype("float32") * 0.3

    x = layers.data(name="x", shape=[t, 4 * d], dtype="float32")
    hidden, cell = layers.dynamic_lstm(
        input=x, size=4 * d, use_peepholes=False,
        param_attr=pt.ParamAttr(name="lstm_w"),
        bias_attr=pt.ParamAttr(name="lstm_b",
                               initializer=pt.initializer.Constant(0.0)),
    )
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    pt.global_scope().set_var("lstm_w", np.asarray(w_np))
    (h,) = exe.run(feed={"x": x_np}, fetch_list=[hidden])

    # manual reference
    def sig(v):
        return 1 / (1 + np.exp(-v))

    h_prev = np.zeros((b, d), np.float32)
    c_prev = np.zeros((b, d), np.float32)
    outs = []
    for step in range(t):
        gates = x_np[:, step] + h_prev @ w_np
        # Reference gate-buffer layout (math/detail/lstm_cpu_kernel.h:50-53):
        # offset 0 = candidate (active_node), then input, forget, output gates.
        c_t, i, f, o = np.split(gates, 4, axis=1)
        c_prev = sig(f) * c_prev + sig(i) * np.tanh(c_t)
        h_prev = sig(o) * np.tanh(c_prev)
        outs.append(h_prev.copy())
    expected = np.stack(outs, axis=1)
    np.testing.assert_allclose(h, expected, atol=1e-5, rtol=1e-4)


def test_dynamic_gru_shapes_and_masking():
    b, t, d = 3, 6, 4
    x = layers.data(name="x", shape=[t, 3 * d], dtype="float32")
    length = layers.data(name="len", shape=[1], dtype="int64")
    hidden = layers.dynamic_gru(input=x, size=d, length=length)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    xv = np.random.randn(b, t, 3 * d).astype("float32")
    lens = np.array([[6], [3], [1]], np.int64)
    (h,) = exe.run(feed={"x": xv, "len": lens}, fetch_list=[hidden])
    assert h.shape == (b, t, d)
    # past the length, hidden state must be frozen
    np.testing.assert_allclose(h[1, 3], h[1, 2], rtol=1e-6)
    np.testing.assert_allclose(h[2, 5], h[2, 0], rtol=1e-6)


def test_sequence_pool_masked():
    x = layers.data(name="x", shape=[4, 3], dtype="float32")
    length = layers.data(name="len", shape=[1], dtype="int64")
    avg = layers.sequence_pool(x, "average", length=length)
    mx = layers.sequence_pool(x, "max", length=length)
    last = layers.sequence_pool(x, "last", length=length)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.arange(24, dtype="float32").reshape(2, 4, 3)
    lens = np.array([[2], [4]], np.int64)
    a, m, l = exe.run(feed={"x": xv, "len": lens}, fetch_list=[avg, mx, last])
    np.testing.assert_allclose(a[0], xv[0, :2].mean(0))
    np.testing.assert_allclose(a[1], xv[1].mean(0))
    np.testing.assert_allclose(m[0], xv[0, :2].max(0))
    np.testing.assert_allclose(l[0], xv[0, 1])
    np.testing.assert_allclose(l[1], xv[1, 3])


def test_edit_distance():
    hyp = layers.data(name="hyp", shape=[5], dtype="int64")
    ref = layers.data(name="ref", shape=[5], dtype="int64")
    hl = layers.data(name="hl", shape=[1], dtype="int64")
    rl = layers.data(name="rl", shape=[1], dtype="int64")
    dist, num = layers.edit_distance(hyp, ref, normalized=False,
                                     input_length=hl, label_length=rl)
    exe = pt.Executor(pt.CPUPlace())
    (d,) = exe.run(
        feed={
            "hyp": np.array([[1, 2, 3, 0, 0], [1, 1, 1, 1, 0]], np.int64),
            "ref": np.array([[1, 3, 3, 0, 0], [2, 2, 2, 0, 0]], np.int64),
            "hl": np.array([[3], [4]], np.int64),
            "rl": np.array([[3], [3]], np.int64),
        },
        fetch_list=[dist],
    )
    # kitten-style: [1,2,3] vs [1,3,3] = 1 sub; [1,1,1,1] vs [2,2,2] = 4? no:
    # 3 subs + 1 del = 4... classic DP gives 4
    np.testing.assert_allclose(d.ravel(), [1.0, 4.0])


def test_resnet_nhwc_matches_nchw():
    """Channel-last tower must produce the same loss as NCHW with the same
    (OIHW-shaped) parameters."""
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import resnet as R

    def build(fmt):
        prog, startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(prog, startup):
                img, label, avg_cost, acc, _ = R.build_train_net(
                    class_dim=10, image_shape=(3, 32, 32), depth=18,
                    with_optimizer=False, data_format=fmt)
        return prog, startup, avg_cost

    rng = np.random.RandomState(0)
    feed = {
        "image": rng.rand(4, 3, 32, 32).astype("float32"),
        "label": rng.randint(0, 10, (4, 1)).astype("int64"),
    }
    exe = pt.Executor(pt.CPUPlace())
    losses = {}
    for fmt in ("NCHW", "NHWC"):
        prog, startup, cost = build(fmt)
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        if fmt == "NCHW":
            saved = {
                p.name: np.asarray(scope.find_var(p.name))
                for p in prog.all_parameters()
            }
        else:
            for name, val in saved.items():
                scope.set_var(name, val)
        (lv,) = exe.run(prog, feed=feed, fetch_list=[cost], scope=scope)
        losses[fmt] = float(np.asarray(lv))
    assert abs(losses["NCHW"] - losses["NHWC"]) < 1e-4, losses
