"""Request-scoped distributed tracing + SLO burn-rate accounting.

The process-level observability of PRs 1-2 (metrics registry, flight
recorder, unified chrome timeline) answers "is the server healthy?"; this
tier answers "why was THIS request slow?".  It is the Dapper span-
propagation pattern (and Orca's iteration-level accounting for the decode
path) rebuilt dependency-free in the repo's stdlib idiom:

  * every serving request gets a TRACE — a W3C `traceparent`-compatible
    id accepted from (and echoed to) the client, generated otherwise —
    that rides the queued request object through BOTH batchers;
  * SPANS record the full latency decomposition: HTTP parse, admission
    decision, queue wait, batch forming (with FAN-IN: one executor-run
    span is parented by N coalesced request spans — the dynamic-batching
    analogue of an RPC fan-out), pad-to-bucket overhead (rows padded vs
    real, the wasted-compute metric the batch-fill histogram cannot
    attribute per request), executor compile vs run wall time (hooked
    per invocation in core/executor.py), de-batch slice, response write;
    for generation: prefill, per-token decode-iteration spans with slot
    occupancy, and the TTFT linkage;
  * finished traces land in a BOUNDED store (FLAGS_trace_store) served at
    /v1/traces[?last=N] and /v1/traces/<id>, in the flight ring (kinds
    `trace.span` / `trace.request`, so crash dumps carry request state
    and the unified chrome timeline renders request spans next to the
    xplane device ops on one clock), and in the response itself
    (`meta.trace` decomposition block + `traceparent` response header);
  * the SLO engine (FLAGS_serving_slo_ms) counts every finished/shed
    request as a good or bad event per model and refreshes multi-window
    BURN-RATE gauges on every /metrics scrape via the registry's collect
    hook (registry.SloTracker).

Zero-cost contract (the FLAGS_monitor discipline): with
FLAGS_trace_requests off, `start()` returns None after ONE flag read —
no trace objects, no spans, no flight events, no registry entries exist
on the request path.  The SLO engine is gated the same way on its own
flag (empty FLAGS_serving_slo_ms = off).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

from .registry import SloTracker, default_registry
from .registry import enabled as _monitor_enabled
from .step import EPOCH_OFFSET

# hard per-trace span cap: a long generation (one span per decode
# iteration) must not grow a trace without bound; drops are counted on
# the trace (`dropped_spans`)
MAX_SPANS = 512

# open-trace registry cap (crash-dump header state): leaked traces (a
# caller that never finishes) evict oldest-first instead of growing
MAX_OPEN = 1024


def enabled() -> bool:
    """Whether request-path call sites should trace (one flag read)."""
    from ..flags import FLAGS

    return FLAGS.trace_requests


def pc_to_epoch(pc: float) -> float:
    """perf_counter stamp -> epoch seconds (the span clock; the same
    offset StepMonitor bridges flight spans with, so request spans,
    executor spans and xplane device ops share one timeline)."""
    return pc + EPOCH_OFFSET


# ---------------------------------------------------------------------------
# W3C traceparent (https://www.w3.org/TR/trace-context/)
# ---------------------------------------------------------------------------


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]):
    """-> (trace_id, parent_span_id) or None on anything malformed (an
    unparseable header starts a fresh trace instead of failing the
    request — propagation is best-effort by contract)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if (len(version) != 2 or version == "ff"
            or len(trace_id) != 32 or len(span_id) != 16):
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
        int(version, 16)
    except ValueError:
        return None
    return trace_id, span_id


# ---------------------------------------------------------------------------
# Spans and traces
# ---------------------------------------------------------------------------


class Span:
    __slots__ = ("name", "span_id", "parent_id", "t0", "dur", "attrs")

    def __init__(self, name, span_id, parent_id, t0, dur, attrs):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = float(t0)      # epoch seconds
        self.dur = float(dur)    # seconds
        self.attrs = attrs

    def to_json(self) -> dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id, "t0": round(self.t0, 6),
             "dur_ms": round(self.dur * 1e3, 3)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


# component span names summed into the decomposition, per trace kind, in
# pipeline order.  These TILE the request window: queue.wait ends where
# batch.form starts, batch.form where batch.exec starts, ... — so
# sum(components) + unattributed == total by construction, and the
# acceptance gate asserts unattributed <= 5% of wall clock.  Sub-spans
# (batch.pad inside batch.form, executor.* inside batch.exec/prefill/
# decode.step) are reported separately, never double-counted.
_COMPONENTS = {
    "predict": ("parse", "admission", "queue.wait", "batch.form",
                "batch.exec", "debatch", "respond"),
    "generate": ("parse", "admission", "queue.wait", "prefill",
                 "decode", "deliver", "respond"),
}
_SUB_SPANS = ("batch.pad", "executor.compile", "executor.run",
              "decode.step")


class RequestTrace:
    """One request's span tree; thread-safe (the HTTP handler thread and
    the batcher scheduler thread both append)."""

    __slots__ = ("trace_id", "kind", "model", "root", "spans", "status",
                 "dropped_spans", "decomp", "client_parent", "_lock",
                 "_done")

    def __init__(self, kind: str, model: str,
                 trace_id: Optional[str] = None,
                 client_parent: Optional[str] = None,
                 t0: Optional[float] = None):
        self.trace_id = trace_id or new_trace_id()
        self.client_parent = client_parent
        self.kind = kind
        self.model = model
        self.status = "open"
        self.dropped_spans = 0
        self.decomp: Optional[dict] = None
        self._lock = threading.Lock()
        self._done = False
        self.root = Span(kind, new_span_id(), client_parent,
                         pc_to_epoch(time.perf_counter())
                         if t0 is None else t0, 0.0, {})
        self.spans: List[Span] = [self.root]

    @property
    def root_span_id(self) -> str:
        return self.root.span_id

    @property
    def done(self) -> bool:
        return self._done

    def traceparent(self) -> str:
        """The header value echoed to the client (root span id as the
        parent of any further client-side spans)."""
        return format_traceparent(self.trace_id, self.root.span_id)

    def add_span(self, name: str, t0: float, t1: Optional[float] = None,
                 dur: Optional[float] = None,
                 parent_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 record_flight: bool = True, **attrs) -> Optional[Span]:
        """Append one completed span (epoch t0; t1 or dur).  Shared spans
        (a batch executed for N requests) pass ONE span_id into every
        participating trace and record_flight only once — each trace owns
        a copy whose parent is its own root, with the full parent list in
        attrs (the fan-in contract).  No-op after finish()."""
        if dur is None:
            dur = 0.0 if t1 is None else (t1 - t0)
        dur = max(0.0, float(dur))
        sp = Span(name, span_id or new_span_id(),
                  parent_id or self.root.span_id, t0, dur, attrs)
        with self._lock:
            if self._done:
                return None
            if len(self.spans) >= MAX_SPANS:
                self.dropped_spans += 1
                return None
            self.spans.append(sp)
        if record_flight and _monitor_enabled():
            from . import flight

            flight.record("trace.span", trace=self.trace_id,
                          span=sp.span_id, name=name, model=self.model,
                          t0=round(sp.t0, 6), dur=round(sp.dur, 6),
                          **{k: v for k, v in attrs.items()
                             if isinstance(v, (int, float, str, bool))})
        return sp

    def set_attr(self, **attrs) -> None:
        self.root.attrs.update(attrs)

    def finish(self, status: str = "ok",
               t_end: Optional[float] = None) -> None:
        """Close the root span, compute the decomposition, land the trace
        in the store + flight ring.  Idempotent — the first caller wins
        (a batcher-side error finish beats the handler's epilogue).
        Stamps ride the bridged perf_counter clock like every span —
        time.time() would drift off it under NTP slew on a long-lived
        server."""
        with self._lock:
            if self._done:
                return
            self._done = True
        self.status = status
        self.root.dur = max(
            0.0, (pc_to_epoch(time.perf_counter())
                  if t_end is None else t_end) - self.root.t0)
        self.decomp = self.decomposition()
        _unregister_open(self)
        _store.add(self)
        if _monitor_enabled():
            from . import flight

            d = self.decomp
            pad = d.get("padding") or {}
            flight.record(
                "trace.request", trace=self.trace_id, model=self.model,
                trace_kind=self.kind, status=status,
                t0=round(self.root.t0, 6), dur=round(self.root.dur, 6),
                total_ms=d["total_ms"], decomposition=d,
                padded_rows=pad.get("rows_padded", 0))

    # -- decomposition ---------------------------------------------------
    def decomposition(self) -> dict:
        """Per-request latency decomposition from the span tree.  Before
        finish() the total (and unattributed remainder) are omitted —
        that partial form is what rides the response's meta.trace block
        (the respond span cannot be measured before the response is
        serialized)."""
        if self.decomp is not None:
            return self.decomp
        comp_names = _COMPONENTS.get(self.kind, ())
        by: Dict[str, float] = {}
        exec_ms = {"compile": 0.0, "run": 0.0}
        decode_ms, decode_steps = 0.0, 0
        pad = None
        with self._lock:
            spans = list(self.spans)
        for sp in spans[1:]:
            if sp.name == "decode.step":
                decode_ms += sp.dur * 1e3
                decode_steps += 1
            elif sp.name == "executor.compile":
                exec_ms["compile"] += sp.dur * 1e3
            elif sp.name == "executor.run":
                exec_ms["run"] += sp.dur * 1e3
            elif sp.name == "batch.pad":
                pad = dict(sp.attrs, pad_ms=round(sp.dur * 1e3, 3))
            elif sp.name in comp_names:
                by[sp.name] = by.get(sp.name, 0.0) + sp.dur * 1e3
        if decode_steps:
            by["decode"] = decode_ms
        out = {"components_ms": {k: round(v, 3)
                                 for k, v in by.items()}}
        if self._done:
            total = self.root.dur * 1e3
            out["total_ms"] = round(total, 3)
            out["unattributed_ms"] = round(
                max(0.0, total - sum(by.values())), 3)
        if exec_ms["compile"] or exec_ms["run"]:
            out["executor_ms"] = {k: round(v, 3)
                                  for k, v in exec_ms.items()}
        if decode_steps:
            out["decode_steps"] = decode_steps
        if pad is not None:
            out["padding"] = pad
        return out

    def meta_block(self) -> dict:
        """The in-response `meta.trace` block (partial decomposition —
        the respond span and total are not measurable pre-response; the
        full record is at /v1/traces/<id>)."""
        return {"trace_id": self.trace_id,
                "traceparent": self.traceparent(),
                **self.decomposition()}

    def to_json(self) -> dict:
        d = {"trace_id": self.trace_id, "kind": self.kind,
             "model": self.model, "status": self.status,
             "t0": round(self.root.t0, 6),
             "dur_ms": round(self.root.dur * 1e3, 3),
             "traceparent": self.traceparent(),
             "decomposition": self.decomposition(),
             "spans": [s.to_json() for s in list(self.spans)]}
        if self.dropped_spans:
            d["dropped_spans"] = self.dropped_spans
        if self.client_parent:
            d["client_parent"] = self.client_parent
        return d


def add_shared_span(traces, name: str, t0: float, t1: float,
                    floors=None, parent_id=None, per_attrs=None,
                    fan_in_attrs=True, **attrs) -> Optional[str]:
    """One logical span shared by N traces (the coalesced-batch fan-in):
    every trace gets a copy under ONE span id; the flight ring sees it
    once — via the first trace that ACCEPTS it, since a finished member
    (waiter timed out before the batch ran) no-ops its add_span and
    blindly electing traces[0] would drop the span from the ring for
    the whole batch.

    `floors` (parallel to traces, epoch seconds) clamps each copy's
    START — a late joiner must not receive span time from before it
    arrived, or its components would sum past its own wall clock (the
    tiling contract the CI sum-gate asserts).  `per_attrs` (parallel
    dicts) carries per-member attrs (slot, token index); `fan_in_attrs`
    False drops the fan_in/parents bookkeeping for high-frequency spans
    (per-token decode iterations)."""
    items = [(t,
              None if floors is None else floors[i],
              {} if per_attrs is None else per_attrs[i])
             for i, t in enumerate(traces) if t is not None]
    if not items:
        return None
    sid = new_span_id()
    if fan_in_attrs:
        attrs = dict(attrs, fan_in=len(items),
                     parents=[t.root_span_id for t, _, _ in items])
    recorded = False
    for tr, floor, extra in items:
        t0_eff = t0 if floor is None else min(max(t0, floor), t1)
        sp = tr.add_span(name, t0_eff, t1, span_id=sid,
                         parent_id=parent_id,
                         record_flight=not recorded,
                         **dict(attrs, **extra))
        recorded = recorded or sp is not None
    return sid


# ---------------------------------------------------------------------------
# Trace store (/v1/traces) + open-trace registry (crash-dump state)
# ---------------------------------------------------------------------------


class TraceStore:
    """Bounded id -> finished RequestTrace store, newest-wins eviction
    (capacity FLAGS_trace_store read at insert so tests can shrink it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, RequestTrace]" = \
            collections.OrderedDict()

    def add(self, trace: RequestTrace) -> None:
        from ..flags import FLAGS

        cap = max(1, int(FLAGS.trace_store))
        with self._lock:
            self._traces.pop(trace.trace_id, None)
            self._traces[trace.trace_id] = trace
            while len(self._traces) > cap:
                self._traces.popitem(last=False)

    def get(self, trace_id: str) -> Optional[RequestTrace]:
        with self._lock:
            return self._traces.get(trace_id)

    def last(self, n: int = 20) -> List[RequestTrace]:
        """Most recent first."""
        with self._lock:
            traces = list(self._traces.values())
        return traces[::-1][:max(0, int(n))]

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_store = TraceStore()
_open_lock = threading.Lock()
_open_traces: "collections.OrderedDict[str, RequestTrace]" = \
    collections.OrderedDict()
_provider_registered = [False]


def default_store() -> TraceStore:
    return _store


def _register_open(trace: RequestTrace) -> None:
    with _open_lock:
        _open_traces[trace.trace_id] = trace
        while len(_open_traces) > MAX_OPEN:
            _open_traces.popitem(last=False)
    if not _provider_registered[0]:
        _provider_registered[0] = True
        from . import flight

        flight.add_header_provider(_open_trace_header)


def _unregister_open(trace: RequestTrace) -> None:
    with _open_lock:
        _open_traces.pop(trace.trace_id, None)


def _open_trace_header() -> dict:
    """Flight dump-header provider: the requests IN FLIGHT when the dump
    fired — the first question of a serving postmortem."""
    now = pc_to_epoch(time.perf_counter())
    with _open_lock:
        open_now = list(_open_traces.values())
    if not open_now:
        return {}
    return {
        "open_trace_count": len(open_now),
        "open_traces": [
            {"trace": t.trace_id, "model": t.model, "kind": t.kind,
             "age_s": round(max(0.0, now - t.root.t0), 3),
             "spans": len(t.spans)}
            for t in open_now[-32:]
        ],
    }


def wait_for(trace_id: str, timeout: float = 0.25) -> \
        Optional[RequestTrace]:
    """Read-your-writes for /v1/traces/<id>: a client that just read its
    response can race the handler's trace.finish() by microseconds —
    when the id is OPEN (in flight), wait briefly for it to land in the
    store; an unknown id returns None immediately."""
    deadline = time.monotonic() + timeout
    while True:
        tr = _store.get(trace_id)
        if tr is not None:
            return tr
        with _open_lock:
            is_open = trace_id in _open_traces
        if not is_open or time.monotonic() >= deadline:
            return None
        time.sleep(0.005)


def start(kind: str, model: str, traceparent: Optional[str] = None,
          t0: Optional[float] = None) -> Optional[RequestTrace]:
    """Begin a request trace, or None when FLAGS_trace_requests is off
    (the zero-cost gate: every call site is `trace = tracing.start(...)`
    + `if trace is not None` guards)."""
    if not enabled():
        return None
    parsed = parse_traceparent(traceparent)
    tr = RequestTrace(kind, model,
                      trace_id=parsed[0] if parsed else None,
                      client_parent=parsed[1] if parsed else None, t0=t0)
    _register_open(tr)
    return tr


def reject(trace: Optional[RequestTrace], reason: str,
           t0: Optional[float] = None) -> None:
    """Close a trace that never reached the executor (shed / draining /
    breaker-open / stopped): one `admission` span naming the outcome,
    status `rejected:<reason>`.  A trace that ALREADY carries an
    admission span was admitted and failed later (the batcher stop()
    path) — only the status closes then; a second admission span with a
    contradictory outcome would misreport where the request died."""
    if trace is None:
        return
    now = pc_to_epoch(time.perf_counter())
    with trace._lock:
        admitted = any(s.name == "admission" for s in trace.spans)
    if not admitted:
        trace.add_span("admission", now if t0 is None else t0, now,
                       outcome=reason)
    trace.finish(status=f"rejected:{reason}", t_end=now)


# ---------------------------------------------------------------------------
# Executor span hook (core/executor.py _record_run_metrics)
# ---------------------------------------------------------------------------

_exec_ctx = threading.local()

import contextlib as _contextlib


@_contextlib.contextmanager
def executor_context(traces):
    """Arm the current thread so executor compile/run wall times land as
    sub-spans in every participating trace — the batchers wrap their
    model calls in this."""
    traces = [t for t in traces if t is not None]
    prev = getattr(_exec_ctx, "traces", None)
    _exec_ctx.traces = traces or None
    try:
        yield
    finally:
        _exec_ctx.traces = prev


def note_executor(mode: str, t0_epoch: float, dur: float,
                  compiled: bool) -> None:
    """Called by the executor telemetry epilogue for every monitored run:
    one thread-local read when no trace context is armed.  The executor
    already flight-records its own span, so these copies skip the ring."""
    traces = getattr(_exec_ctx, "traces", None)
    if not traces:
        return
    name = "executor.compile" if compiled else "executor.run"
    sid = new_span_id()
    for tr in traces:
        tr.add_span(name, t0_epoch, dur=dur, span_id=sid,
                    record_flight=False, mode=mode)


# ---------------------------------------------------------------------------
# SLO engine (FLAGS_serving_slo_ms; burn-rate gauges via collect hook)
# ---------------------------------------------------------------------------

BURN_WINDOWS = (("5m", 300.0), ("30m", 1800.0), ("1h", 3600.0))

_slo_lock = threading.Lock()
_slo_trackers: Dict[str, SloTracker] = {}
_slo_cfg_cache = [None, None]  # [raw string, parsed dict]
_slo_hook_registered = [False]


def parse_slo_config(raw: str) -> Dict[str, float]:
    """"50" -> {"*": 50.0}; "demo=50,gen=500" -> per-model objectives
    (a bare number entry is the default for unlisted models).  Malformed
    entries are dropped — config must not fail a serving process."""
    out: Dict[str, float] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if "=" in part:
                name, _, val = part.partition("=")
                out[name.strip()] = float(val)
            else:
                out["*"] = float(part)
        except ValueError:
            from ..log import warning

            warning("FLAGS_serving_slo_ms: ignoring malformed entry %r",
                    part)
    return out


def slo_objective(model: str) -> Optional[float]:
    """The model's latency objective in ms, or None when the SLO engine
    is off for it (empty/unmatched FLAGS_serving_slo_ms)."""
    from ..flags import FLAGS

    raw = FLAGS.serving_slo_ms
    if not raw:
        return None
    if _slo_cfg_cache[0] != raw:
        _slo_cfg_cache[1] = parse_slo_config(raw)
        _slo_cfg_cache[0] = raw
    cfg = _slo_cfg_cache[1]
    return cfg.get(model, cfg.get("*"))


def slo_tracker(model: str) -> Optional[SloTracker]:
    return _slo_trackers.get(model)


def slo_observe(model: str, seconds: float, ok: bool = True) -> None:
    """Count one finished/failed/shed request against the model's
    objective.  Call sites gate on monitor.enabled(); this adds one flag
    read and returns immediately when no objective is configured."""
    obj = slo_objective(model)
    if obj is None:
        return
    good = bool(ok) and seconds * 1e3 <= obj
    tr = _slo_trackers.get(model)
    if tr is None:
        from ..flags import FLAGS

        with _slo_lock:
            tr = _slo_trackers.get(model)
            if tr is None:
                tr = SloTracker(model, obj,
                                target=FLAGS.serving_slo_target)
                _slo_trackers[model] = tr
            if not _slo_hook_registered[0]:
                _slo_hook_registered[0] = True
                default_registry().add_collect_hook(_slo_collect)
    tr.observe(good)
    from .registry import counter

    counter(f"serving.{model}.slo_good_total" if good
            else f"serving.{model}.slo_bad_total").inc()


def _slo_collect() -> None:
    """Registry collect hook: refresh the burn-rate gauges lazily at
    scrape time instead of per request."""
    from .registry import gauge

    for model, tr in list(_slo_trackers.items()):
        gauge(f"serving.{model}.slo_objective_ms").set(tr.objective_ms)
        for label, window in BURN_WINDOWS:
            gauge(f"serving.{model}.slo_burn_rate_{label}").set(
                tr.burn_rate(window))


def slo_info(model: str) -> Optional[dict]:
    """The /v1/models info block for a model's SLO state."""
    obj = slo_objective(model)
    if obj is None:
        return None
    from ..flags import FLAGS

    out = {"objective_ms": obj, "target": FLAGS.serving_slo_target}
    tr = _slo_trackers.get(model)
    if tr is not None:
        out["good_total"] = tr.good_total
        out["bad_total"] = tr.bad_total
        out["burn_rate"] = {label: round(tr.burn_rate(window), 4)
                            for label, window in BURN_WINDOWS}
    return out


# ---------------------------------------------------------------------------
# test hygiene
# ---------------------------------------------------------------------------


def reset() -> None:
    """Clear every module-level accumulator (trace store, open-trace
    registry, SLO trackers + config cache) — test-fixture hygiene; the
    registry collect hook stays registered (it no-ops with no trackers)."""
    _store.clear()
    with _open_lock:
        _open_traces.clear()
    with _slo_lock:
        _slo_trackers.clear()
        _slo_cfg_cache[0] = _slo_cfg_cache[1] = None
