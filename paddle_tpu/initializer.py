"""Initializers appended as ops to the startup program
(reference: python/paddle/fluid/initializer.py)."""

from __future__ import annotations

import numpy as np

from .core import framework as fw


class Initializer:
    def __call__(self, var: fw.Variable, block: fw.Block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "value": float(self.value), "dtype": var.dtype},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
                "dtype": var.dtype,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
                "dtype": var.dtype,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
                "dtype": var.dtype,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 1:
        return shape[0], shape[0]
    fan_in = int(np.prod(shape[1:]))
    fan_out = int(shape[0] * np.prod(shape[2:])) if len(shape) > 2 else shape[1]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """Glorot (reference: initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init (reference: initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in or fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / fi))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "values": self.value.ravel().tolist(),
                "dtype": var.dtype,
            },
        )


class BilinearInitializer(Initializer):
    """For conv_transpose upsampling weights (reference: initializer.py)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer expects 4-D weights")
        c, k, h, w = shape
        f = np.ceil(w / 2.0)
        center = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        og = np.ogrid[:h, :w]
        filt = (1 - abs(og[0] / f - center)) * (1 - abs(og[1] / f - center))
        weight[range(c), range(k) if k == c else 0, :, :] = filt
        return NumpyArrayInitializer(weight)(var, block)


# canonical aliases (reference exports these names)
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False
