"""Transformer (reference: python/paddle/fluid/tests/unittests/
transformer_model.py — multi_head_attention:44, positionwise_feed_forward,
pre/post_process_layer, encoder_layer, decoder_layer, transformer:396).

TPU-first: static padded sequences + additive attention bias (instead of the
reference's LoD-free padded path), bf16-friendly; the fused flash-attention
path lives in kernels/attention.py and is switched in via use_flash."""

from __future__ import annotations

import numpy as np

from .. import layers
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def multi_head_attention(
    queries,
    keys,
    values,
    attn_bias,
    d_key,
    d_value,
    d_model,
    n_head=1,
    dropout_rate=0.0,
    use_flash=False,
    use_ring=False,
    ring_causal=False,
    ring_axis="sp",
):
    """reference transformer_model.py:44.

    use_ring (context parallelism, self-attention only): the sequence axis
    shards over mesh axis `ring_axis` and K/V circulate via ppermute —
    attn_bias is ignored on this path (pad-free batches / pure-causal via
    ring_causal), see ops/fused_ops.py ring_attention."""
    is_self = keys is None and values is None
    keys = queries if keys is None else keys
    values = keys if values is None else values

    # stable param names: the Megatron TP rules (parallel/sharding.py
    # transformer_tp_rules) address these by regex
    from ..core.framework import unique_name

    if is_self and d_key == d_value and use_flash and not use_ring:
        from ..flags import FLAGS

        if FLAGS.fused_qkv_attention:
            # ONE op: the qkv AND output projection dots run inside the
            # flash kernels (kernels/attention.py flash_qkv_attention) —
            # q/k/v never exist in HBM, so the dot-preferred<->custom-call
            # relayout copies at the projection boundaries (PERF.md r09
            # lead 1, ~1.2 GB/step) have nothing to convert.  Parameter
            # names and shapes are EXACTLY the flag-off path's (the same
            # unique_name draws, the same packed [d_model, 3hd] /
            # [hd, d_model] fc layouts), so checkpoints interop across
            # the flag.
            from ..layers.contrib import fused_qkv_attention
            from ..param_attr import ParamAttr as _PA

            return fused_qkv_attention(
                queries, n_head=n_head, d_key=d_key, d_model=d_model,
                bias=attn_bias, scale=d_key**-0.5,
                dropout_rate=dropout_rate,
                qkv_param_attr=_PA(name=unique_name("attn_qkv_w")),
                out_param_attr=_PA(name=unique_name("attn_out_w")),
            )

    if is_self and d_key == d_value:
        # ONE fused [d_model, 3*h*d] projection for self-attention: a
        # single dot (fewer custom-call-adjacent layout boundaries —
        # PERF.md r04 lead 2: the split q/k/v dots paid ~1.2 GB/step of
        # relayout copies between dot-preferred and kernel layouts)
        qkv = layers.fc(input=queries, size=3 * d_key * n_head,
                        bias_attr=False, num_flatten_dims=2,
                        param_attr=ParamAttr(name=unique_name("attn_qkv_w")))
        q, k, v = layers.split(qkv, 3, dim=-1)
    else:
        q = layers.fc(input=queries, size=d_key * n_head, bias_attr=False,
                      num_flatten_dims=2,
                      param_attr=ParamAttr(name=unique_name("attn_q_w")))
        k = layers.fc(input=keys, size=d_key * n_head, bias_attr=False,
                      num_flatten_dims=2,
                      param_attr=ParamAttr(name=unique_name("attn_k_w")))
        v = layers.fc(input=values, size=d_value * n_head, bias_attr=False,
                      num_flatten_dims=2,
                      param_attr=ParamAttr(name=unique_name("attn_v_w")))

    def split_heads(x, d):
        b, t, _ = x.shape
        r = layers.reshape(x, [b, t, n_head, d])
        return layers.transpose(r, [0, 2, 1, 3])

    def to_bthd(x, d):
        b, t, _ = x.shape
        return layers.reshape(x, [b, t, n_head, d])

    def merge_and_project(ctx):
        """[b, t, h, d] context -> output projection (the shared tail of
        the transpose-free bthd paths: the reshape is a bitcast)."""
        b, t, h, d = ctx.shape
        ctx = layers.reshape(ctx, [b, t, h * d])
        return layers.fc(input=ctx, size=d_model, bias_attr=False,
                         num_flatten_dims=2,
                         param_attr=ParamAttr(name=unique_name("attn_out_w")))

    if use_flash and not use_ring:
        # transpose-free path: [b,t,h*d] -> [b,t,h,d] is a bitcast, the
        # kernel indexes heads via its grid, and the output reshapes
        # straight back — no split/merge-head transposes exist, so XLA
        # inserts no relayout copies at the custom-call boundary
        # (round-3 profile: ~5.5 GB/step of them on the [b,h,t,d] path)
        from ..layers.contrib import fused_attention

        # weights_dropout (in-kernel, reference semantics) is on at every
        # sequence length: the kernels draw mask bits from the TPU
        # hardware PRNG (kernels/attention.py _keep_tile_prng), which
        # removed the O(T²·H) hash-regeneration cost that made seq-256 a
        # −2.5 MFU-pt loss in r05 and forced a per-length selection hack
        ctx = fused_attention(
            to_bthd(q, d_key), to_bthd(k, d_key), to_bthd(v, d_value),
            attn_bias, scale=d_key**-0.5, dropout_rate=dropout_rate,
            fmt="bthd",
        )
        return merge_and_project(ctx)

    if use_ring:
        # context-parallel path on the same transpose-free convention:
        # the ring chunks reuse the single-device bthd whole-head block
        # specs (kernels/ring_attention.py) — CP re-introduces NO
        # split/merge-head transposes
        from ..layers.contrib import ring_attention

        ctx = ring_attention(
            to_bthd(q, d_key), to_bthd(k, d_key), to_bthd(v, d_value),
            scale=d_key**-0.5, causal=ring_causal, axis_name=ring_axis,
            fmt="bthd")
        return merge_and_project(ctx)

    q = split_heads(q, d_key)
    k = split_heads(k, d_key)
    v = split_heads(v, d_value)

    product = layers.matmul(q, k, transpose_y=True, alpha=d_key**-0.5)
    if attn_bias is not None:
        product = layers.elementwise_add(product, attn_bias)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(
            weights, dropout_prob=dropout_rate,
            dropout_implementation="upscale_in_train",
        )
    ctx = layers.matmul(weights, v)

    b, h, t, d = ctx.shape
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [b, t, h * d])
    return layers.fc(input=ctx, size=d_model, bias_attr=False,
                     num_flatten_dims=2,
                     param_attr=ParamAttr(name=unique_name("attn_out_w")))


def positionwise_feed_forward(x, d_inner_hid, d_hid):
    from ..core.framework import unique_name

    hidden = layers.fc(input=x, size=d_inner_hid, act="relu",
                       num_flatten_dims=2,
                       param_attr=ParamAttr(name=unique_name("ffn_in_w")),
                       bias_attr=ParamAttr(name=unique_name("ffn_in_b")))
    return layers.fc(input=hidden, size=d_hid, num_flatten_dims=2,
                     param_attr=ParamAttr(name=unique_name("ffn_out_w")),
                     bias_attr=ParamAttr(name=unique_name("ffn_out_b")))


def pre_post_process_layer(prev_out, out, process_cmd, dropout_rate=0.0):
    """reference transformer_model.py pre_post_process_layer: a=add, n=norm,
    d=dropout.

    A leading "da" (dropout then residual-add — the post-process pattern
    of every encoder/decoder sub-layer) lowers as ONE fused dropout-add
    op (layers.dropout_add -> kernels/dropout_epilogue.py) under
    FLAGS.fused_dropout_add: the keep-mask is generated in-kernel and
    regenerated in the backward, so it never exists in HBM.  With the
    flag off, or without a residual, the reference's separate
    dropout + elementwise_add ops are emitted unchanged."""
    from ..flags import FLAGS

    if (dropout_rate and prev_out is not None
            and process_cmd.startswith("da") and FLAGS.fused_dropout_add):
        out = layers.dropout_add(out, prev_out, dropout_rate)
        process_cmd = process_cmd[2:]
    for cmd in process_cmd:
        if cmd == "a":
            out = layers.elementwise_add(out, prev_out) if prev_out is not None else out
        elif cmd == "n":
            out = layers.layer_norm(
                out, begin_norm_axis=len(out.shape) - 1,
                param_attr=ParamAttr(initializer=None),
            )
        elif cmd == "d":
            if dropout_rate:
                out = layers.dropout(
                    out, dropout_prob=dropout_rate,
                    dropout_implementation="upscale_in_train",
                )
    return out


def prepare_encoder(
    src_word,
    src_pos,
    src_vocab_size,
    src_emb_dim,
    src_max_len,
    dropout_rate=0.0,
    word_emb_param_name=None,
    pos_enc_param_name=None,
):
    """Word + sinusoid position embedding (reference prepare_encoder)."""
    src_word_emb = layers.embedding(
        src_word,
        size=[src_vocab_size, src_emb_dim],
        param_attr=ParamAttr(
            name=word_emb_param_name,
            initializer=NormalInitializer(0.0, src_emb_dim**-0.5),
        ),
    )
    src_pos_enc = layers.embedding(
        src_pos,
        size=[src_max_len, src_emb_dim],
        param_attr=ParamAttr(
            name=pos_enc_param_name,
            initializer=NormalInitializer(0.0, src_emb_dim**-0.5),
            trainable=False,
        ),
    )
    src_pos_enc.stop_gradient = True
    enc_input = layers.elementwise_add(src_word_emb, src_pos_enc)
    if dropout_rate:
        enc_input = layers.dropout(
            enc_input, dropout_prob=dropout_rate,
            dropout_implementation="upscale_in_train",
        )
    return enc_input


def encoder_layer(enc_input, attn_bias, n_head, d_key, d_value, d_model,
                  d_inner_hid, dropout_rate=0.0, use_flash=False,
                  use_ring=False):
    attn_output = multi_head_attention(
        enc_input, None, None, attn_bias, d_key, d_value, d_model, n_head,
        dropout_rate, use_flash=use_flash, use_ring=use_ring,
    )
    attn_output = pre_post_process_layer(enc_input, attn_output, "dan",
                                         dropout_rate)
    ffd_output = positionwise_feed_forward(attn_output, d_inner_hid, d_model)
    return pre_post_process_layer(attn_output, ffd_output, "dan", dropout_rate)


def encoder(enc_input, attn_bias, n_layer, n_head, d_key, d_value, d_model,
            d_inner_hid, dropout_rate=0.0, use_flash=False, use_ring=False):
    for i in range(n_layer):
        enc_output = encoder_layer(
            enc_input, attn_bias, n_head, d_key, d_value, d_model,
            d_inner_hid, dropout_rate, use_flash=use_flash,
            use_ring=use_ring,
        )
        enc_input = enc_output
    return enc_output


def decoder_layer(dec_input, enc_output, slf_attn_bias, dec_enc_attn_bias,
                  n_head, d_key, d_value, d_model, d_inner_hid,
                  dropout_rate=0.0, use_flash=False, use_ring=False):
    slf_attn_output = multi_head_attention(
        dec_input, None, None, slf_attn_bias, d_key, d_value, d_model, n_head,
        dropout_rate, use_flash=use_flash, use_ring=use_ring,
        ring_causal=True,
    )
    slf_attn_output = pre_post_process_layer(dec_input, slf_attn_output, "dan",
                                             dropout_rate)
    enc_attn_output = multi_head_attention(
        slf_attn_output, enc_output, enc_output, dec_enc_attn_bias, d_key,
        d_value, d_model, n_head, dropout_rate, use_flash=use_flash,
    )
    enc_attn_output = pre_post_process_layer(
        slf_attn_output, enc_attn_output, "dan", dropout_rate
    )
    ffd_output = positionwise_feed_forward(enc_attn_output, d_inner_hid, d_model)
    return pre_post_process_layer(enc_attn_output, ffd_output, "dan", dropout_rate)


def decoder(dec_input, enc_output, dec_slf_attn_bias, dec_enc_attn_bias,
            n_layer, n_head, d_key, d_value, d_model, d_inner_hid,
            dropout_rate=0.0, use_flash=False, use_ring=False):
    for i in range(n_layer):
        dec_output = decoder_layer(
            dec_input, enc_output, dec_slf_attn_bias, dec_enc_attn_bias,
            n_head, d_key, d_value, d_model, d_inner_hid, dropout_rate,
            use_flash=use_flash, use_ring=use_ring,
        )
        dec_input = dec_output
    return dec_output


def transformer(
    src_vocab_size=10000,
    trg_vocab_size=10000,
    max_length=256,
    n_layer=6,
    n_head=8,
    d_key=64,
    d_value=64,
    d_model=512,
    d_inner_hid=2048,
    dropout_rate=0.1,
    batch_size=None,
    src_seq_len=None,
    trg_seq_len=None,
    use_flash=False,
    use_ring=False,
    device_biases=True,
):
    """Full encoder-decoder Transformer-base (reference
    transformer_model.py:396).  Declares padded-sequence data vars; returns
    (avg_cost, predict, feed_names).

    device_biases (TPU-first, default): attention biases are computed ON
    DEVICE inside the compiled step — padding masks from the word ids
    (pad id 0) and the causal mask as a program constant.  The reference
    feeds dense [b, n_head, t, t] bias tensors from the host
    (transformer_model.py prepare_batch_input), which costs O(b·h·t²)
    host->HBM bandwidth per step — at (b=32, h=8, t=256) that is ~200 MB
    per step, orders of magnitude more than the token ids themselves.
    Set device_biases=False for reference-parity feeding."""
    src_seq_len = src_seq_len or max_length
    trg_seq_len = trg_seq_len or max_length

    src_word = layers.data(name="src_word", shape=[src_seq_len, 1], dtype="int64")
    src_pos = layers.data(name="src_pos", shape=[src_seq_len, 1], dtype="int64")
    trg_word = layers.data(name="trg_word", shape=[trg_seq_len, 1], dtype="int64")
    trg_pos = layers.data(name="trg_pos", shape=[trg_seq_len, 1], dtype="int64")
    if device_biases:
        neg_inf = -1e9

        def pad_bias(word, t):
            # [b, t, 1] ids -> [b, 1, 1, t] additive bias (-inf at pad id 0)
            zero = layers.fill_constant([1], "int64", 0)
            is_pad = layers.cast(layers.equal(word, zero), "float32")
            bias = layers.scale(is_pad, scale=neg_inf)
            bias = layers.reshape(bias, [-1, 1, 1, t])
            bias.stop_gradient = True
            return bias

        src_pad = pad_bias(src_word, src_seq_len)
        # causal mask from the (already fed) position ids: bias[q, k] = -inf
        # where k_pos > q_pos — computed on device, no O(t^2) IR constant
        qpos = layers.reshape(trg_pos, [-1, trg_seq_len, 1])
        kpos = layers.reshape(trg_pos, [-1, 1, trg_seq_len])
        future = layers.cast(layers.less_than(qpos, kpos), "float32")
        causal = layers.reshape(
            layers.scale(future, scale=neg_inf),
            [-1, 1, trg_seq_len, trg_seq_len],
        )
        causal.stop_gradient = True
        src_slf_attn_bias = src_pad
        trg_slf_attn_bias = layers.elementwise_add(
            causal, pad_bias(trg_word, trg_seq_len)
        )
        trg_slf_attn_bias.stop_gradient = True
        trg_src_attn_bias = src_pad
    else:
        src_slf_attn_bias = layers.data(
            name="src_slf_attn_bias", shape=[n_head, src_seq_len, src_seq_len],
            dtype="float32",
        )
        trg_slf_attn_bias = layers.data(
            name="trg_slf_attn_bias", shape=[n_head, trg_seq_len, trg_seq_len],
            dtype="float32",
        )
        trg_src_attn_bias = layers.data(
            name="trg_src_attn_bias", shape=[n_head, trg_seq_len, src_seq_len],
            dtype="float32",
        )
    gold = layers.data(name="lbl_word", shape=[trg_seq_len, 1], dtype="int64")
    weights = layers.data(name="lbl_weight", shape=[trg_seq_len, 1], dtype="float32")

    enc_input = prepare_encoder(
        src_word, src_pos, src_vocab_size, d_model, max_length, dropout_rate,
        word_emb_param_name="src_word_emb_table",
        pos_enc_param_name="src_pos_enc_table",
    )
    enc_output = encoder(
        enc_input, src_slf_attn_bias, n_layer, n_head, d_key, d_value,
        d_model, d_inner_hid, dropout_rate, use_flash=use_flash,
        use_ring=use_ring,
    )

    dec_input = prepare_encoder(
        trg_word, trg_pos, trg_vocab_size, d_model, max_length, dropout_rate,
        word_emb_param_name="trg_word_emb_table",
        pos_enc_param_name="trg_pos_enc_table",
    )
    dec_output = decoder(
        dec_input, enc_output, trg_slf_attn_bias, trg_src_attn_bias,
        n_layer, n_head, d_key, d_value, d_model, d_inner_hid, dropout_rate,
        use_flash=use_flash, use_ring=use_ring,
    )

    predict = layers.fc(input=dec_output, size=trg_vocab_size,
                        num_flatten_dims=2,
                        param_attr=ParamAttr(name="predict_w"),
                        bias_attr=ParamAttr(name="predict_b"))
    b, t, v = predict.shape
    predict_2d = layers.reshape(predict, [-1, v])
    gold_2d = layers.reshape(gold, [-1, 1])
    cost = layers.softmax_with_cross_entropy(logits=predict_2d, label=gold_2d)
    w2d = layers.reshape(weights, [-1, 1])
    weighted_cost = layers.elementwise_mul(cost, w2d)
    sum_cost = layers.reduce_sum(weighted_cost)
    token_count = layers.reduce_sum(w2d)
    avg_cost = layers.elementwise_div(sum_cost, token_count)

    feed_names = ["src_word", "src_pos", "trg_word", "trg_pos",
                  "lbl_word", "lbl_weight"]
    if not device_biases:
        feed_names[4:4] = [
            "src_slf_attn_bias", "trg_slf_attn_bias", "trg_src_attn_bias"
        ]
    return avg_cost, predict, feed_names


def make_batch(batch_size, src_len, trg_len, n_head, src_vocab, trg_vocab,
               rng=None, device_biases=True):
    """Synthetic padded batch.  With device_biases (default) only token
    streams are produced — the model builds attention biases on device; pass
    device_biases=False for the reference-parity dense-bias feed."""
    rng = rng or np.random.RandomState(0)
    neg_inf = -1e9

    def pos(n, t):
        return np.tile(np.arange(t, dtype=np.int64)[None, :, None], (n, 1, 1))

    src_word = rng.randint(1, src_vocab, (batch_size, src_len, 1)).astype("int64")
    trg_word = rng.randint(1, trg_vocab, (batch_size, trg_len, 1)).astype("int64")
    lbl_word = rng.randint(1, trg_vocab, (batch_size, trg_len, 1)).astype("int64")
    lbl_weight = np.ones((batch_size, trg_len, 1), "float32")
    batch = {
        "src_word": src_word,
        "src_pos": pos(batch_size, src_len),
        "trg_word": trg_word,
        "trg_pos": pos(batch_size, trg_len),
        "lbl_word": lbl_word,
        "lbl_weight": lbl_weight,
    }
    if not device_biases:
        causal = np.triu(np.full((trg_len, trg_len), neg_inf, "float32"), 1)
        batch["src_slf_attn_bias"] = np.zeros(
            (batch_size, n_head, src_len, src_len), "float32")
        batch["trg_slf_attn_bias"] = np.tile(
            causal[None, None], (batch_size, n_head, 1, 1))
        batch["trg_src_attn_bias"] = np.zeros(
            (batch_size, n_head, trg_len, src_len), "float32")
    return batch


def _log_softmax(x, axis_dim):
    """logits [.., V] -> log-probs, numerically stable, built from layer ops."""
    m = layers.reduce_max(x, dim=axis_dim, keep_dim=True)
    shifted = layers.elementwise_sub(x, m)
    lse = layers.log(
        layers.reduce_sum(layers.exp(shifted), dim=axis_dim, keep_dim=True))
    return layers.elementwise_sub(shifted, lse)


# ---------------------------------------------------------------------------
# KV-cached decoding (paddle_tpu/generation): the single-token decoder step
# and the prefill/decode program pair.  Parameter names are drawn through
# the SAME unique_name sequences as transformer()/build_decoder, so a scope
# trained with the train net decodes through the cache directly.
# ---------------------------------------------------------------------------


def _cache_rows(n):
    """Ring-buffer row count rounded up to the flash-decode block quantum:
    the plan gate (kernels/decode_attention.py _decode_plan) wants
    max_t % block == 0 with 128 the smallest compiled block, so cache
    buffers are allocated in 128-row steps (the tail rows are dead weight
    the length mask never reads)."""
    return ((int(n) + 127) // 128) * 128


def _src_token_lengths(src_word, src_seq_len):
    """[b, Ts, 1] int64 ids -> [b] int32 length = 1 + LAST non-pad
    position (pad id 0).  Length-masking the cross cache to this value
    is equivalent to the reference's -1e9 pad bias for TRAILING padding
    (the framework's sequence contract); computing the trailing run —
    rather than counting zeros — means an out-of-contract mid-sequence 0
    can never truncate real tokens off the tail (it is attended like any
    token, where the bias route would mask that one position)."""
    zero = layers.fill_constant([1], "int64", 0)
    nonpad = layers.cast(layers.not_equal(src_word, zero), "float32")
    ones_t = layers.fill_constant([src_seq_len, 1], "float32", 1.0)
    pos1 = layers.reshape(layers.cumsum(ones_t, axis=0),
                          [1, src_seq_len, 1])  # 1..Ts
    last = layers.reduce_max(layers.elementwise_mul(nonpad, pos1),
                             dim=[1, 2])  # [b] = 1 + last non-pad pos
    return layers.cast(last, "int32")


def _flat_beam_parents(parent_idx, b, k):
    """[b, k] within-group beam parents -> [b, k] int64 FLAT lane indices
    (group offset b_idx*k + parent) — the kv_cache_reorder gather
    contract shared by the build_decoder While route and the per-token
    beam decode program."""
    ones_b = layers.fill_constant([b, 1], "float32", 1.0)
    offs = layers.scale(
        layers.elementwise_sub(layers.cumsum(ones_b, axis=0), ones_b),
        scale=float(k))
    return layers.cast(
        layers.elementwise_add(layers.cast(parent_idx, "float32"),
                               layers.expand(offs, [1, k])),
        "int64")


def _prefill_cross_cache(enc_output, cross_cache, n_layer, n_head, d_key,
                         d_value, active=None):
    """Project the (possibly beam-tiled) encoder output into per-layer
    cross-attention K/V and write them at row 0 of every sequence's cache
    slot.  Draws attn_k_w/attn_v_w in layer order — the same per-key
    unique_name sequence the in-loop recompute route draws."""
    from ..core.framework import unique_name

    # ts is the SOURCE length (what the encoder produced); the cache may
    # hold more rows (128-row allocation quantum) — the tail stays zero
    # and the cross length mask never reads it
    b, ts = cross_cache.batch, int(enc_output.shape[1])
    zero_pos = layers.fill_constant([b], "int32", 0)
    for i in range(n_layer):
        k = layers.fc(input=enc_output, size=d_key * n_head,
                      bias_attr=False, num_flatten_dims=2,
                      param_attr=ParamAttr(name=unique_name("attn_k_w")))
        v = layers.fc(input=enc_output, size=d_value * n_head,
                      bias_attr=False, num_flatten_dims=2,
                      param_attr=ParamAttr(name=unique_name("attn_v_w")))
        k4 = layers.reshape(k, [b, ts, n_head, d_key])
        v4 = layers.reshape(v, [b, ts, n_head, d_value])
        cross_cache.write(k4, v4, zero_pos, layer=i, active=active)


def cached_decoder_step(dec_input, self_cache, cross_cache, write_pos,
                        self_lens, cross_lens, n_layer, n_head, d_key,
                        d_value, d_model, d_inner_hid, active=None,
                        dropout_rate=0.0):
    """ONE decoder step over a single embedded token [b, 1, d_model]:
    per layer, project q/k/v, append k/v to the self cache at write_pos,
    single-query attention over the first self_lens rows, then cached
    cross-attention over cross_lens rows of the prefilled cross cache,
    then the feed-forward — the op-for-op cached counterpart of
    decoder_layer (same post-process "dan" chain, same parameter-name
    draws), minus the O(T²) full-prefix recompute.

    Under FLAGS_fused_decode_step (default on) each layer lowers to ONE
    fused_decode_step op instead of the ~10-op composition below —
    kernels/decode_step.py runs the whole layer per Pallas launch (or
    its numerically-identical XLA fallback off-contract/off-TPU).
    Parameter names, shapes and draw order are EXACTLY the flag-off
    path's, so checkpoints interop across the flag; flag-off graphs are
    op-for-op identical to the pre-fusion ones (asserted in
    tests/test_decode_step.py)."""
    from ..core.framework import unique_name
    from ..flags import FLAGS

    if FLAGS.fused_decode_step and dropout_rate == 0.0 and d_key == d_value:
        return _fused_cached_decoder_step(
            dec_input, self_cache, cross_cache, write_pos, self_lens,
            cross_lens, n_layer, n_head, d_key, d_value, d_model,
            d_inner_hid, active=active)

    x = dec_input
    b = x.shape[0]
    for i in range(n_layer):
        # self-attention against the growing cache
        qkv = layers.fc(input=x, size=3 * d_key * n_head, bias_attr=False,
                        num_flatten_dims=2,
                        param_attr=ParamAttr(name=unique_name("attn_qkv_w")))
        q, k, v = layers.split(qkv, 3, dim=-1)
        q4 = layers.reshape(q, [b, 1, n_head, d_key])
        k4 = layers.reshape(k, [b, 1, n_head, d_key])
        v4 = layers.reshape(v, [b, 1, n_head, d_value])
        self_cache.write(k4, v4, write_pos, layer=i, active=active)
        ctx = self_cache.attend(q4, self_lens, layer=i, scale=d_key**-0.5)
        attn_out = layers.fc(
            input=layers.reshape(ctx, [b, 1, n_head * d_value]),
            size=d_model, bias_attr=False, num_flatten_dims=2,
            param_attr=ParamAttr(name=unique_name("attn_out_w")))
        x = pre_post_process_layer(x, attn_out, "dan", dropout_rate)
        # cross-attention against the prefilled encoder K/V
        cq = layers.fc(input=x, size=d_key * n_head, bias_attr=False,
                       num_flatten_dims=2,
                       param_attr=ParamAttr(name=unique_name("attn_q_w")))
        cq4 = layers.reshape(cq, [b, 1, n_head, d_key])
        cctx = cross_cache.attend(cq4, cross_lens, layer=i,
                                  scale=d_key**-0.5)
        cross_out = layers.fc(
            input=layers.reshape(cctx, [b, 1, n_head * d_value]),
            size=d_model, bias_attr=False, num_flatten_dims=2,
            param_attr=ParamAttr(name=unique_name("attn_out_w")))
        x = pre_post_process_layer(x, cross_out, "dan", dropout_rate)
        ffd = positionwise_feed_forward(x, d_inner_hid, d_model)
        x = pre_post_process_layer(x, ffd, "dan", dropout_rate)
    return x


def _fused_cached_decoder_step(dec_input, self_cache, cross_cache,
                               write_pos, self_lens, cross_lens, n_layer,
                               n_head, d_key, d_value, d_model,
                               d_inner_hid, active=None):
    """The FLAGS_fused_decode_step lowering of cached_decoder_step: one
    fused_decode_step op per layer (ops/generation_ops.py ->
    kernels/decode_step.py).  Parameters are created through the SAME
    LayerHelper recipes and unique_name draws as the composition —
    attn_qkv_w, attn_out_w, layer_norm, attn_q_w, attn_out_w,
    layer_norm, ffn_in_w/b, ffn_out_w/b, layer_norm per layer — so a
    scope trained with `transformer(...)` runs either path and the
    flag-off graph's names never shift."""
    from ..core.framework import unique_name
    from ..initializer import ConstantInitializer
    from ..layer_helper import LayerHelper

    x = dec_input
    dtype = x.dtype

    def fc_param(key, shape):
        helper = LayerHelper(
            "fc", param_attr=ParamAttr(name=unique_name(key)))
        return helper.create_parameter(helper.param_attr(), shape=shape,
                                       dtype=dtype)

    def fc_bias(key, shape):
        helper = LayerHelper(
            "fc", bias_attr=ParamAttr(name=unique_name(key)))
        return helper.create_parameter(helper.bias_attr(), shape=shape,
                                       dtype=dtype, is_bias=True)

    def ln_params():
        helper = LayerHelper("layer_norm",
                             param_attr=ParamAttr(initializer=None))
        scale = helper.create_parameter(
            helper.param_attr(), shape=[d_model], dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        bias = helper.create_parameter(
            helper.bias_attr(), shape=[d_model], dtype=dtype,
            is_bias=True)
        return scale, bias

    cache_k, cache_v, _ = self_cache.vars_in()
    cross_k, cross_v, _ = cross_cache.vars_in()
    # paged caches (FLAGS_paged_kv_cache) route to the paged op form,
    # which adds the graph-read-only block tables to the inputs — the
    # weight draws and attrs are identical, so flag-on fused/unfused
    # programs stay numerically interchangeable
    paged = hasattr(self_cache, "table_in")
    step_op = "fused_decode_step_paged" if paged else "fused_decode_step"
    for i in range(n_layer):
        w_qkv = fc_param("attn_qkv_w", [d_model, 3 * d_key * n_head])
        w_out = fc_param("attn_out_w", [n_head * d_value, d_model])
        ln1_s, ln1_b = ln_params()
        w_cq = fc_param("attn_q_w", [d_model, d_key * n_head])
        w_cout = fc_param("attn_out_w", [n_head * d_value, d_model])
        ln2_s, ln2_b = ln_params()
        ffn_iw = fc_param("ffn_in_w", [d_model, d_inner_hid])
        ffn_ib = fc_bias("ffn_in_b", [d_inner_hid])
        ffn_ow = fc_param("ffn_out_w", [d_inner_hid, d_model])
        ffn_ob = fc_bias("ffn_out_b", [d_model])
        ln3_s, ln3_b = ln_params()

        helper = LayerHelper(step_op)
        out = helper.create_variable_for_type_inference(dtype)
        inputs = {
            "X": [x], "WQkv": [w_qkv], "WOut": [w_out],
            "Ln1Scale": [ln1_s], "Ln1Bias": [ln1_b], "WCq": [w_cq],
            "WCOut": [w_cout], "Ln2Scale": [ln2_s], "Ln2Bias": [ln2_b],
            "FfnInW": [ffn_iw], "FfnInB": [ffn_ib], "FfnOutW": [ffn_ow],
            "FfnOutB": [ffn_ob], "Ln3Scale": [ln3_s], "Ln3Bias": [ln3_b],
            "CacheK": [cache_k], "CacheV": [cache_v],
            "CrossK": [cross_k], "CrossV": [cross_v],
            "Pos": [write_pos], "Lengths": [self_lens],
            "CrossLengths": [cross_lens],
        }
        if paged:
            inputs["SelfTable"] = [self_cache.table_in()]
            inputs["CrossTable"] = [cross_cache.table_in()]
        if active is not None:
            inputs["Active"] = [active]
        # cache outputs carry the SAME var objects — the persistable
        # read-then-write the executor donates (kv_cache_update contract
        # verbatim)
        helper.append_op(
            step_op, inputs=inputs,
            outputs={"Out": [out], "CacheKOut": [cache_k],
                     "CacheVOut": [cache_v]},
            attrs={"layer": i, "n_head": n_head, "scale": d_key ** -0.5,
                   "epsilon": 1e-5})
        out.shape = list(x.shape)
        x = out
    return x


def build_decoder(
    src_vocab_size=10000,
    trg_vocab_size=10000,
    max_length=256,
    n_layer=6,
    n_head=8,
    d_key=64,
    d_value=64,
    d_model=512,
    d_inner_hid=2048,
    batch_size=4,
    src_seq_len=None,
    max_out_len=16,
    beam_size=4,
    bos_id=0,
    eos_id=1,
    use_flash=False,
):
    """Beam-search inference net (reference:
    tests/book/test_machine_translation.py decode + layers.beam_search
    nn.py:3833).  Shares parameter names with `transformer(...)` so a scope
    trained with the train net decodes directly.

    TPU-first shape: beams are a static [batch, beam] lane; the While loop
    compiles to one XLA while_loop.  The decode step inside the loop is
    chosen by FLAGS.kv_cache:

      * on (default): per-layer K/V ring buffers ride the loop carry
        (cached_decoder_step + ops/generation_ops.py) — each step embeds
        ONE token, appends its K/V at position t, reorders the cache by
        the beam parents, and attends the single query row over the
        t+1-row prefix (O(T) per token; kernels/decode_attention.py).
      * off: the legacy full-prefix recompute — every step re-runs the
        causal decoder over the static [T+1]-padded prefix (O(T²)
        recompute per token).  Kept as the parity oracle: both routes are
        output-identical (asserted in tests/test_generation.py).

    The While-free per-token generation drivers (one Executor.run per
    token, serving-grade) live in paddle_tpu/generation — this builder is
    the single-program book-test/batch path.

    Returns (sentence_ids [b, beam, T], sentence_scores [b, beam],
    feed_names).
    """
    from ..flags import FLAGS
    src_seq_len = src_seq_len or max_length
    if max_length < max_out_len + 1 or max_length < src_seq_len:
        # same position-table NaN footgun as build_generation_programs
        raise ValueError(
            f"max_length={max_length} position table is smaller than the "
            f"decode buffer (max_out_len+1={max_out_len + 1}) or the "
            f"source length ({src_seq_len})")
    t_buf = max_out_len + 1  # position 0 is BOS
    b, k = batch_size, beam_size
    bk = b * k

    src_word = layers.data(name="src_word", shape=[src_seq_len, 1],
                           dtype="int64")
    src_pos = layers.data(name="src_pos", shape=[src_seq_len, 1],
                          dtype="int64")

    # ---- encoder (runs once, before the loop) ---------------------------
    neg_inf = -1e9
    zero = layers.fill_constant([1], "int64", 0)
    is_pad = layers.cast(layers.equal(src_word, zero), "float32")
    src_bias = layers.reshape(layers.scale(is_pad, scale=neg_inf),
                              [-1, 1, 1, src_seq_len])
    src_bias.stop_gradient = True
    enc_input = prepare_encoder(
        src_word, src_pos, src_vocab_size, d_model, max_length,
        word_emb_param_name="src_word_emb_table",
        pos_enc_param_name="src_pos_enc_table",
    )
    enc_output = encoder(
        enc_input, src_bias, n_layer, n_head, d_key, d_value, d_model,
        d_inner_hid, use_flash=use_flash,
    )
    # tile per beam: [b, Ts, d] -> [b*k, Ts, d] (beam-major within batch)
    enc_output = layers.reshape(
        layers.expand(
            layers.reshape(enc_output, [b, 1, src_seq_len, d_model]),
            [1, k, 1, 1],
        ),
        [bk, src_seq_len, d_model],
    )
    # ---- loop state -----------------------------------------------------
    t = layers.fill_constant([1], "int64", 0)
    limit = layers.fill_constant([1], "int64", max_out_len)
    cond = layers.less_than(t, limit)

    pre_ids = layers.fill_constant([b, k], "int64", bos_id)
    beam0 = layers.one_hot(layers.fill_constant([1], "int64", 0), k)  # [k]
    pre_scores = layers.expand(
        layers.reshape(layers.scale(beam0, scale=1e9, bias=neg_inf),
                       [1, k]),
        [b, 1],
    )  # beam 0 -> 0, others -> -1e9

    ids_arr = layers.create_array("int64", element_shape=[b, k],
                                  capacity=max_out_len)
    parents_arr = layers.create_array("int64", element_shape=[b, k],
                                      capacity=max_out_len)

    if FLAGS.kv_cache:
        # ---- KV-cached route (default): the caches ride the loop carry
        from ..core import framework as fw
        from ..generation.kv_cache import KVCache

        def _zeroed_cache(prefix_name, max_t):
            cache = KVCache(prefix_name, n_layer, bk, max_t, n_head, d_key)
            kv_vars = cache.vars_in(persistable=False)
            for var in kv_vars[:2]:
                zeros = layers.fill_constant(list(cache.shape), "float32",
                                             0.0)
                layers.assign(zeros, output=var)
            return cache

        uid = fw.unique_name("dec_cache")
        self_cache = _zeroed_cache(f"{uid}_self", _cache_rows(t_buf))
        cross_cache = _zeroed_cache(f"{uid}_cross",
                                    _cache_rows(src_seq_len))
        _prefill_cross_cache(enc_output, cross_cache, n_layer, n_head,
                             d_key, d_value)
        # cross length = true (untiled-then-tiled) source token count
        src_lens = _src_token_lengths(src_word, src_seq_len)  # [b] int32
        cross_lens = layers.reshape(
            layers.expand(layers.reshape(src_lens, [b, 1]), [1, k]), [bk])
        # flat beam-parent carry, identity at step 0 (slot i -> slot i)
        ones_bk = layers.fill_constant([bk, 1], "float32", 1.0)
        identity = layers.cast(
            layers.reshape(
                layers.elementwise_sub(layers.cumsum(ones_bk, axis=0),
                                       ones_bk),
                [bk]),
            "int64")
        pre_parents = layers.fill_constant([bk], "int64", 0)
        layers.assign(identity, output=pre_parents)

        w = layers.While(cond)
        with w.block():
            # continue from the parent beam's prefix: gather the cache
            # slots the selected tokens actually extended
            self_cache.reorder(pre_parents)
            write_pos = layers.cast(
                layers.reshape(layers.expand(layers.reshape(t, [1, 1]),
                                             [bk, 1]), [bk]),
                "int32")
            att_len = layers.elementwise_add(
                write_pos, layers.fill_constant([bk], "int32", 1))
            tpos_ids = layers.expand(layers.reshape(t, [1, 1, 1]),
                                     [bk, 1, 1])
            dec_input = prepare_encoder(
                layers.reshape(pre_ids, [bk, 1, 1]), tpos_ids,
                trg_vocab_size, d_model, max_length,
                word_emb_param_name="trg_word_emb_table",
                pos_enc_param_name="trg_pos_enc_table",
            )
            dec_output = cached_decoder_step(
                dec_input, self_cache, cross_cache, write_pos, att_len,
                cross_lens, n_layer, n_head, d_key, d_value, d_model,
                d_inner_hid)
            logits = layers.fc(input=dec_output, size=trg_vocab_size,
                               num_flatten_dims=2,
                               param_attr=ParamAttr(name="predict_w"),
                               bias_attr=ParamAttr(name="predict_b"))
            step_logits = layers.reshape(logits, [b, k, trg_vocab_size])
            log_probs = _log_softmax(step_logits, axis_dim=2)

            sel_ids, sel_scores, parent_idx = layers.beam_search(
                pre_ids, pre_scores, None, log_probs, beam_size=k,
                end_id=eos_id)

            # flat parents for the NEXT step's cache gather
            layers.assign(
                layers.reshape(_flat_beam_parents(parent_idx, b, k),
                               [bk]),
                output=pre_parents)

            layers.array_write(sel_ids, t, array=ids_arr)
            layers.array_write(parent_idx, t, array=parents_arr)
            layers.assign(sel_ids, output=pre_ids)
            layers.assign(sel_scores, output=pre_scores)
            layers.increment(t, value=1.0, in_place=True)
            layers.less_than(t, limit, cond=cond)

        sent_ids, sent_scores = layers.beam_search_decode(
            ids_arr, pre_scores, beam_size=k, end_id=eos_id,
            parents=parents_arr)
        return sent_ids, sent_scores, ["src_word", "src_pos"]

    # ---- flag-off route: full-prefix recompute (the parity oracle) ------
    # causal self-attention bias over the prefix buffer: [1, 1, T, T]
    ones_t = layers.fill_constant([t_buf, 1], "float32", 1.0)
    arange_t = layers.elementwise_sub(
        layers.cumsum(ones_t, axis=0), ones_t)  # [T,1] = 0..T-1
    qpos = layers.reshape(arange_t, [1, t_buf, 1])
    kpos = layers.reshape(arange_t, [1, 1, t_buf])
    future = layers.cast(layers.less_than(qpos, kpos), "float32")
    causal_bias = layers.reshape(layers.scale(future, scale=neg_inf),
                                 [1, 1, t_buf, t_buf])
    causal_bias.stop_gradient = True

    trg_pos_ids = layers.cast(
        layers.expand(layers.reshape(arange_t, [1, t_buf, 1]), [bk, 1, 1]),
        "int64")

    # beam-tiled source pad bias (the cached route masks the cross cache
    # by true source lengths instead)
    src_bias_bk = layers.reshape(
        layers.expand(layers.reshape(src_bias, [b, 1, 1, 1, src_seq_len]),
                      [1, k, 1, 1, 1]),
        [bk, 1, 1, src_seq_len],
    )

    prefix = layers.fill_constant([b, k, t_buf], "int64", bos_id)

    w = layers.While(cond)
    with w.block():
        dec_input = prepare_encoder(
            layers.reshape(prefix, [bk, t_buf, 1]), trg_pos_ids,
            trg_vocab_size, d_model, max_length,
            word_emb_param_name="trg_word_emb_table",
            pos_enc_param_name="trg_pos_enc_table",
        )
        dec_output = decoder(
            dec_input, enc_output, causal_bias, src_bias_bk,
            n_layer, n_head, d_key, d_value, d_model, d_inner_hid,
            use_flash=use_flash,
        )
        logits = layers.fc(input=dec_output, size=trg_vocab_size,
                           num_flatten_dims=2,
                           param_attr=ParamAttr(name="predict_w"),
                           bias_attr=ParamAttr(name="predict_b"))
        # logits at position t: [bk, T, V] -> [bk, V]
        t_idx = layers.cast(
            layers.expand(layers.reshape(t, [1, 1, 1]),
                          [bk, 1, trg_vocab_size]),
            "int64")
        step_logits = layers.reshape(
            layers.take_along_axis(logits, t_idx, axis=1),
            [b, k, trg_vocab_size])
        log_probs = _log_softmax(step_logits, axis_dim=2)

        sel_ids, sel_scores, parent_idx = layers.beam_search(
            pre_ids, pre_scores, None, log_probs, beam_size=k,
            end_id=eos_id)

        # reorder prefixes by parent beam, write new token at position t+1
        par3 = layers.expand(layers.reshape(parent_idx, [b, k, 1]),
                             [1, 1, t_buf])
        prefix_re = layers.take_along_axis(prefix, par3, axis=1)
        tpos = layers.increment(layers.assign(t), value=1.0, in_place=False)
        oh = layers.one_hot(tpos, t_buf)  # [T] f32, 1 at position t+1
        keep = layers.elementwise_mul(
            layers.cast(prefix_re, "float32"),
            layers.scale(oh, scale=-1.0, bias=1.0))
        put = layers.elementwise_mul(
            layers.cast(layers.reshape(sel_ids, [b, k, 1]), "float32"), oh)
        new_prefix = layers.cast(layers.elementwise_add(keep, put), "int64")

        layers.array_write(sel_ids, t, array=ids_arr)
        layers.array_write(parent_idx, t, array=parents_arr)
        layers.assign(new_prefix, output=prefix)
        layers.assign(sel_ids, output=pre_ids)
        layers.assign(sel_scores, output=pre_scores)
        layers.increment(t, value=1.0, in_place=True)
        layers.less_than(t, limit, cond=cond)

    sent_ids, sent_scores = layers.beam_search_decode(
        ids_arr, pre_scores, beam_size=k, end_id=eos_id,
        parents=parents_arr)
    return sent_ids, sent_scores, ["src_word", "src_pos"]


# ---------------------------------------------------------------------------
# Generation program pair: the While-FREE serving path.  One compiled
# prefill program (encoder -> cross cache) + ONE compiled per-token decode
# program stepped by the host (paddle_tpu/generation/sampler.py drives it,
# paddle_tpu/serving/generation.py continuous-batches it).
# ---------------------------------------------------------------------------


class GenerationPrograms:
    """Program pair + cache contract handed to the generation drivers."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


def build_generation_programs(
    src_vocab_size=10000,
    trg_vocab_size=10000,
    max_length=256,
    n_layer=6,
    n_head=8,
    d_key=64,
    d_value=64,
    d_model=512,
    d_inner_hid=2048,
    batch_size=4,
    src_seq_len=None,
    max_out_len=16,
    bos_id=0,
    eos_id=1,
    use_flash=False,
    beam_size=None,
    strategy="greedy",
    temperature=1.0,
    top_k=0,
    cache_prefix="gen",
    kv_cache=None,
):
    """Build the (prefill, decode[, hyps]) program set for autoregressive
    generation.  Parameter names are drawn through the same unique_name
    sequences as `transformer(...)` (fresh generator inside), so a scope
    trained with the train net generates directly.

    kv_cache=None follows FLAGS.kv_cache:
      * cached (default): prefill runs the encoder once and writes the
        per-layer cross-attention K/V into the `<prefix>_cross` ring
        buffer; the decode program embeds ONE token, appends its K/V to
        the `<prefix>_self` cache at the per-sequence length counters,
        and attends a single query row (decode_attention) — O(T) per
        token, all cache state scope-resident + donated, compile key
        length-independent.
      * recompute (flag-off parity oracle, non-beam only): prefill
        stores enc_output + the source pad bias; the decode program
        re-runs the full causal decoder over the host-maintained
        [max_out_len+1]-token prefix and samples at position t — O(T²)
        per token, token-identical outputs.

    Both decode programs feed fixed shapes every step, so the executor
    compiles each exactly once (asserted in tests/test_generation.py and
    bench.py --model decode).

    beam_size=None builds the sampling pair ("greedy"/"sample" via
    sample_token); an int builds the beam pair: the decode program runs
    one cached step + a beam_search op + the kv_cache_reorder parent
    gather, and `hyps` backtracks the stacked steps via
    beam_search_decode.
    """
    from ..core import framework as fw
    from ..flags import FLAGS
    from ..generation.kv_cache import KVCache, PagedKVCache

    if kv_cache is None:
        kv_cache = FLAGS.kv_cache
    src_seq_len = src_seq_len or max_length
    if max_length < max_out_len + 1 or max_length < src_seq_len:
        # position-table rows gate BOTH streams; an out-of-range lookup
        # NaN-fills (jnp.take) and one NaN poisons every softmax row
        # through the additive masks — fail loudly at build time instead
        raise ValueError(
            f"max_length={max_length} position table is smaller than the "
            f"decode buffer (max_out_len+1={max_out_len + 1}) or the "
            f"source length ({src_seq_len})")
    b = batch_size
    k = beam_size or 1
    lanes = b * k
    if beam_size is not None and not kv_cache:
        raise ValueError(
            "build_generation_programs: the beam pair requires the "
            "KV-cache route (FLAGS_kv_cache); the flag-off recompute "
            "oracle for beams is models/transformer.py build_decoder")

    t_buf = max_out_len + 1  # position 0 is BOS
    prefill = fw.Program()
    decode = fw.Program()
    hyps = fw.Program() if beam_size is not None else None
    startup = fw.Program()

    # FLAGS_paged_kv_cache swaps the ring buffers for block pools +
    # per-slot tables; the op surface (write/attend/reorder) is drawn
    # from the cache object, so the rest of the build is layout-blind.
    # Flag OFF keeps the ring construction byte-for-byte (parameter and
    # state names unchanged — checkpoints interop).
    paged = bool(kv_cache and FLAGS.paged_kv_cache)
    if paged:
        self_cache = PagedKVCache(
            f"{cache_prefix}_self", n_layer, lanes, _cache_rows(t_buf),
            n_head, d_key, block_t=int(FLAGS.kv_block_t),
            num_blocks=int(FLAGS.kv_cache_blocks))
        cross_cache = PagedKVCache(
            f"{cache_prefix}_cross", n_layer, lanes,
            _cache_rows(src_seq_len), n_head, d_key,
            block_t=int(FLAGS.kv_block_t),
            num_blocks=int(FLAGS.kv_cache_blocks))
    else:
        self_cache = KVCache(f"{cache_prefix}_self", n_layer, lanes,
                             _cache_rows(t_buf), n_head, d_key)
        cross_cache = KVCache(f"{cache_prefix}_cross", n_layer, lanes,
                              _cache_rows(src_seq_len), n_head, d_key)
    enc_out_name = f"{cache_prefix}_enc_out"
    src_bias_name = f"{cache_prefix}_src_bias"
    last_tok_name = f"{cache_prefix}_last_tok"
    finished_name = f"{cache_prefix}_finished"
    # greedy self-feed (FLAGS_fused_decode_step tail trim): the decode
    # program reads its own last sampled token from scope state instead
    # of a host feed, and latches eos in-graph exactly like the host
    # loop's masking — the per-token host round-trip of the argmax
    # disappears.  Sampled/beam paths are unchanged (they need the host
    # token stream / beam state anyway).
    use_self_feed = bool(kv_cache and beam_size is None
                         and strategy == "greedy"
                         and FLAGS.fused_decode_step)

    def state_var(name, shape, dtype):
        return fw.default_main_program().global_block().create_var(
            name=name, shape=list(shape), dtype=dtype,
            persistable=True, stop_gradient=True)

    def aux_var(name, shape):
        return state_var(name, shape, "float32")

    with fw.guard_unique_name():
        # ---- prefill ----------------------------------------------------
        with fw.program_guard(prefill, startup):
            src_word = layers.data(name="src_word",
                                   shape=[src_seq_len, 1], dtype="int64")
            src_pos = layers.data(name="src_pos", shape=[src_seq_len, 1],
                                  dtype="int64")
            active = (layers.data(name="gen_active", shape=[1],
                                  dtype="float32") if kv_cache else None)
            neg_inf = -1e9
            zero = layers.fill_constant([1], "int64", 0)
            is_pad = layers.cast(layers.equal(src_word, zero), "float32")
            src_bias = layers.reshape(
                layers.scale(is_pad, scale=neg_inf),
                [-1, 1, 1, src_seq_len])
            src_bias.stop_gradient = True
            enc_input = prepare_encoder(
                src_word, src_pos, src_vocab_size, d_model, max_length,
                word_emb_param_name="src_word_emb_table",
                pos_enc_param_name="src_pos_enc_table",
            )
            enc_output = encoder(
                enc_input, src_bias, n_layer, n_head, d_key, d_value,
                d_model, d_inner_hid, use_flash=use_flash,
            )
            src_lens = _src_token_lengths(src_word, src_seq_len)  # [b]
            if k > 1:  # tile per beam (beam-major within batch)
                enc_output = layers.reshape(
                    layers.expand(
                        layers.reshape(enc_output,
                                       [b, 1, src_seq_len, d_model]),
                        [1, k, 1, 1]),
                    [lanes, src_seq_len, d_model])
                src_lens = layers.reshape(
                    layers.expand(layers.reshape(src_lens, [b, 1]),
                                  [1, k]), [lanes])
            if kv_cache:
                if k > 1:
                    active_l = layers.reshape(
                        layers.expand(layers.reshape(active, [b, 1]),
                                      [1, k]), [lanes])
                else:
                    active_l = layers.reshape(active, [lanes])
                a32 = layers.cast(active_l, "int32")
                inv = layers.elementwise_sub(
                    layers.fill_constant([lanes], "int32", 1), a32)
                _prefill_cross_cache(enc_output, cross_cache, n_layer,
                                     n_head, d_key, d_value, active=a32)
                _, _, cross_len = cross_cache.vars_in()
                _, _, self_len = self_cache.vars_in()
                # joined sequences: cross len = true source length,
                # self len resets to 0; others keep their counters
                layers.assign(
                    layers.elementwise_add(
                        layers.elementwise_mul(a32, src_lens),
                        layers.elementwise_mul(inv, cross_len)),
                    output=cross_len)
                layers.assign(layers.elementwise_mul(inv, self_len),
                              output=self_len)
                if use_self_feed:
                    # self-feed state: joining lanes restart from BOS
                    # with a cleared finished latch; the rest keep their
                    # in-flight token (continuous batching's late joins)
                    last_tok = state_var(last_tok_name, (lanes, 1),
                                         "int64")
                    fin = state_var(finished_name, (lanes,), "int32")
                    a64 = layers.cast(a32, "int64")
                    inv64 = layers.cast(inv, "int64")
                    bos_c = layers.fill_constant([lanes], "int64",
                                                 bos_id)
                    layers.assign(
                        layers.reshape(
                            layers.elementwise_add(
                                layers.elementwise_mul(a64, bos_c),
                                layers.elementwise_mul(
                                    inv64,
                                    layers.reshape(last_tok, [lanes]))),
                            [lanes, 1]),
                        output=last_tok)
                    layers.assign(layers.elementwise_mul(inv, fin),
                                  output=fin)
            else:
                layers.assign(enc_output,
                              output=aux_var(enc_out_name,
                                             (lanes, src_seq_len,
                                              d_model)))
                layers.assign(src_bias,
                              output=aux_var(src_bias_name,
                                             (lanes, 1, 1, src_seq_len)))
            prefill_fetch = [src_lens.name]

        # ---- decode -----------------------------------------------------
        with fw.program_guard(decode, startup):
            if beam_size is None:
                if use_self_feed:
                    # scope-resident token state (read-then-written, so
                    # the executor donates it like the cache counters)
                    token = state_var(last_tok_name, (lanes, 1), "int64")
                    fin = state_var(finished_name, (lanes,), "int32")
                else:
                    token = layers.data(name="gen_token", shape=[1],
                                        dtype="int64")
                dactive = layers.data(name="gen_active", shape=[1],
                                      dtype="float32")
                if kv_cache:
                    _, _, self_len = self_cache.vars_in()
                    _, _, cross_len = cross_cache.vars_in()
                    da32 = layers.cast(layers.reshape(dactive, [lanes]),
                                       "int32")
                    att_len = layers.elementwise_add(self_len, da32)
                    pos_ids = layers.cast(
                        layers.reshape(self_len, [lanes, 1, 1]), "int64")
                    dec_input = prepare_encoder(
                        layers.reshape(token, [lanes, 1, 1]), pos_ids,
                        trg_vocab_size, d_model, max_length,
                        word_emb_param_name="trg_word_emb_table",
                        pos_enc_param_name="trg_pos_enc_table",
                    )
                    dec_output = cached_decoder_step(
                        dec_input, self_cache, cross_cache,
                        write_pos=self_len, self_lens=att_len,
                        cross_lens=cross_len, n_layer=n_layer,
                        n_head=n_head, d_key=d_key, d_value=d_value,
                        d_model=d_model, d_inner_hid=d_inner_hid,
                        active=da32)
                    logits = layers.fc(
                        input=dec_output, size=trg_vocab_size,
                        num_flatten_dims=2,
                        param_attr=ParamAttr(name="predict_w"),
                        bias_attr=ParamAttr(name="predict_b"))
                    next_tok = layers.sample_token(
                        layers.reshape(logits, [lanes, trg_vocab_size]),
                        strategy=strategy, temperature=temperature,
                        top_k=top_k)
                    if use_self_feed:
                        # in-graph eos latch — the exact host masking of
                        # GenerationSession.generate: finished lanes
                        # keep emitting (and self-feeding) eos, the
                        # latch ORs in fresh eos hits.  The masked token
                        # both writes the self-feed state and is the
                        # fetch, so host and device streams stay
                        # bit-identical.
                        eos_c = layers.fill_constant([lanes, 1], "int64",
                                                     eos_id)
                        one_c = layers.fill_constant([lanes, 1], "int64",
                                                     1)
                        fin64 = layers.cast(
                            layers.reshape(fin, [lanes, 1]), "int64")
                        not_fin = layers.elementwise_sub(one_c, fin64)
                        masked = layers.elementwise_add(
                            layers.elementwise_mul(fin64, eos_c),
                            layers.elementwise_mul(not_fin, next_tok))
                        is_eos = layers.reshape(
                            layers.cast(layers.equal(masked, eos_c),
                                        "int32"), [lanes])
                        layers.assign(
                            layers.elementwise_sub(
                                layers.elementwise_add(fin, is_eos),
                                layers.elementwise_mul(fin, is_eos)),
                            output=fin)
                        layers.assign(masked, output=token)
                        next_tok = masked
                    # advance the counters of the stepped sequences LAST
                    # (every read above wants the pre-step lengths)
                    layers.assign(att_len, output=self_len)
                else:
                    # full-prefix recompute oracle: the host maintains
                    # the [t_buf] prefix and feeds the step index
                    prefix = layers.data(name="gen_prefix",
                                         shape=[t_buf, 1], dtype="int64")
                    t_step = layers.data(name="gen_t", shape=[1],
                                         dtype="int64")
                    neg_inf = -1e9
                    ones_t = layers.fill_constant([t_buf, 1], "float32",
                                                  1.0)
                    arange_t = layers.elementwise_sub(
                        layers.cumsum(ones_t, axis=0), ones_t)
                    qpos = layers.reshape(arange_t, [1, t_buf, 1])
                    kpos = layers.reshape(arange_t, [1, 1, t_buf])
                    future = layers.cast(layers.less_than(qpos, kpos),
                                         "float32")
                    causal_bias = layers.reshape(
                        layers.scale(future, scale=neg_inf),
                        [1, 1, t_buf, t_buf])
                    causal_bias.stop_gradient = True
                    trg_pos_ids = layers.cast(
                        layers.expand(
                            layers.reshape(arange_t, [1, t_buf, 1]),
                            [lanes, 1, 1]),
                        "int64")
                    dec_input = prepare_encoder(
                        prefix, trg_pos_ids, trg_vocab_size, d_model,
                        max_length,
                        word_emb_param_name="trg_word_emb_table",
                        pos_enc_param_name="trg_pos_enc_table",
                    )
                    enc_out_v = aux_var(enc_out_name,
                                        (lanes, src_seq_len, d_model))
                    src_bias_v = aux_var(src_bias_name,
                                         (lanes, 1, 1, src_seq_len))
                    dec_output = decoder(
                        dec_input, enc_out_v, causal_bias, src_bias_v,
                        n_layer, n_head, d_key, d_value, d_model,
                        d_inner_hid, use_flash=use_flash,
                    )
                    logits = layers.fc(
                        input=dec_output, size=trg_vocab_size,
                        num_flatten_dims=2,
                        param_attr=ParamAttr(name="predict_w"),
                        bias_attr=ParamAttr(name="predict_b"))
                    t_idx = layers.cast(
                        layers.expand(layers.reshape(t_step, [1, 1, 1]),
                                      [lanes, 1, trg_vocab_size]),
                        "int64")
                    step_logits = layers.reshape(
                        layers.take_along_axis(logits, t_idx, axis=1),
                        [lanes, trg_vocab_size])
                    next_tok = layers.sample_token(
                        step_logits, strategy=strategy,
                        temperature=temperature, top_k=top_k)
                decode_fetch = [next_tok.name]
            else:
                pre_ids = layers.data(name="gen_pre_ids", shape=[k],
                                      dtype="int64")
                pre_scores = layers.data(name="gen_pre_scores",
                                         shape=[k], dtype="float32")
                parents = layers.data(name="gen_parents", shape=[1],
                                      dtype="int64")
                _, _, self_len = self_cache.vars_in()
                _, _, cross_len = cross_cache.vars_in()
                flat_parents = layers.reshape(parents, [lanes])
                self_cache.reorder(flat_parents)
                ones_l = layers.fill_constant([lanes], "int32", 1)
                att_len = layers.elementwise_add(self_len, ones_l)
                pos_ids = layers.cast(
                    layers.reshape(self_len, [lanes, 1, 1]), "int64")
                dec_input = prepare_encoder(
                    layers.reshape(pre_ids, [lanes, 1, 1]), pos_ids,
                    trg_vocab_size, d_model, max_length,
                    word_emb_param_name="trg_word_emb_table",
                    pos_enc_param_name="trg_pos_enc_table",
                )
                dec_output = cached_decoder_step(
                    dec_input, self_cache, cross_cache,
                    write_pos=self_len, self_lens=att_len,
                    cross_lens=cross_len, n_layer=n_layer, n_head=n_head,
                    d_key=d_key, d_value=d_value, d_model=d_model,
                    d_inner_hid=d_inner_hid)
                logits = layers.fc(
                    input=dec_output, size=trg_vocab_size,
                    num_flatten_dims=2,
                    param_attr=ParamAttr(name="predict_w"),
                    bias_attr=ParamAttr(name="predict_b"))
                log_probs = _log_softmax(
                    layers.reshape(logits, [b, k, trg_vocab_size]),
                    axis_dim=2)
                sel_ids, sel_scores, parent_idx = layers.beam_search(
                    pre_ids, pre_scores, None, log_probs, beam_size=k,
                    end_id=eos_id)
                next_parents = _flat_beam_parents(parent_idx, b, k)
                layers.assign(att_len, output=self_len)
                decode_fetch = [sel_ids.name, sel_scores.name,
                                next_parents.name]

        # ---- hyps (beam backtrack) --------------------------------------
        if hyps is not None:
            with fw.program_guard(hyps, startup):
                ids_steps = layers.data(name="gen_steps_ids",
                                        shape=[b, k], dtype="int64")
                parent_steps = layers.data(name="gen_steps_parents",
                                           shape=[b, k], dtype="int64")
                final_scores = layers.data(name="gen_final_scores",
                                           shape=[k], dtype="float32")
                sent_ids, sent_scores = layers.beam_search_decode(
                    ids_steps, final_scores, beam_size=k, end_id=eos_id,
                    parents=parent_steps)
                hyps_fetch = [sent_ids.name, sent_scores.name]

    if beam_size is not None:
        decode_feeds = ["gen_pre_ids", "gen_pre_scores", "gen_parents"]
    elif not kv_cache:
        decode_feeds = ["gen_prefix", "gen_t"]
    elif use_self_feed:
        decode_feeds = ["gen_active"]
    else:
        decode_feeds = ["gen_token", "gen_active"]
    return GenerationPrograms(
        prefill=prefill, decode=decode, hyps=hyps, startup=startup,
        self_cache=self_cache, cross_cache=cross_cache,
        enc_out_name=enc_out_name, src_bias_name=src_bias_name,
        self_feed_token=use_self_feed, last_tok_name=last_tok_name,
        finished_name=finished_name, decode_feeds=decode_feeds,
        prefill_fetch=prefill_fetch, decode_fetch=decode_fetch,
        hyps_fetch=hyps_fetch if hyps is not None else None,
        batch_size=b, beam_size=beam_size, lanes=lanes,
        src_seq_len=src_seq_len, max_out_len=max_out_len, t_buf=t_buf,
        bos_id=bos_id, eos_id=eos_id, kv_cache=kv_cache, paged=paged,
        kv_block_t=self_cache.block_t if paged else 0,
        src_vocab_size=src_vocab_size, trg_vocab_size=trg_vocab_size,
        d_model=d_model, strategy=strategy)
