"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, box_coder, iou_similarity, bipartite_match, multiclass_nms,
roi_pool, roi_align, target_assign, ssd_loss:779, detection_output:201,
multi_box_head:1259, density_prior_box:1133, detection_map:515)."""

from __future__ import annotations

import math

from ..layer_helper import LayerHelper

__all__ = [
    "anchor_generator",
    "box_clip",
    "prior_box",
    "box_coder",
    "iou_similarity",
    "bipartite_match",
    "multiclass_nms",
    "roi_pool",
    "roi_align",
    "target_assign",
    "ssd_loss",
    "detection_output",
    "multi_box_head",
    "density_prior_box",
    "detection_map",
    "yolov3_loss",
    "generate_proposals",
    "generate_proposal_labels",
    "rpn_target_assign",
    "polygon_box_transform",
    "roi_perspective_transform",
    "psroi_pool",
]


def _expand_ratios_static(ratios, flip):
    # must agree EXACTLY with lower_prior_box's expansion: same function
    from ..ops.detection_ops import _expand_aspect_ratios

    return _expand_aspect_ratios(ratios, flip)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        "prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios or [1.0]),
            "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
        },
    )
    boxes.stop_gradient = True
    var.stop_gradient = True
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference("float32")
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        "box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "iou_similarity",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int64")
    dist = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx], "ColToRowMatchDis": [dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return idx, dist


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_rois_num=False):
    """Dense NMS: Out [N, keep_top_k, 6] padded with label -1 (+ optional
    NmsRoisNum [N]); the reference returns a ragged LoD tensor."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [num]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
            "nms_eta": nms_eta,
            "background_label": background_label,
        },
    )
    if return_rois_num:
        return out, num
    return out


def _roi(op_type, input, rois, pooled_height, pooled_width, spatial_scale,
         batch_idx, extra_attrs, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_idx is not None:
        inputs["BatchIdx"] = [batch_idx]
    helper.append_op(
        op_type,
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
            **extra_attrs,
        },
    )
    if rois.shape and input.shape:
        out.shape = (rois.shape[0], input.shape[1], pooled_height,
                     pooled_width)
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, batch_idx=None, name=None):
    return _roi("roi_pool", input, rois, pooled_height, pooled_width,
                spatial_scale, batch_idx, {}, name)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, batch_idx=None,
              name=None):
    return _roi("roi_align", input, rois, pooled_height, pooled_width,
                spatial_scale, batch_idx,
                {"sampling_ratio": sampling_ratio}, name)


def anchor_generator(input, anchor_sizes, aspect_ratios, variance=None,
                     stride=None, offset=0.5, name=None):
    """RPN anchors in pixel coords (reference detection.py anchor_generator,
    anchor_generator_op.h)."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={
            "anchor_sizes": list(anchor_sizes),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        },
    )
    anchors.stop_gradient = True
    var.stop_gradient = True
    return anchors, var


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference detection.py box_clip)."""
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "box_clip",
        inputs={"Input": [input], "ImInfo": [im_info]},
        outputs={"Output": [out]},
    )
    return out


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Assign per-prior targets from matched gt rows (reference
    layers/detection.py target_assign / target_assign_op.h).  Dense idiom:
    input [N, G, K] (or [N, G, P, K]), matched_indices [N, P],
    negative_indices a dense [N, P] 0/1 mask.  Returns (out, out_weight)."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_wt = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(
        "target_assign",
        inputs=inputs,
        outputs={"Out": [out], "OutWeight": [out_wt]},
        attrs={"mismatch_value": mismatch_value},
    )
    out.stop_gradient = True
    out_wt.stop_gradient = True
    return out, out_wt


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             gt_count=None):
    """SSD multibox loss (reference layers/detection.py:779 ssd_loss —
    the same 5-step composition, over dense padded gt).

    Dense idiom: gt_box [N, G, 4] / gt_label [N, G] padded; `gt_count`
    [N] gives the valid prefix per image (padded rows are masked out of
    matching).  location [N, P, 4], confidence [N, P, C],
    prior_box/prior_box_var [P, 4].  Returns [N, 1] loss.
    """
    from . import nn, tensor

    if mining_type != "max_negative":
        raise ValueError("Only mining_type == 'max_negative' is supported")
    num_prior = location.shape[-2]
    num_class = confidence.shape[-1]
    g = gt_box.shape[1]

    # 1. IoU between every gt and every prior, per image: [N, G, P]
    flat_gt = tensor.reshape(gt_box, [-1, 4])
    iou = iou_similarity(flat_gt, prior_box)              # [N*G, P]
    iou = tensor.reshape(iou, [-1, g, num_prior])
    if gt_count is not None:
        # mask [N, G, 1]: 1 for real gt rows, 0 for padding
        arange_g = _range_like(gt_box, g)                 # [G] float32
        cnt = tensor.reshape(tensor.cast(gt_count, "float32"), [-1, 1])
        valid = tensor.cast(
            tensor.less_than(tensor.reshape(arange_g, [1, g]), cnt),
            "float32")
        valid = tensor.reshape(valid, [-1, g, 1])
        # padded gt rows must fall below the matcher's -1e9 exhaustion
        # threshold so they can never be matched (even after all real gts
        # are claimed) — their box_coder encodings contain log(0) = -inf
        penalty = tensor.scale(valid, scale=1e10, bias=-1e10)  # 0 or -1e10
        iou = tensor.elementwise_add(
            tensor.elementwise_mul(iou, valid), penalty)
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold)               # [N, P]

    # 2. conf loss for mining
    gt_label3 = tensor.reshape(tensor.cast(gt_label, "float32"), [-1, g, 1])
    target_label, _ = target_assign(gt_label3, matched_indices,
                                    mismatch_value=background_label)
    conf2d = tensor.reshape(confidence, [-1, num_class])
    tl2d = tensor.reshape(tensor.cast(target_label, "int64"), [-1, 1])
    tl2d.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(conf2d, tl2d)
    conf_loss = tensor.reshape(conf_loss, [-1, num_prior])
    conf_loss.stop_gradient = True

    # 3. hard-negative mining
    helper = LayerHelper("ssd_loss")
    neg_mask = helper.create_variable_for_type_inference("int32")
    updated = helper.create_variable_for_type_inference(matched_indices.dtype)
    helper.append_op(
        "mine_hard_examples",
        inputs={"ClsLoss": [conf_loss], "MatchIndices": [matched_indices],
                "MatchDist": [matched_dist]},
        outputs={"NegIndices": [neg_mask], "UpdatedMatchIndices": [updated]},
        attrs={"neg_pos_ratio": neg_pos_ratio,
               "neg_dist_threshold": neg_overlap,
               "mining_type": mining_type,
               "sample_size": sample_size or 0},
    )
    neg_mask.stop_gradient = True
    updated.stop_gradient = True

    # 4. targets: encode gt against priors, gather matched
    encoded = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                        target_box=flat_gt,
                        code_type="encode_center_size")   # [P, N*G, 4]
    enc = tensor.transpose(encoded, [1, 0, 2])            # [N*G, P, 4]
    enc = tensor.reshape(enc, [-1, g, num_prior, 4])      # [N, G, P, 4]
    target_bbox, target_loc_weight = target_assign(
        enc, updated, mismatch_value=background_label)
    target_label, target_conf_weight = target_assign(
        gt_label3, updated, negative_indices=neg_mask,
        mismatch_value=background_label)

    # 5. losses
    tl2d = tensor.reshape(tensor.cast(target_label, "int64"), [-1, 1])
    tl2d.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(conf2d, tl2d)
    conf_w = tensor.reshape(target_conf_weight, [-1, 1])
    conf_loss = tensor.elementwise_mul(conf_loss, conf_w)

    loc2d = tensor.reshape(location, [-1, 4])
    tb2d = tensor.reshape(target_bbox, [-1, 4])
    tb2d.stop_gradient = True
    loc_loss = nn.smooth_l1(loc2d, tb2d)
    loc_w = tensor.reshape(target_loc_weight, [-1, 1])
    loc_loss = tensor.elementwise_mul(loc_loss, loc_w)

    loss = tensor.elementwise_add(
        tensor.scale(conf_loss, scale=conf_loss_weight),
        tensor.scale(loc_loss, scale=loc_loss_weight))
    loss = tensor.reshape(loss, [-1, num_prior])
    loss = tensor.reduce_sum(loss, dim=1, keep_dim=True)
    if normalize:
        normalizer = tensor.scale(tensor.reduce_sum(target_loc_weight),
                                  bias=1e-6)
        loss = tensor.elementwise_div(loss, tensor.reshape(normalizer, [1]))
    return loss


def _range_like(ref_var, n):
    """[0..n) as a float32 graph constant."""
    from . import tensor
    import numpy as np

    return tensor.assign(np.arange(n, dtype="float32"))


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predictions + multiclass NMS (reference
    layers/detection.py:201 detection_output).  loc [N, P, 4] deltas,
    scores [N, P, C] logits.  Returns (out [N, keep_top_k, 6], counts)."""
    from . import nn, tensor

    decoded = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                        target_box=loc,
                        code_type="decode_center_size")   # [N, P, 4]
    probs = nn.softmax(scores)                            # [N, P, C]
    probs_t = tensor.transpose(probs, [0, 2, 1])          # [N, C, P]
    return multiclass_nms(
        bboxes=decoded, scores=probs_t, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, background_label=background_label,
        return_rois_num=True)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=None, flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD prediction heads over a feature pyramid (reference
    layers/detection.py:1259 multi_box_head): per feature map, conv loc
    [P*4] + conf [P*C] heads and prior boxes; concat across maps.
    Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C],
    boxes [P, 4], variances [P, 4])."""
    from . import nn, tensor

    variance = variance or [0.1, 0.1, 0.2, 0.2]
    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule (detection.py:1397-1410)
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        mins = [mins] if not isinstance(mins, (list, tuple)) else list(mins)
        maxs = ([maxs] if maxs and not isinstance(maxs, (list, tuple))
                else (list(maxs) if maxs else None))
        ar = [ar] if not isinstance(ar, (list, tuple)) else list(ar)
        if steps:
            layer_steps = (list(steps[i])
                           if isinstance(steps[i], (list, tuple))
                           else [float(steps[i])] * 2)
        elif step_w or step_h:
            layer_steps = [step_w[i] if step_w else 0.0,
                           step_h[i] if step_h else 0.0]
        else:
            layer_steps = None
        box, var = prior_box(
            feat, image, mins, maxs, ar, variance, flip, clip,
            layer_steps, offset, None,
            min_max_aspect_ratios_order)
        # [H, W, P, 4] -> [H*W*P, 4]
        box = tensor.reshape(box, [-1, 4])
        var = tensor.reshape(var, [-1, 4])
        # priors per cell, statically (mirrors lower_prior_box's spec)
        expanded = _expand_ratios_static(ar, flip)
        num_priors_per_cell = len(mins) * len(expanded) + (
            min(len(mins), len(maxs)) if maxs else 0)
        num_px = num_priors_per_cell * feat.shape[2] * feat.shape[3]

        loc = nn.conv2d(feat, num_filters=num_priors_per_cell * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        loc = tensor.transpose(loc, [0, 2, 3, 1])        # NHWC
        loc = tensor.reshape(loc, [-1, num_px, 4])
        conf = nn.conv2d(feat, num_filters=num_priors_per_cell * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = tensor.transpose(conf, [0, 2, 3, 1])
        conf = tensor.reshape(conf, [-1, num_px, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(box)
        vars_all.append(var)

    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    boxes = tensor.concat(boxes_all, axis=0)
    variances = tensor.concat(vars_all, axis=0)
    boxes.stop_gradient = True
    variances.stop_gradient = True
    return mbox_locs, mbox_confs, boxes, variances


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=None, clip=False, steps=None, offset=0.5,
                      name=None):
    """Densified prior boxes (reference layers/detection.py:1133,
    density_prior_box_op.h)."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    attrs = {
        "densities": [int(d) for d in densities],
        "fixed_sizes": [float(s) for s in fixed_sizes],
        "fixed_ratios": [float(r) for r in fixed_ratios],
        "variances": variance or [0.1, 0.1, 0.2, 0.2],
        "clip": clip,
        "offset": offset,
    }
    if steps:
        attrs["step_w"], attrs["step_h"] = float(steps[0]), float(steps[1])
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs=attrs,
    )
    boxes.stop_gradient = True
    var.stop_gradient = True
    return boxes, var


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral"):
    """Single-shot mAP metric (reference layers/detection.py:515,
    detection_map_op.cc).  Dense idiom: detect_res [N, D, 6] padded with
    label -1 (multiclass_nms output), label [N, G, 6]."""
    helper = LayerHelper("detection_map")
    m_ap = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "detection_map",
        inputs={"DetectRes": [detect_res], "Label": [label]},
        outputs={"MAP": [m_ap]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version},
    )
    m_ap.shape = (1,)
    return m_ap


def yolov3_loss(x, gtbox, gtlabel, anchors, class_num, ignore_thresh,
                loss_weight_xy=1.0, loss_weight_wh=1.0,
                loss_weight_conf_target=1.0, loss_weight_conf_notarget=1.0,
                loss_weight_class=1.0, name=None):
    """YOLOv3 loss (reference layers/detection.py yolov3_loss,
    yolov3_loss_op.h).  x [N, A*(5+C), H, W]; gtbox [N, B, 4] normalized
    cx/cy/w/h (zero rows = padding); gtlabel [N, B]."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolov3_loss",
        inputs={"X": [x], "GTBox": [gtbox], "GTLabel": [gtlabel]},
        outputs={"Loss": [loss]},
        attrs={
            "anchors": [float(a) for a in anchors],
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
            "loss_weight_xy": loss_weight_xy,
            "loss_weight_wh": loss_weight_wh,
            "loss_weight_conf_target": loss_weight_conf_target,
            "loss_weight_conf_notarget": loss_weight_conf_notarget,
            "loss_weight_class": loss_weight_class,
        },
    )
    loss.shape = (1,)
    return loss


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances=None,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposals (reference layers/detection.py generate_proposals,
    generate_proposals_op.cc).  Dense: returns (rois [N, post, 4],
    roi_probs [N, post, 1], rois_num [N])."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference("float32")
    probs = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    inputs = {"Scores": [scores], "BboxDeltas": [bbox_deltas],
              "ImInfo": [im_info], "Anchors": [anchors]}
    if variances is not None:
        inputs["Variances"] = [variances]
    helper.append_op(
        "generate_proposals",
        inputs=inputs,
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisNum": [num]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size},
    )
    for v in (rois, probs, num):
        v.stop_gradient = True
    return rois, probs, num


def rpn_target_assign(anchor_box, gt_boxes, im_info=None, is_crowd=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=False,
                      name=None):
    """RPN anchor sampling (reference layers/detection.py
    rpn_target_assign, rpn_target_assign_op.cc).  Dense: returns
    (target_label [N, A] with 1/0/-1, target_bbox [N, A, 4],
    bbox_inside_weight [N, A, 1])."""
    if use_random:
        raise NotImplementedError(
            "rpn_target_assign: use_random sampling is not supported under "
            "jit; subsampling is deterministic (top-IoU fg, first bg)")
    helper = LayerHelper("rpn_target_assign", name=name)
    label = helper.create_variable_for_type_inference("int32")
    tbox = helper.create_variable_for_type_inference("float32")
    inw = helper.create_variable_for_type_inference("float32")
    inputs = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if im_info is not None:
        inputs["ImInfo"] = [im_info]
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd]
    helper.append_op(
        "rpn_target_assign",
        inputs=inputs,
        outputs={"TargetLabel": [label], "TargetBBox": [tbox],
                 "BBoxInsideWeight": [inw]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_straddle_thresh": rpn_straddle_thresh},
    )
    for v in (label, tbox, inw):
        v.stop_gradient = True
    return label, tbox, inw


def polygon_box_transform(input, name=None):
    """EAST geometry map -> absolute quad coordinates (reference
    polygon_box_transform_op.cc)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    out.shape = input.shape
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              batch_idx=None, name=None):
    """Warp quad ROIs to rectangles (reference
    roi_perspective_transform_op.cc).  rois [R, 8] quads."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_idx is not None:
        inputs["BatchIdx"] = [batch_idx]
    helper.append_op(
        "roi_perspective_transform",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale},
    )
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, batch_idx=None, name=None):
    """Position-sensitive ROI pooling (reference psroi_pool_op.h)."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_idx is not None:
        inputs["BatchIdx"] = [batch_idx]
    helper.append_op(
        "psroi_pool",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"output_channels": output_channels,
               "spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width},
    )
    return out


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=False, name=None):
    """Second-stage RoI sampling + targets (reference layers/detection.py
    generate_proposal_labels, generate_proposal_labels_op.cc:1).  Dense:
    returns (rois [N, B, 4], labels_int32 [N, B, 1], bbox_targets
    [N, B, 4*C], bbox_inside_weights, bbox_outside_weights, rois_valid
    [N, B, 1]) with B = batch_size_per_im; unfilled rows carry label -1,
    zero weights, rois_valid 0."""
    if use_random:
        raise NotImplementedError(
            "generate_proposal_labels: use_random sampling is not "
            "supported under jit; sampling is deterministic (top-IoU fg, "
            "first bg)")
    helper = LayerHelper("generate_proposal_labels", name=name)
    outs = {
        "Rois": helper.create_variable_for_type_inference("float32"),
        "LabelsInt32": helper.create_variable_for_type_inference("int32"),
        "BboxTargets": helper.create_variable_for_type_inference("float32"),
        "BboxInsideWeights":
            helper.create_variable_for_type_inference("float32"),
        "BboxOutsideWeights":
            helper.create_variable_for_type_inference("float32"),
        "RoisValid": helper.create_variable_for_type_inference("float32"),
    }
    helper.append_op(
        "generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={k: [v] for k, v in outs.items()},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums},
    )
    nb = rpn_rois.shape[0] if rpn_rois.shape else -1
    b = batch_size_per_im
    c4 = 4 * class_nums if class_nums else -1
    outs["Rois"].shape = (nb, b, 4)
    outs["LabelsInt32"].shape = (nb, b, 1)
    outs["BboxTargets"].shape = (nb, b, c4)
    outs["BboxInsideWeights"].shape = (nb, b, c4)
    outs["BboxOutsideWeights"].shape = (nb, b, c4)
    outs["RoisValid"].shape = (nb, b, 1)
    for v in outs.values():
        v.stop_gradient = True
    return (outs["Rois"], outs["LabelsInt32"], outs["BboxTargets"],
            outs["BboxInsideWeights"], outs["BboxOutsideWeights"],
            outs["RoisValid"])
