"""Transformer model + flash attention tests (reference:
test_parallel_executor_transformer.py / dist_transformer.py scale-downs)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer as T


def _tiny_transformer(use_flash=False):
    return T.transformer(
        src_vocab_size=64,
        trg_vocab_size=64,
        max_length=16,
        n_layer=2,
        n_head=2,
        d_key=8,
        d_value=8,
        d_model=16,
        d_inner_hid=32,
        dropout_rate=0.0,
        src_seq_len=16,
        trg_seq_len=16,
        use_flash=use_flash,
    )


def test_transformer_trains():
    avg_cost, predict, feed_names = _tiny_transformer()
    pt.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    batch = T.make_batch(4, 16, 16, 2, 64, 64, rng)
    losses = []
    for _ in range(30):
        (l,) = exe.run(feed=batch, fetch_list=[avg_cost])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0] * 0.6, losses  # memorizes the fixed batch


def test_flash_attention_matches_reference():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.attention import (
        flash_attention,
        reference_attention,
    )

    with jax.default_matmul_precision("highest"):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 2, 128, 128).astype("float32"))
        k = jnp.asarray(rng.randn(1, 2, 128, 128).astype("float32"))
        v = jnp.asarray(rng.randn(1, 2, 128, 128).astype("float32"))
        bias = jnp.asarray(rng.randn(1, 2, 128, 128).astype("float32"))
        ref = reference_attention(q, k, v, bias, scale=0.125)
        out = flash_attention(q, k, v, bias, scale=0.125, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

        refc = reference_attention(q, k, v, None, 0.125, causal=True)
        outc = flash_attention(q, k, v, None, 0.125, causal=True,
                               block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(outc), np.asarray(refc), atol=1e-5)


def test_fused_attention_layer_in_program():
    from paddle_tpu import layers

    q = layers.data(name="q", shape=[2, 64, 128], dtype="float32")
    k = layers.data(name="k", shape=[2, 64, 128], dtype="float32")
    v = layers.data(name="v", shape=[2, 64, 128], dtype="float32")
    out = layers.contrib.fused_attention(q, k, v, scale=0.1)
    exe = pt.Executor(pt.CPUPlace())
    rng = np.random.RandomState(0)
    feed = {
        n: rng.randn(1, 2, 64, 128).astype("float32") for n in ("q", "k", "v")
    }
    (o,) = exe.run(feed=feed, fetch_list=[out])
    assert o.shape == (1, 2, 64, 128)

    from paddle_tpu.kernels.attention import reference_attention
    import jax.numpy as jnp

    ref = reference_attention(
        jnp.asarray(feed["q"]), jnp.asarray(feed["k"]), jnp.asarray(feed["v"]),
        None, 0.1,
    )
    np.testing.assert_allclose(o, np.asarray(ref), atol=2e-2, rtol=2e-2)


def test_transformer_with_flash_matches_unfused():
    # same seed -> same params; flash vs unfused attention give same loss
    prog_a, prog_b = pt.Program(), pt.Program()
    startup_a, startup_b = pt.Program(), pt.Program()
    losses = {}
    rng_batch = np.random.RandomState(3)
    batch = T.make_batch(2, 16, 16, 2, 64, 64, rng_batch)
    for name, prog, startup, flash in (
        ("unfused", prog_a, startup_a, False),
        ("flash", prog_b, startup_b, True),
    ):
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                avg_cost, _, _ = _tiny_transformer(use_flash=flash)
        prog.random_seed = startup.random_seed = 17
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        (l,) = exe.run(prog, feed=batch, fetch_list=[avg_cost], scope=scope)
        losses[name] = float(np.asarray(l))
    assert abs(losses["flash"] - losses["unfused"]) < 2e-2, losses
