"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (reference: wang-kangkang/Paddle @ Fluid 1.2),
re-designed for JAX/XLA/Pallas/pjit.

Architecture (vs the reference, see SURVEY.md):
  * Python builds a Program IR (core/framework.py) — parity with
    ProgramDesc/BlockDesc/OpDesc — but execution traces the whole program
    into ONE jitted XLA computation (core/executor.py); the per-op C++
    interpreter loop, kernel registry, SSA graph executors, memory
    transpilers and NCCL op-handles of the reference are deleted by design.
  * Gradients: program-level grad ops (core/backward.py) whose default
    lowering is jax.vjp of the forward lowering (core/registry.py).
  * Parallelism: jax.sharding.Mesh + NamedSharding/pjit (compiler.py,
    parallel/) instead of ParallelExecutor/DistributeTranspiler RPC.
"""

from . import ops  # registers all op lowerings  # noqa: F401

from .core.framework import (  # noqa: F401
    Program,
    Block,
    Variable,
    Parameter,
    Operator,
    program_guard,
    default_main_program,
    default_startup_program,
    switch_main_program,
    switch_startup_program,
    unique_name,
    grad_var_name,
    OpRole,
    VarType,
)
from .core.executor import (  # noqa: F401
    Executor,
    Scope,
    global_scope,
    scope_guard,
    CPUPlace,
    TPUPlace,
    Place,
    default_place,
    as_numpy,
)
from .core.backward import append_backward, calc_gradient  # noqa: F401
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import nets  # noqa: F401
from . import io  # noqa: F401
from . import metrics  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .data_feed import (  # noqa: F401
    AsyncExecutor,
    DataFeedDesc,
    MultiSlotDataFeed,
)
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import interop  # noqa: F401
from .interop import to_dlpack, from_dlpack  # noqa: F401
from . import amp  # noqa: F401
from . import memory  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import contrib  # noqa: F401
from . import recordio  # noqa: F401
from . import imperative  # noqa: F401
from . import flags  # noqa: F401
from .flags import FLAGS  # noqa: F401
from . import log  # noqa: F401
from . import debugger  # noqa: F401
from . import passes  # noqa: F401
from . import utils  # noqa: F401
from . import testing  # noqa: F401
from .core import registry  # noqa: F401

__version__ = "0.1.0"


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
