"""Statistical + determinism tests for the counter-based hash PRNG
(kernels/hash_rng.py) and its use by the dropout op.

The reference's dropout contract (dropout_op.cc): mask ~ Bernoulli(1-p),
identical mask applied in forward and backward.  Here the mask is
regenerated (not saved), so the determinism properties ARE the contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels import hash_rng


def _bits(seed, n):
    idx = jnp.arange(n, dtype=jnp.uint32)
    return np.asarray(hash_rng.mix32(idx * jnp.uint32(hash_rng.GOLDEN)
                                     + jnp.uint32(seed)))


class TestHashBits:
    def test_deterministic(self):
        assert (_bits(123, 1000) == _bits(123, 1000)).all()

    def test_seed_sensitivity(self):
        # one-bit seed change flips ~half the mask decisions
        a = _bits(0x1234, 1 << 14) >> 31
        b = _bits(0x1235, 1 << 14) >> 31
        frac = (a != b).mean()
        assert 0.45 < frac < 0.55

    def test_uniformity_chi_square(self):
        # 256-bucket chi-square over the top byte; 3 sigma ~ 255 + 3*sqrt(510)
        n = 1 << 16
        top = _bits(42, n) >> 24
        counts = np.bincount(top, minlength=256)
        chi2 = ((counts - n / 256) ** 2 / (n / 256)).sum()
        assert chi2 < 350, chi2

    def test_mean_variance(self):
        n = 1 << 16
        u = _bits(7, n).astype(np.float64) / 2**32
        assert abs(u.mean() - 0.5) < 0.01
        assert abs(u.var() - 1 / 12) < 0.01

    def test_adjacent_index_independence(self):
        # lag-1 autocorrelation of the uniform stream ~ 0
        n = 1 << 16
        u = _bits(99, n).astype(np.float64) / 2**32
        r = np.corrcoef(u[:-1], u[1:])[0, 1]
        assert abs(r) < 0.02, r


class TestKeepMask:
    @pytest.mark.parametrize("rate", [0.1, 0.5, 0.9])
    def test_keep_fraction(self, rate):
        seed = jnp.uint32(31337)
        m = np.asarray(hash_rng.keep_mask(seed, (256, 256), rate))
        frac = m.mean()
        assert abs(frac - (1.0 - rate)) < 0.02, (rate, frac)

    def test_rate_zero_keeps_all(self):
        m = np.asarray(hash_rng.keep_mask(jnp.uint32(5), (64,), 0.0))
        assert m.all()

    def test_base_index_tiles_match_full(self):
        # blocked generation with base_index == slicing the full mask
        seed = jnp.uint32(777)
        full = np.asarray(hash_rng.keep_mask(seed, (4, 128), 0.3))
        t0 = np.asarray(hash_rng.keep_mask(seed, (2, 128), 0.3, base_index=0))
        t1 = np.asarray(hash_rng.keep_mask(seed, (2, 128), 0.3,
                                           base_index=2 * 128))
        assert (full[:2] == t0).all() and (full[2:] == t1).all()

    def test_keep_mask_tile_matches_keep_mask(self):
        seed = jnp.uint32(4242)
        idx = jnp.arange(512, dtype=jnp.uint32).reshape(4, 128)
        a = np.asarray(hash_rng.keep_mask(seed, (4, 128), 0.25))
        b = np.asarray(hash_rng.keep_mask_tile(seed, idx, 0.25))
        assert (a == b).all()

    def test_site_independence(self):
        # different rng_ids (seeds via seed_from_key) give uncorrelated masks
        key = jax.random.key(0, impl="rbg")
        m1 = np.asarray(hash_rng.keep_mask(
            hash_rng.seed_from_key(key, 1), (1 << 14,), 0.5))
        m2 = np.asarray(hash_rng.keep_mask(
            hash_rng.seed_from_key(key, 2), (1 << 14,), 0.5))
        agree = (m1 == m2).mean()
        assert 0.45 < agree < 0.55

    def test_step_independence(self):
        # fold_in'ing the key (a new step) changes the mask
        key = jax.random.key(0, impl="rbg")
        k2 = jax.random.fold_in(key, 1)
        m1 = np.asarray(hash_rng.keep_mask(
            hash_rng.seed_from_key(key, 1), (1 << 14,), 0.5))
        m2 = np.asarray(hash_rng.keep_mask(
            hash_rng.seed_from_key(k2, 1), (1 << 14,), 0.5))
        agree = (m1 == m2).mean()
        assert 0.45 < agree < 0.55


class TestDropoutOpUsesHash:
    def test_train_fwd_bwd_mask_consistency(self):
        """Grad of sum(dropout(x)) must be scale exactly where out != 0 —
        i.e. the backward regenerated the forward's mask bit-exactly."""
        import paddle_tpu as pt
        from paddle_tpu import layers

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[64, 64], dtype="float32")
            x.stop_gradient = False
            out = layers.dropout(x, dropout_prob=0.4,
                                 dropout_implementation="upscale_in_train")
            loss = layers.reduce_sum(out)
            pt.append_backward(loss)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        xv = np.random.RandomState(0).randn(1, 64, 64).astype("float32")
        outs = exe.run(prog, feed={"x": xv},
                       fetch_list=[out.name, "x@GRAD"], scope=scope)
        o, gx = np.asarray(outs[0]), np.asarray(outs[1])
        scale = 1.0 / 0.6
        kept = o != 0
        assert np.allclose(gx[kept], scale, atol=1e-5)
        assert np.allclose(gx[~kept], 0.0)
        # keep fraction sane
        assert abs(kept.mean() - 0.6) < 0.05


class TestFastMixer:
    """mix32_fast backs the in-kernel attention masks — same statistical
    contract at lower op count (the per-head seed is full-mix32)."""

    def test_keep_fraction_and_seed_mix(self):
        key = jax.random.key(0, impl="rbg")
        seed = hash_rng.seed_from_key(key, 5)
        for rate in (0.1, 0.5):
            m = np.asarray(hash_rng.keep_mask_attn(seed, (2, 4, 64, 64),
                                                   rate))
            assert abs(m.mean() - (1 - rate)) < 0.02, (rate, m.mean())
        # different heads decorrelated (seed path uses full mix32)
        m = np.asarray(hash_rng.keep_mask_attn(seed, (1, 2, 64, 64), 0.5))
        agree = (m[0, 0] == m[0, 1]).mean()
        assert 0.45 < agree < 0.55

    def test_adjacent_index_independence_fast(self):
        idx = jnp.arange(1 << 14, dtype=jnp.uint32)
        m = np.asarray(hash_rng.keep_mask_tile(jnp.uint32(99), idx, 0.5,
                                               fast=True))
        r = np.corrcoef(m[:-1], m[1:])[0, 1]
        assert abs(r) < 0.03, r
