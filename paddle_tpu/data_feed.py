"""MultiSlot data feed + AsyncExecutor-style file trainer (reference:
framework/data_feed.{h,cc,proto} — MultiSlotDataFeed parses sparse/dense
slot text lines into tensors; framework/async_executor.cc runs one trainer
thread per file shard with no Python in the loop;
python/paddle/fluid/data_feed_desc.py, async_executor.py).

TPU-first adaptation: the reference's thread-per-model CPU trainers become
parse workers feeding ONE compiled device step — IO/parse parallelism on
the host, compute on the chip (the executor's compile cache makes each
batch a single XLA call).  Sparse slots become padded [b, max_len] id
tensors + a length vector (the dense replacement for LoD; pair with
sequence ops' Length inputs or is_sparse embeddings).

Text format (data_feed.cc ParseOneInstance): each line holds, for every
slot in desc order, "<n> v1 ... vn" — uint64 ids for sparse slots, floats
for dense ones.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


class Slot:
    __slots__ = ("name", "type", "is_dense", "is_used", "dim", "max_len",
                 "id_space", "_warned")

    def __init__(self, name, type="uint64", is_dense=False, is_used=True,
                 dim=1, max_len=64, id_space=None):
        if type not in ("uint64", "float"):
            raise ValueError(f"slot type must be uint64|float, got {type!r}")
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dim = dim          # dense: values per instance
        self.max_len = max_len  # sparse: pad/truncate length
        # sparse: SET THIS TO THE EMBEDDING TABLE SIZE.  uint64 wire ids
        # are reduced mod id_space ON THE HOST (with jax x64 off, device
        # transfer would silently truncate uint64 -> uint32, corrupting
        # ids >= 2^32).  lookup_table CLAMPS out-of-range ids to the last
        # row (jnp.take mode="clip") rather than wrapping, so ids must
        # arrive already in-range — id_space is the mechanism.  None ->
        # 2^31-1 (int32-transfer-safe only; a one-time warning fires if
        # ids actually needed reducing, since clamp-collapse at the
        # lookup is then likely).
        self.id_space = id_space
        self._warned = False


class DataFeedDesc:
    """Typed slot schema (reference data_feed.proto DataFeedDesc).

        desc = DataFeedDesc(batch_size=32)
        desc.add_slot("click", type="float", is_dense=True, dim=1)
        desc.add_slot("query_ids")          # sparse uint64
    """

    def __init__(self, batch_size: int = 32, name: str = ""):
        self.name = name
        self.batch_size = batch_size
        self.slots: List[Slot] = []

    def add_slot(self, name, **kwargs) -> Slot:
        s = Slot(name, **kwargs)
        self.slots.append(s)
        return s

    def desc_str(self) -> str:
        """Reference-style prototxt rendering (for logs/debugging)."""
        lines = [f'name: "{self.name}"', f"batch_size: {self.batch_size}",
                 "multi_slot_desc {"]
        for s in self.slots:
            lines += ["  slots {", f'    name: "{s.name}"',
                      f'    type: "{s.type}"',
                      f"    is_dense: {str(s.is_dense).lower()}",
                      f"    is_used: {str(s.is_used).lower()}", "  }"]
        lines.append("}")
        return "\n".join(lines)


class MultiSlotDataFeed:
    """Parse MultiSlot text files into feed dicts (reference
    MultiSlotDataFeed data_feed.cc:139,282)."""

    def __init__(self, desc: DataFeedDesc):
        self.desc = desc

    def parse_line(self, line: str) -> Optional[List[np.ndarray]]:
        toks = line.split()
        vals = []
        i = 0
        for slot in self.desc.slots:
            if i >= len(toks):
                return None  # malformed
            n = int(toks[i])
            i += 1
            raw = toks[i:i + n]
            if len(raw) != n:
                return None
            i += n
            if slot.type == "float":
                vals.append(np.asarray(raw, dtype=np.float32))
            else:
                # ids are uint64 on the wire (reference MultiSlot format);
                # np.int64 would OverflowError on hashed ids >= 2^63
                vals.append(np.asarray(raw, dtype=np.uint64))
        return vals

    def _batch_to_feed(self, rows: List[List[np.ndarray]]) -> Dict[str, np.ndarray]:
        feed: Dict[str, np.ndarray] = {}
        for si, slot in enumerate(self.desc.slots):
            if not slot.is_used:
                continue
            cols = [r[si] for r in rows]
            if slot.is_dense:
                arr = np.zeros((len(cols), slot.dim),
                               "float32" if slot.type == "float" else "int64")
                for i, c in enumerate(cols):
                    arr[i, :min(len(c), slot.dim)] = c[:slot.dim]
                feed[slot.name] = arr
            else:
                # padded ids + length vector (dense LoD replacement).
                # Reduce the uint64 wire ids into the table's id space on
                # the HOST: with x64 disabled the device transfer would
                # downcast uint64 -> uint32, silently truncating hashed
                # ids >= 2^32 (round-3 advisor finding).
                space = np.uint64(slot.id_space or 0x7FFFFFFF)
                arr = np.zeros((len(cols), slot.max_len), "int64")
                lens = np.zeros((len(cols),), "int64")
                reduced = False
                for i, c in enumerate(cols):
                    k = min(len(c), slot.max_len)
                    reduced = reduced or bool((c[:k] >= space).any())
                    arr[i, :k] = (c[:k] % space).astype("int64")
                    lens[i] = k
                if reduced and slot.id_space is None and not slot._warned:
                    import warnings

                    warnings.warn(
                        f"MultiSlot slot {slot.name!r}: ids exceeded the "
                        "default id_space (2^31-1) and were reduced mod "
                        "it; lookup_table CLAMPS out-of-range ids, so set "
                        "Slot(id_space=<embedding table size>) to get "
                        "well-distributed in-range ids.")
                    slot._warned = True
                feed[slot.name] = arr
                feed[slot.name + "__len"] = lens
        return feed

    def read_file(self, path: str):
        """Yield batched feed dicts from one file."""
        rows: List[List[np.ndarray]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = self.parse_line(line)
                if r is None:
                    raise ValueError(
                        f"malformed MultiSlot line in {path}: {line[:80]!r}")
                rows.append(r)
                if len(rows) == self.desc.batch_size:
                    yield self._batch_to_feed(rows)
                    rows = []
        if rows:
            yield self._batch_to_feed(rows)


class AsyncExecutor:
    """File-list trainer (reference async_executor.{h,cc} RunFromFile +
    ExecutorThreadWorker::TrainFiles): `thread_num` parse workers stream
    batches from their file shards into a bounded queue; the device
    consumes them through one compiled step."""

    def __init__(self, place=None):
        from .core.executor import CPUPlace, Executor

        self.executor = Executor(place or CPUPlace())

    def run_from_files(
        self,
        program,
        data_feed_desc: DataFeedDesc,
        filelist: Sequence[str],
        thread_num: int = 2,
        fetch_list=None,
        scope=None,
        queue_capacity: int = 8,
    ) -> List[List[float]]:
        """Train over every batch in `filelist`; returns the fetch values
        per batch (floats for scalar fetches)."""
        feed_parser = MultiSlotDataFeed(data_feed_desc)
        q: "queue.Queue" = queue.Queue(maxsize=queue_capacity)
        end = object()

        class _Err:
            def __init__(self, exc):
                self.exc = exc

        thread_num = max(1, min(thread_num, len(filelist)))

        def worker(shard: List[str]):
            try:
                for path in shard:
                    for feed in feed_parser.read_file(path):
                        q.put(feed)
            except BaseException as e:
                # promptly surfaced: the consumer stops at the NEXT batch
                # instead of silently training through a full pass and
                # discarding every result at the end
                q.put(_Err(e))
            finally:
                q.put(end)

        shards = [list(filelist[i::thread_num]) for i in range(thread_num)]
        threads = [
            threading.Thread(target=worker, args=(s,), daemon=True)
            for s in shards
        ]
        for t in threads:
            t.start()

        results: List[List[float]] = []
        done = 0
        while done < len(threads):
            item = q.get()
            if item is end:
                done += 1
                continue
            if isinstance(item, _Err):
                raise item.exc
            outs = self.executor.run(
                program, feed=item, fetch_list=fetch_list, scope=scope)
            results.append([float(np.asarray(o).reshape(-1)[0])
                            if np.asarray(o).size == 1 else np.asarray(o)
                            for o in outs])
        return results
