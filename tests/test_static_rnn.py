"""StaticRNN / DynamicRNN DSL (reference: control_flow.py StaticRNN /
DynamicRNN, recurrent_op.cc:39 — here one lax.scan per RNN)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr

rng = np.random.RandomState(23)


def test_static_rnn_matches_manual_recurrence():
    b, t, d, h = 3, 5, 4, 6
    x = layers.data(name="x", shape=[t, d], dtype="float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[h], batch_ref=word)
        hidden = layers.fc(
            layers.concat([word, prev], axis=1), size=h, act="tanh",
            param_attr=ParamAttr(name="rnn_w"),
            bias_attr=ParamAttr(name="rnn_b"))
        rnn.update_memory(prev, hidden)
        rnn.step_output(hidden)
    out = rnn()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    xv = rng.randn(b, t, d).astype("float32")
    (o,) = exe.run(feed={"x": xv}, fetch_list=[out])
    o = np.asarray(o)
    assert o.shape == (b, t, h)

    w = np.asarray(pt.global_scope().find_var("rnn_w"))
    bias = np.asarray(pt.global_scope().find_var("rnn_b"))
    state = np.zeros((b, h), "float32")
    for i in range(t):
        state = np.tanh(
            np.concatenate([xv[:, i], state], axis=1) @ w + bias)
        np.testing.assert_allclose(o[:, i], state, rtol=1e-4, atol=1e-5)


def test_static_rnn_trains_through_scan():
    """Grads must flow into step params: learn to sum a sequence."""
    b, t, d = 16, 6, 3
    x = layers.data(name="x", shape=[t, d], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        acc = rnn.memory(shape=[1], batch_ref=word)
        nxt = layers.elementwise_add(
            acc, layers.fc(word, size=1, bias_attr=False,
                           param_attr=ParamAttr(name="sum_w")))
        rnn.update_memory(acc, nxt)
        rnn.step_output(nxt)
    out = rnn()  # [b, t, 1]
    last = layers.slice(out, axes=[1], starts=[t - 1], ends=[t])
    loss = layers.mean(layers.square(layers.reshape(last, [-1, 1]) - y))
    pt.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(120):
        xv = rng.randn(b, t, d).astype("float32")
        yv = xv.sum(axis=(1, 2), keepdims=False)[:, None].astype("float32")
        (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
    # the learned weight should approximate all-ones (summing)
    w = np.asarray(pt.global_scope().find_var("sum_w"))
    np.testing.assert_allclose(w, np.ones_like(w), atol=0.2)


def test_dynamic_rnn_masks_by_length():
    b, t, d = 3, 6, 2
    x = layers.data(name="x", shape=[t, d], dtype="float32")
    ln = layers.data(name="len", shape=[1], dtype="int64")
    rnn = layers.DynamicRNN(seq_len=ln)
    with rnn.block():
        word = rnn.step_input(x)
        acc = rnn.memory(shape=[d], batch_ref=word)
        nxt = layers.elementwise_add(acc, word)
        rnn.update_memory(acc, nxt)
        rnn.step_output(nxt)
    out = rnn()
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((b, t, d), "float32")
    lv = np.array([6, 3, 1], "int64")
    (o,) = exe.run(feed={"x": xv, "len": lv}, fetch_list=[out])
    o = np.asarray(o)
    # running sum freezes at each sequence's length; outputs zero past it
    np.testing.assert_allclose(o[0, :, 0], [1, 2, 3, 4, 5, 6])
    np.testing.assert_allclose(o[1, :, 0], [1, 2, 3, 0, 0, 0])
    np.testing.assert_allclose(o[2, :, 0], [1, 0, 0, 0, 0, 0])


def test_dynamic_lstmp_shapes_and_training():
    """LSTM with recurrent projection (reference lstmp_op.cc): projection
    output drives the recurrence; trains end-to-end."""
    b, t, h, p = 8, 6, 16, 8
    x = layers.data(name="x", shape=[t, 5], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    proj_in = layers.fc(x, size=4 * h, num_flatten_dims=2, bias_attr=False)
    proj, cell = layers.dynamic_lstmp(proj_in, size=4 * h, proj_size=p)
    pooled = layers.sequence_pool(proj, "last")
    pred = layers.fc(pooled, size=1)
    loss = layers.mean(layers.square(pred - y))
    pt.optimizer.AdamOptimizer(learning_rate=0.02).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(40):
        xv = rng.randn(b, t, 5).astype("float32")
        yv = xv.sum(axis=(1, 2), keepdims=False)[:, None].astype(
            "float32") * 0.1
        (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    # shapes: projection [b, t, p], cell [b, t, h]
    res = exe.run(feed={"x": xv, "y": yv}, fetch_list=[proj, cell])
    assert np.asarray(res[0]).shape == (b, t, p)
    assert np.asarray(res[1]).shape == (b, t, h)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
