"""Memory-optimization tier: static HBM planner + graph-level memory
rewrites (the Fluid memory_optimization transpiler class, rebuilt for
XLA — PAPER.md's "memory optimization" transpiler bullet).

  * `planner`   — static liveness analysis over the Program IR: per-op
    live sets, peak watermark, per-var lifetime table, footprint split
    (params / opt state / activations / workspace), cross-checked
    against `compiled.memory_analysis()` ground truth.
  * `recompute` — activation-recompute (gradient checkpointing) pass:
    segment forwards re-run in front of their grad ops instead of
    stashing intermediates; FLAGS_recompute.
  * `offload`   — host offload for long-lived stash vars via paired
    memcpy_d2h/memcpy_h2d ops at liveness edges;
    FLAGS_offload_activations.
"""

from .planner import (  # noqa: F401
    CLASSES,
    MemoryPlan,
    PLANNER_XLA_TOLERANCE,
    VarLife,
    plan_accumulated,
    plan_program,
    plan_stages,
    publish_plan,
    var_bytes,
    xla_cross_check,
    xla_memory_stats,
)
from .recompute import (  # noqa: F401
    RecomputeError,
    apply_recompute,
    auto_checkpoints,
    maybe_optimize_memory,
)
from .offload import apply_offload, select_offload_vars  # noqa: F401
