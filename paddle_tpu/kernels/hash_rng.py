"""Counter-based hash PRNG for dropout masks.

The reference generates dropout masks with a stateful curand/std::mt19937
stream per op (dropout_op.cc, dropout_op.cu) and SAVES the mask as a
fwd->bwd residual.  Our round-4 design already regenerates the mask in the
backward from a static per-op rng id; this module replaces the underlying
generator (`jax.random.bernoulli` over an rbg key) with a stateless
counter-based integer hash:

    bits(i) = lowbias32(i * GOLDEN + seed)         (uint32 avalanche hash)
    keep(i) = bits(i) >= floor(rate * 2^32)

Why this beats a keyed RNG here:

  * **Fusible.** It is ~10 integer vector ops over an iota — XLA fuses it
    straight into the consuming select/multiply, so no random-bits tensor
    is ever materialized in HBM (the rbg `rng-bit-generator` HLO is a
    fusion barrier; the bits round-trip through HBM at every dropout
    site — measured at ~2.5 ms/step on transformer-base).
  * **Identical everywhere.** Plain `jnp` integer ops run unchanged inside
    a Pallas kernel, under `interpret=True`, and in the XLA graph — so an
    in-kernel dropout (flash attention) and its pure-XLA fallback produce
    the SAME mask from the same (seed, element-index), and backward
    kernels regenerate the forward's mask exactly.
  * **Sharding-invariant.** The mask is a pure function of the global
    element index; GSPMD partitioning of the iota cannot change it.

The generator is NOT cryptographic; lowbias32 (a public-domain 32-bit
avalanche constant set) is far beyond what dropout needs statistically
(see tests/test_hash_rng.py: mean/variance/chi-square and independence
across sites/steps).
"""

from __future__ import annotations

GOLDEN = 0x9E3779B9  # 2^32 / phi, odd — idx*GOLDEN is a bijection mod 2^32


def mix32(x):
    """lowbias32 avalanche finalizer over a uint32 array.

    Constants are np.uint32 (NOT jnp.uint32): numpy scalars inline as
    jaxpr literals, while jax Arrays become constvars — and a Pallas
    kernel jaxpr with constvars refuses to lower."""
    import jax.numpy as jnp
    import numpy as np

    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def keep_threshold(rate: float) -> int:
    """uint32 threshold such that P(bits >= thr) = 1 - rate."""
    t = int(round(float(rate) * 4294967296.0))
    return max(0, min(t, 0xFFFFFFFF))


def seed_from_key(key, rng_id: int):
    """Derive a per-(step, site) uint32 scalar seed from a jax PRNG key.

    `key` is the executor's per-step base key (any impl); `rng_id` the
    static per-op stream id.  Returns a traced uint32 scalar."""
    import jax
    import jax.numpy as jnp

    kd = jax.random.key_data(key).reshape(-1).astype(jnp.uint32)
    site = mix32(jnp.uint32(rng_id & 0xFFFFFFFF))
    return (kd[0] * jnp.uint32(GOLDEN) + kd[-1]) ^ site


def keep_mask(seed, shape, rate: float, base_index: int = 0):
    """Boolean keep-mask of `shape`: True with probability 1 - rate.

    seed: traced uint32 scalar (see seed_from_key).  base_index offsets the
    flat element index (for tiled/blocked generation: pass the tile's global
    flat offset so tiles of one logical tensor never overlap streams)."""
    import jax
    import jax.numpy as jnp

    import numpy as np

    n = 1
    for s in shape:
        n *= int(s)
    idx = jax.lax.iota(jnp.uint32, n).reshape(shape)
    if base_index:
        idx = idx + np.uint32(base_index & 0xFFFFFFFF)
    bits = mix32(idx * np.uint32(GOLDEN) + seed.astype(jnp.uint32))
    return bits >= np.uint32(keep_threshold(rate))


def keep_mask_tile(seed, global_idx, rate: float, fast: bool = False):
    """keep-mask from explicit global element indices (uint32 array) —
    the in-kernel form: build `global_idx` from grid/iota coordinates so a
    backward kernel walking a different grid regenerates identical bits.
    fast=True uses the cheaper mix32_fast (attention-weights masks)."""
    import jax.numpy as jnp
    import numpy as np

    mixer = mix32_fast if fast else mix32
    bits = mixer(global_idx.astype(jnp.uint32) * np.uint32(GOLDEN)
                 + seed.astype(jnp.uint32))
    return bits >= np.uint32(keep_threshold(rate))


def mix32_fast(x):
    """Cheaper 2-round mixer for the in-kernel attention-dropout masks:
    one multiply + two xor-shifts (vs lowbias32's two multiplies + three).
    The threshold compare consumes all 32 bits, and the per-head seed is
    already avalanche-mixed (attn_head_seed uses full mix32), so the
    per-element mixing only needs to decorrelate neighboring indices —
    the O(T²·H) hash regenerated in three flash kernels is the measured
    cost of in-kernel weights-dropout, so every op counts."""
    import jax.numpy as jnp
    import numpy as np

    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    return x


def attn_head_seed(seed, bh_idx):
    """Per-(batch*head) derived seed for attention-weights dropout.

    Attention masks index a [b*h, Tq, Tk] space that can exceed 2^32
    elements (e.g. b=4, h=16, T=16k) — a single flat uint32 index would
    wrap and silently correlate mask bits.  Instead the (b*h) coordinate
    is folded into the seed and the in-plane index q*Tk + k (exact for
    T <= 65535) keys the hash.  Used by the Pallas kernels and the
    pure-XLA fallback identically."""
    import jax.numpy as jnp
    import numpy as np

    return mix32(seed.astype(jnp.uint32)
                 + bh_idx.astype(jnp.uint32) * np.uint32(GOLDEN))


def keep_mask_attn(seed, shape, rate: float):
    """Attention-weights keep-mask over a full [b, h, tq, tk] array —
    the pure-XLA counterpart of the kernels' _keep_tile: bit-identical
    masks from (seed, b*h, q, k).

    Raises when tq*tk > 2^32 (max in-plane index tq*tk - 1 no longer
    fits uint32): the index q*tk + k would wrap and silently correlate
    mask bits between distant rows (the failure mode attn_head_seed
    exists to avoid on the b*h axis).  At such lengths apply dropout at
    the attention OUTPUT site instead."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    b, h, tq, tk = shape
    if int(tq) * int(tk) > 2 ** 32:
        raise ValueError(
            f"keep_mask_attn: mask plane tq*tk = {tq}*{tk} > 2^32 wraps "
            "the uint32 hash index and correlates mask bits; use "
            "output-site dropout for sequences this long")
    u32 = jnp.uint32
    bh = (jax.lax.broadcasted_iota(u32, shape, 0) * np.uint32(h)
          + jax.lax.broadcasted_iota(u32, shape, 1))
    q_idx = jax.lax.broadcasted_iota(u32, shape, 2)
    k_idx = jax.lax.broadcasted_iota(u32, shape, 3)
    hseed = attn_head_seed(seed, bh)
    return keep_mask_tile(hseed, q_idx * np.uint32(tk) + k_idx, rate,
                          fast=True)
