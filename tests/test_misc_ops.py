"""Op tests for the misc op family (ops/misc_ops.py): output parity with
numpy references + numeric grad checks for the differentiable ones.
Mirrors the reference's per-op unittests (tests/unittests/test_rank_loss_op.py,
test_smooth_l1_loss_op.py, test_cos_sim_op.py, ...)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import registry

from op_test import OpTest


rng = np.random.RandomState(7)


def f32(*shape):
    return rng.uniform(-1, 1, shape).astype("float32")


class TestRankLoss(OpTest):
    op_type = "rank_loss"

    def test_output_and_grad(self):
        left, right = f32(5, 1), f32(5, 1)
        label = (rng.rand(5, 1) > 0.5).astype("float32")
        d = left - right
        expected = np.log1p(np.exp(d)) - label * d
        self.check_output(
            {"Left": left, "Right": right, "Label": label}, {"Out": expected}
        )
        self.check_grad(
            {"Left": left, "Right": right, "Label": label},
            {"Out": ["out"]},
            ["Left", "Right"],
        )


class TestModifiedHuberLoss(OpTest):
    op_type = "modified_huber_loss"

    def test_output(self):
        x = f32(6, 1) * 2
        y = (rng.rand(6, 1) > 0.5).astype("float32")
        val = x * (2 * y - 1)
        expected = np.where(
            val < -1, -4.0 * val, np.where(val < 1, (1 - val) ** 2, 0.0)
        ).astype("float32")
        self.check_output(
            {"X": x, "Y": y},
            {"Out": [("out", expected)], "IntermediateVal": [("ival", val)]},
        )


class TestTeacherStudentSigmoidLoss(OpTest):
    op_type = "teacher_student_sigmoid_loss"

    def test_output(self):
        x = f32(8, 1)
        label = np.array(
            [[-2.0], [-0.5], [0.3], [0.9], [1.2], [1.8], [0.0], [1.0]],
            dtype="float32",
        )
        base = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
        expected = np.where(
            label < -1.0, base,
            np.where(
                label < 0.0, base - x,
                np.where(
                    label < 1.0, 2 * base - x * label,
                    2 * base - x - x * (label - 1.0),
                ),
            ),
        )
        self.check_output({"X": x, "Label": label}, {"Y": expected})


class TestSmoothL1Loss(OpTest):
    op_type = "smooth_l1_loss"
    attrs = {"sigma": 2.0}

    def test_output_and_grad(self):
        x, y = f32(4, 6), f32(4, 6)
        d = x - y
        s2 = 4.0
        ad = np.abs(d)
        elem = np.where(ad < 1 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
        expected = elem.reshape(4, -1).sum(axis=1, keepdims=True)
        self.check_output(
            {"X": x, "Y": y},
            {"Out": [("out", expected)], "Diff": [("diff", d)]},
        )
        self.check_grad(
            {"X": x, "Y": y},
            {"Out": ["out"], "Diff": ["diff"]},
            ["X"],
            loss_slot="Out",
        )


class TestSquaredL2Distance(OpTest):
    op_type = "squared_l2_distance"

    def test_output(self):
        x, y = f32(5, 4), f32(5, 4)
        sub = x - y
        self.check_output(
            {"X": x, "Y": y},
            {
                "Out": [("out", (sub ** 2).sum(axis=1, keepdims=True))],
                "sub_result": [("sub", sub)],
            },
        )


class TestCosSim(OpTest):
    op_type = "cos_sim"

    def test_output_and_grad(self):
        x, y = f32(4, 5) + 1.5, f32(4, 5) + 1.5
        xn = np.sqrt((x ** 2).sum(axis=1, keepdims=True))
        yn = np.sqrt((y ** 2).sum(axis=1, keepdims=True))
        expected = (x * y).sum(axis=1, keepdims=True) / (xn * yn)
        self.check_output(
            {"X": x, "Y": y},
            {"Out": [("out", expected)], "XNorm": [("xn", xn)],
             "YNorm": [("yn", yn)]},
        )
        self.check_grad(
            {"X": x, "Y": y},
            {"Out": ["out"], "XNorm": ["xn"], "YNorm": ["yn"]},
            ["X", "Y"],
            loss_slot="Out",
        )


class TestL1Norm(OpTest):
    op_type = "l1_norm"

    def test_output_and_grad(self):
        x = f32(3, 4)
        self.check_output({"X": x}, {"Out": np.abs(x).sum().reshape(1)})
        self.check_grad({"X": x + 0.3}, {"Out": ["out"]}, ["X"])


class TestSelu(OpTest):
    op_type = "selu"

    def test_output_and_grad(self):
        x = f32(4, 5) * 2
        scale, alpha = 1.0507009873554805, 1.6732632423543772
        expected = scale * np.where(x > 0, x, alpha * (np.exp(x) - 1))
        self.check_output({"X": x}, {"Out": expected})
        # keep x away from the kink at 0 for finite differences
        x2 = np.where(np.abs(x) < 0.05, 0.2, x).astype("float32")
        self.check_grad({"X": x2}, {"Out": ["out"]}, ["X"])


class TestSignMinus(OpTest):
    def test_sign(self):
        self.op_type = "sign"
        x = f32(3, 4)
        self.check_output({"X": x}, {"Out": np.sign(x)})

    def test_minus(self):
        self.op_type = "minus"
        x, y = f32(3, 4), f32(3, 4)
        self.check_output({"X": x, "Y": y}, {"Out": x - y})
        self.check_grad({"X": x, "Y": y}, {"Out": ["out"]}, ["X", "Y"])


class TestLabelSmooth(OpTest):
    op_type = "label_smooth"
    attrs = {"epsilon": 0.1}

    def test_uniform_prior(self):
        x = np.eye(4, dtype="float32")[[0, 2, 3]]
        expected = 0.9 * x + 0.1 / 4
        self.check_output({"X": x}, {"Out": expected})

    def test_explicit_prior(self):
        x = np.eye(4, dtype="float32")[[1, 3]]
        prior = np.array([0.1, 0.2, 0.3, 0.4], dtype="float32")
        expected = 0.9 * x + 0.1 * prior[None]
        self.check_output(
            {"X": x, "PriorDist": prior}, {"Out": expected}
        )


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def test_output(self):
        a, b, c = f32(4, 3), f32(4, 3), f32(4, 3)
        ids = np.array([[0], [2], [1], [0]], dtype="int32")
        expected = np.stack([a[0], c[1], b[2], a[3]])
        self.check_output(
            {"X": [("a", a), ("b", b), ("c", c)], "Ids": ids},
            {"Out": expected},
        )


class TestAffineChannel(OpTest):
    op_type = "affine_channel"

    def test_nchw(self):
        x = f32(2, 3, 4, 4)
        scale, bias = f32(3), f32(3)
        expected = x * scale[None, :, None, None] + bias[None, :, None, None]
        self.check_output(
            {"X": x, "Scale": scale, "Bias": bias}, {"Out": expected},
            attrs={"data_layout": "NCHW"},
        )

    def test_nhwc(self):
        x = f32(2, 4, 4, 3)
        scale, bias = f32(3), f32(3)
        expected = x * scale[None, None, None, :] + bias[None, None, None, :]
        self.check_output(
            {"X": x, "Scale": scale, "Bias": bias}, {"Out": expected},
            attrs={"data_layout": "NHWC"},
        )


class TestDataNorm(OpTest):
    op_type = "data_norm"
    attrs = {"epsilon": 1e-4}

    def test_output(self):
        x = f32(6, 3)
        bsize = np.full(3, 10.0, dtype="float32")
        bsum = f32(3) * 5
        bsq = np.abs(f32(3)) * 20 + 10
        mean = bsum / bsize
        scale = np.sqrt(bsize / (bsq - bsum * mean + 1e-4 * bsize))
        expected = (x - mean[None]) * scale[None]
        self.check_output(
            {"X": x, "BatchSize": bsize, "BatchSum": bsum,
             "BatchSquareSum": bsq},
            {
                "Y": [("y", expected)],
                "Means": [("m", mean)],
                "Scales": [("s", scale)],
                "BatchSizeOut": [("bso", bsize + 6)],
                "BatchSumOut": [("bsumo", bsum + x.sum(axis=0))],
                "BatchSquareSumOut": [("bsqo", bsq + (x ** 2).sum(axis=0))],
            },
            rtol=1e-4,
        )


class TestFillOps(OpTest):
    def test_fill(self):
        self.op_type = "fill"
        expected = np.arange(6, dtype="float32").reshape(2, 3)
        self.check_output(
            {},
            {"Out": expected},
            attrs={"shape": [2, 3], "value": list(range(6)),
                   "dtype": "float32"},
        )

    def test_fill_constant_batch_size_like(self):
        self.op_type = "fill_constant_batch_size_like"
        x = f32(5, 2)
        self.check_output(
            {"Input": x},
            {"Out": np.full((5, 7), 3.5, dtype="float32")},
            attrs={"shape": [-1, 7], "value": 3.5, "dtype": "float32",
                   "input_dim_idx": 0, "output_dim_idx": 0},
        )

    def test_int64_requests_do_not_warn(self):
        """int64 fill requests with x64 off must clamp through jax's
        canonical dtype (-> int32) EXPLICITLY — not truncate-and-warn on
        every trace (the bench-visible UserWarning; ISSUE 4 satellite)."""
        import warnings

        from paddle_tpu import layers

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[3], dtype="float32")
            f = layers.tensor.fill_constant_batch_size_like(
                x, [-1, 4], "int64", 7)
            out = layers.reduce_sum(layers.cast(f, "float32"))
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            (val,) = exe.run(prog, feed={"x": np.zeros((2, 3), "float32")},
                             fetch_list=[out], scope=scope)
            trunc = [str(m.message) for m in w
                     if "truncated" in str(m.message)]
        assert not trunc, trunc
        assert float(np.asarray(val)) == 2 * 4 * 7


class TestCrop(OpTest):
    op_type = "crop"

    def test_static_attrs(self):
        x = f32(4, 5)
        self.check_output(
            {"X": x},
            {"Out": x[1:3, 2:5]},
            attrs={"shape": [2, 3], "offsets": [1, 2]},
        )


class TestIsEmpty(OpTest):
    op_type = "is_empty"

    def test_nonempty(self):
        self.check_output({"X": f32(2, 2)}, {"Out": np.array([False])})


class TestMeanIou(OpTest):
    op_type = "mean_iou"
    attrs = {"num_classes": 3}

    def test_output(self):
        pred = np.array([0, 1, 2, 1, 0, 2], dtype="int32")
        label = np.array([0, 1, 1, 1, 2, 2], dtype="int32")
        n = 3
        cm = np.zeros((n, n), dtype=np.int64)
        for p, l in zip(pred, label):
            cm[l, p] += 1
        inter = np.diag(cm).astype("float64")
        union = cm.sum(0) + cm.sum(1) - inter
        valid = union > 0
        miou = np.where(valid, inter / np.maximum(union, 1), 0).sum() / valid.sum()
        self.check_output(
            {"Predictions": pred, "Labels": label},
            {"OutMeanIou": [("iou", np.float32(miou))],
             "OutWrong": [("w", (cm.sum(1) - np.diag(cm)).astype("int32"))],
             "OutCorrect": [("c", np.diag(cm).astype("int32"))]},
        )


class TestFsp(OpTest):
    op_type = "fsp"

    def test_output_and_grad(self):
        x, y = f32(2, 3, 2, 2), f32(2, 4, 2, 2)
        xf = x.reshape(2, 3, 4)
        yf = y.reshape(2, 4, 4)
        expected = np.einsum("nch,ndh->ncd", xf, yf) / 4.0
        self.check_output({"X": x, "Y": y}, {"Out": expected})
        self.check_grad({"X": x, "Y": y}, {"Out": ["out"]}, ["X", "Y"])


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def test_output_and_grad(self):
        x, y = f32(2, 5), f32(2, 3)
        b, w = x.shape
        m = y.shape[1]
        expected = np.zeros_like(x)
        for i in range(b):
            for j in range(w):
                for k in range(m):
                    expected[i, j] += x[i, (j + k - m // 2) % w] * y[i, k]
        self.check_output({"X": x, "Y": y}, {"Out": expected})
        self.check_grad({"X": x, "Y": y}, {"Out": ["out"]}, ["X", "Y"])


class TestBilinearTensorProduct(OpTest):
    op_type = "bilinear_tensor_product"

    def test_output_and_grad(self):
        x, y, w = f32(3, 2), f32(3, 4), f32(5, 2, 4)
        bias = f32(1, 5)
        expected = np.einsum("bi,kij,bj->bk", x, w, y) + bias
        self.check_output(
            {"X": x, "Y": y, "Weight": w, "Bias": bias}, {"Out": expected}
        )
        self.check_grad(
            {"X": x, "Y": y, "Weight": w, "Bias": bias},
            {"Out": ["out"]},
            ["X", "Weight"],
        )


class TestAddPositionEncoding(OpTest):
    op_type = "add_position_encoding"
    attrs = {"alpha": 0.5, "beta": 2.0}

    def test_output(self):
        x = f32(2, 3, 4)
        t, d = 3, 4
        pos = np.arange(t, dtype="float64")[:, None]
        dim = np.arange(d // 2, dtype="float64")[None, :]
        div = np.power(10000.0, 2.0 * dim / d)
        enc = np.zeros((t, d))
        enc[:, 0::2] = np.sin(pos / div)
        enc[:, 1::2] = np.cos(pos / div)
        expected = 0.5 * x + 2.0 * enc[None].astype("float32")
        self.check_output({"X": x}, {"Out": expected}, rtol=1e-4)


class TestSimilarityFocus(OpTest):
    op_type = "similarity_focus"
    attrs = {"axis": 1, "indexes": [0]}

    def test_output(self):
        x = f32(1, 2, 3, 3)
        ch = x[0, 0]
        row_max = ch == ch.max(axis=1, keepdims=True)
        col_max = ch == ch.max(axis=0, keepdims=True)
        m = (row_max | col_max).astype("float32")
        expected = np.broadcast_to(m[None, None], x.shape).copy()
        self.check_output({"X": x}, {"Out": expected})


class TestShardIndex(OpTest):
    op_type = "shard_index"
    attrs = {"index_num": 20, "nshards": 2, "shard_id": 0,
             "ignore_value": -1}

    def test_output(self):
        x = np.array([[1], [9], [10], [19]], dtype="int64")
        expected = np.array([[1], [9], [-1], [-1]], dtype="int64")
        self.check_output({"X": x}, {"Out": expected})


class TestUnpool(OpTest):
    op_type = "unpool"
    attrs = {"ksize": [2, 2]}

    def test_output(self):
        x = np.array([[[[1.0, 2.0], [3.0, 4.0]]]], dtype="float32")
        idx = np.array([[[[0, 7], [9, 15]]]], dtype="int32")
        expected = np.zeros((1, 1, 4, 4), dtype="float32")
        for v, i in zip([1, 2, 3, 4], [0, 7, 9, 15]):
            expected[0, 0, i // 4, i % 4] = v
        self.check_output({"X": x, "Indices": idx}, {"Out": expected})


def test_selected_rows_ops_direct():
    """get_tensor_from_selected_rows / merge_selected_rows operate on
    SelectedRows values — exercised at the lowering level (the feed path is
    dense-only, matching the reference where these appear mid-graph)."""
    import jax.numpy as jnp

    from paddle_tpu.core.selected_rows import SelectedRows

    ids = jnp.array([3, 1, 3], dtype=jnp.int32)
    rows = jnp.array([[1.0, 1.0], [2.0, 2.0], [4.0, 4.0]])
    sr = SelectedRows(ids, rows, height=6)

    out = registry.get("get_tensor_from_selected_rows").lower(
        _ctx(), {"X": [sr]}
    )["Out"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(rows))

    merged = registry.get("merge_selected_rows").lower(_ctx(), {"X": [sr]})[
        "Out"
    ][0]
    assert isinstance(merged, SelectedRows)
    got = {int(i): np.asarray(r) for i, r in zip(merged.ids, merged.rows)
           if int(i) >= 0}
    np.testing.assert_allclose(got[3], [5.0, 5.0])
    np.testing.assert_allclose(got[1], [2.0, 2.0])


def _ctx():
    class _C:
        attrs = {}

        def attr(self, name, default=None):
            return default

    return _C()


def test_misc_ops_all_registered():
    """Every op in misc_ops is importable through the package registry
    (regression for the round-2 dead-code finding)."""
    for op in [
        "rank_loss", "modified_huber_loss", "teacher_student_sigmoid_loss",
        "smooth_l1_loss", "squared_l2_distance", "cos_sim", "l1_norm",
        "selu", "sign", "minus", "label_smooth", "multiplex",
        "affine_channel", "data_norm", "fill",
        "fill_constant_batch_size_like", "crop", "is_empty", "mean_iou",
        "fsp", "conv_shift", "bilinear_tensor_product",
        "add_position_encoding", "similarity_focus",
        "get_tensor_from_selected_rows", "merge_selected_rows",
        "shard_index", "unpool",
    ]:
        assert registry.lookup(op) is not None, op


def test_misc_layer_wrappers():
    """Layer-level smoke: the nn.py wrappers build and run."""
    import paddle_tpu.layers as layers
    from paddle_tpu.core import framework as fw

    prog, startup = fw.Program(), fw.Program()
    with fw.program_guard(prog, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[4], dtype="float32")
        sim = layers.cos_sim(x, y)
        sl1 = layers.smooth_l1(x, y)
        act = layers.selu(x)
        pe_in = layers.data(name="p", shape=[3, 4], dtype="float32")
        pe = layers.add_position_encoding(pe_in, alpha=1.0, beta=1.0)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    res = exe.run(
        prog,
        feed={
            "x": f32(2, 4) + 1.2,
            "y": f32(2, 4) + 1.2,
            "p": f32(2, 3, 4),
        },
        fetch_list=[sim, sl1, act, pe],
    )
    assert all(np.asarray(r).size for r in res)
