"""DEPRECATED alias — folded into kernels/conv_bn.py (PR r07).

This module was the r05 "Pallas matmul + per-column statistics" experiment
(measured negative result: XLA's plain dot beat it by 35-50% at the ResNet
1x1 K=64/128 shapes, and lowering 1x1 convs as dots collapsed end-to-end
throughput 2521 -> 1412 img/s on layout duals — PERF.md round-5).  Its
measured cost model and the fused-stats idea now live in conv_bn.py, whose
dot_col_stats kernel keeps the filter in ONE [C_out, C_in] orientation for
forward and backward (the fix for the r05 collapse) and whose
conv_bn_stats/bn_apply pair is the shipping fused-BN path.

`matmul_col_stats` is re-exported for the r05 record and existing callers;
new code should use conv_bn.dot_col_stats / conv_bn.conv_bn_stats.
"""

from __future__ import annotations

from .conv_bn import dot_col_stats, matmul_col_stats  # noqa: F401
