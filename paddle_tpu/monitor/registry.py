"""Metrics registry: counters, gauges, bounded-bucket histograms.

Prometheus-inspired but dependency-free; metric names are dotted strings
("executor.cache_miss") which the Prometheus exposition sanitizes to
underscore form.  All mutation goes through per-metric locks so parse
workers / serving threads can hammer the same counter safely (the GIL makes
`+=` *mostly* atomic in CPython, but "mostly" is not a contract).

The registry itself is intentionally always-on and cheap; the FLAGS.monitor
gate lives at the instrumentation call-sites (executor, data_feed,
inference, collectives) so the hot paths skip even the helper call when
telemetry is off.
"""

from __future__ import annotations

import bisect
import collections
import json
import threading
import time
from typing import Dict, List, Optional, Sequence


def enabled() -> bool:
    """Whether telemetry call-sites should write (the FLAGS.monitor gate)."""
    from ..flags import FLAGS

    return FLAGS.monitor


# latency-flavored default buckets (seconds): 100us .. 30s, bounded
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class Counter:
    """Monotonically increasing float counter."""

    kind = "counter"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError(f"counter {self.name!r}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"metric": self.name, "type": self.kind, "value": self._value}


class Gauge:
    """Instantaneous value (queue depth, last loss, ...)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"metric": self.name, "type": self.kind, "value": self._value}


class Histogram:
    """Fixed-bucket histogram (bounded memory: len(buckets)+1 counts).

    `buckets` are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail.  Exposition is cumulative (Prometheus `le` form).
    """

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum",
                 "_count", "_max")

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 help: str = ""):
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(bs):
            raise ValueError(
                f"histogram {name!r}: buckets must be ascending, got {bs}")
        self.name = name
        self.help = help
        self.buckets = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # +1: the +Inf tail
        self._sum = 0.0
        self._count = 0
        self._max = 0.0  # largest observed value (the +Inf bucket's clamp)

    def observe(self, v: float):
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        """Largest observed value (0.0 before any observation)."""
        return self._max

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile estimate (the upper bound of the
        bucket holding the q-th observation, Prometheus histogram_quantile
        style).  Returns None with no observations.  The +Inf tail bucket
        clamps to the LARGEST OBSERVED value instead of returning inf —
        a single outlier past the top bound must not make a p99 report
        `inf` in /v1/models info."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
            vmax = self._max
        if total == 0:
            return None
        target = q * total
        cum = 0
        for le, c in zip(self.buckets + (float("inf"),), counts):
            cum += c
            if cum >= target:
                return vmax if le == float("inf") else le
        return vmax

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s, vmax = self._count, self._sum, self._max
        cum, cum_counts = 0, []
        for le, c in zip(self.buckets + (float("inf"),), counts):
            cum += c
            cum_counts.append([le, cum])
        return {"metric": self.name, "type": self.kind, "count": total,
                "sum": s, "max": vmax, "buckets": cum_counts}


class SloTracker:
    """Good/bad SLO event accounting behind the serving burn-rate gauges.

    A request is GOOD when it completed inside its latency objective, BAD
    when it missed it, errored, or was shed.  Events land in coarse
    fixed-width time buckets (bounded memory: one [start, good, bad] row
    per BUCKET_S over the horizon), so the multi-window burn rates the
    SRE playbook asks for — observed bad fraction over the window divided
    by the error budget (1 - target); 1.0 means burning the budget
    exactly at the sustainable rate — come from one deque walk at scrape
    time, not a per-request histogram."""

    BUCKET_S = 10.0

    __slots__ = ("name", "objective_ms", "target", "_lock", "_buckets",
                 "good_total", "bad_total")

    def __init__(self, name: str, objective_ms: float,
                 target: float = 0.999, horizon_s: float = 3600.0):
        if not 0.0 < target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got {target}")
        self.name = name
        self.objective_ms = float(objective_ms)
        self.target = float(target)
        self._lock = threading.Lock()
        self._buckets: "collections.deque" = collections.deque(
            maxlen=int(horizon_s / self.BUCKET_S) + 2)
        self.good_total = 0
        self.bad_total = 0

    def observe(self, good: bool, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        start = now - (now % self.BUCKET_S)
        with self._lock:
            if not self._buckets or self._buckets[-1][0] != start:
                self._buckets.append([start, 0, 0])
            self._buckets[-1][1 if good else 2] += 1
            if good:
                self.good_total += 1
            else:
                self.bad_total += 1

    def window_counts(self, window_s: float,
                      now: Optional[float] = None) -> tuple:
        """(good, bad) over the trailing window (bucket resolution)."""
        now = time.time() if now is None else now
        cut = now - float(window_s)
        good = bad = 0
        with self._lock:
            for start, g, b in self._buckets:
                if start + self.BUCKET_S > cut:
                    good += g
                    bad += b
        return good, bad

    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> float:
        good, bad = self.window_counts(window_s, now)
        n = good + bad
        if n == 0:
            return 0.0
        return (bad / n) / max(1.0 - self.target, 1e-9)


class MetricsRegistry:
    """Name -> metric store; get-or-create, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # collect hooks run at the top of every snapshot() (and therefore
        # every /metrics scrape) OUTSIDE the registry lock — the place to
        # refresh derived gauges (SLO burn rates) lazily instead of per
        # request.  Exception-proof: a broken hook must not fail a scrape.
        self._collect_hooks: List = []

    def _get_or_create(self, name, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  help: str = "") -> Histogram:
        h = self._get_or_create(
            name, Histogram, buckets=buckets or DEFAULT_BUCKETS, help=help)
        # explicit buckets that don't match the live metric would put
        # observations past the old top bucket in +Inf; warn (never
        # raise — instrumentation must not be able to fail a run)
        if buckets is not None and tuple(float(b) for b in buckets) != h.buckets:
            from ..log import warning

            warning(
                "histogram %r already registered with buckets %s; "
                "requested %s ignored", name, h.buckets, tuple(buckets))
        return h

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def add_collect_hook(self, fn) -> None:
        """Register `fn()` to run before every snapshot()/scrape (derived-
        gauge refresh).  Idempotent per callable; hooks survive reset()."""
        if fn not in self._collect_hooks:
            self._collect_hooks.append(fn)

    def remove_collect_hook(self, fn) -> None:
        try:
            self._collect_hooks.remove(fn)
        except ValueError:
            pass

    # -- exposition ------------------------------------------------------
    def snapshot(self) -> List[dict]:
        for fn in list(self._collect_hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 — a hook must not fail a scrape
                pass
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return [m.snapshot() for m in metrics]

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (scrape-ready)."""
        lines = []
        for snap in self.snapshot():
            name = _prom_name(snap["metric"])
            lines.append(f"# TYPE {name} {snap['type']}")
            if snap["type"] == "histogram":
                for le, cum in snap["buckets"]:
                    le_s = "+Inf" if le == float("inf") else _prom_num(le)
                    lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
                lines.append(f"{name}_sum {_prom_num(snap['sum'])}")
                lines.append(f"{name}_count {snap['count']}")
            else:
                lines.append(f"{name} {_prom_num(snap['value'])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def jsonl(self) -> str:
        """One JSON object per line per metric (BENCH-artifact style).
        Non-finite values (a NaN loss gauge from a diverged run) become
        strings so the output stays strict JSON."""
        ts = time.time()
        return "\n".join(
            json.dumps(_json_safe(dict(snap, ts=round(ts, 3))))
            for snap in self.snapshot()
        ) + ("\n" if self._metrics else "")

    def write_jsonl(self, path: str):
        with open(path, "w") as f:
            f.write(self.jsonl())

    def write_prometheus(self, path: str):
        with open(path, "w") as f:
            f.write(self.prometheus_text())


def _json_safe(v):
    import math

    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else (
            "Infinity" if v > 0 else "-Infinity")
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


def _prom_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_num(v) -> str:
    import math

    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help=help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help=help)


def histogram(name: str, buckets: Optional[Sequence[float]] = None,
              help: str = "") -> Histogram:
    return _default.histogram(name, buckets=buckets, help=help)
