"""Round-4 small-gap features: sequence expand_as/reshape/scatter/
enumerate, conv3d_transpose, max_pool2d_with_index (+unpool round trip),
py_func, int8 inference execution (freeze_int8), slim pruning +
distillation losses."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(6)


def _run(fetch, feed, startup=False):
    exe = pt.Executor(pt.CPUPlace())
    if startup:
        exe.run(pt.default_startup_program())
    return [np.asarray(r) for r in exe.run(feed=feed, fetch_list=fetch)]


def test_sequence_quartet():
    b, t, d = 2, 4, 6
    x2 = rng.randn(b, d).astype("float32")
    y3 = rng.randn(b, t, d).astype("float32")
    toks = rng.randint(0, 9, (b, t)).astype("int64")
    x2v = layers.data(name="x2", shape=[d], dtype="float32")
    y3v = layers.data(name="y3", shape=[t, d], dtype="float32")
    tkv = layers.data(name="tk", shape=[t], dtype="int64")
    ea = layers.sequence_expand_as(x2v, y3v)
    rs = layers.sequence_reshape(y3v, new_dim=3)
    ids = rng.randint(0, d, (b, 3)).astype("int64")
    upd = rng.randn(b, 3).astype("float32")
    iv = layers.data(name="ids", shape=[3], dtype="int64")
    uv = layers.data(name="upd", shape=[3], dtype="float32")
    sc = layers.sequence_scatter(x2v, iv, uv)
    en = layers.sequence_enumerate(tkv, win_size=2, pad_value=-1)
    r1, r2, r3, r4 = _run([ea, rs, sc, en],
                          {"x2": x2, "y3": y3, "tk": toks,
                           "ids": ids, "upd": upd})
    np.testing.assert_allclose(r1, np.repeat(x2[:, None], t, 1))
    np.testing.assert_allclose(r2, y3.reshape(b, t * 2, 3))
    expect = x2.copy()
    for i in range(b):
        for j in range(3):
            expect[i, ids[i, j]] += upd[i, j]
    np.testing.assert_allclose(r3, expect, rtol=1e-6)
    assert r4.shape == (b, t, 2)
    np.testing.assert_array_equal(r4[:, :-1, 0], toks[:, :-1])
    np.testing.assert_array_equal(r4[:, :-1, 1], toks[:, 1:])
    assert (r4[:, -1, 1] == -1).all()


def test_conv3d_transpose():
    x = rng.randn(2, 3, 4, 4, 4).astype("float32")
    xv = layers.data(name="x", shape=[3, 4, 4, 4], dtype="float32")
    out = layers.conv3d_transpose(xv, num_filters=5, filter_size=2,
                                  stride=2)
    (o,) = _run([out], {"x": x}, startup=True)
    assert o.shape == (2, 5, 8, 8, 8)
    # stride-2 k2 transpose conv exactly inverts shape of stride-2 conv
    assert np.isfinite(o).all()


def test_max_pool_with_index_unpool_roundtrip():
    x = rng.randn(1, 2, 4, 4).astype("float32")
    xv = layers.data(name="x", shape=[2, 4, 4], dtype="float32")
    out, mask = layers.max_pool2d_with_index(xv, pool_size=2)
    up = layers.unpool(out, mask, ksize=[2, 2])
    o, m, u = _run([out, mask, up], {"x": x})
    np.testing.assert_allclose(o, x.reshape(1, 2, 2, 2, 2, 2).max((3, 5)))
    # unpool scatters each max back to its recorded position
    for c in range(2):
        for i in range(2):
            for j in range(2):
                flat = m[0, c, i, j]
                assert u[0, c, flat // 4, flat % 4] == o[0, c, i, j]
    # everything else zero
    assert (u != 0).sum() == 8


def test_py_func_host_callback():
    def host_fn(a, b):
        return np.maximum(a, 0) + np.sort(b, axis=-1)

    a = rng.randn(3, 4).astype("float32")
    b = rng.randn(3, 4).astype("float32")
    av = layers.data(name="a", shape=[4], dtype="float32")
    bv = layers.data(name="b", shape=[4], dtype="float32")
    out = layers.py_func(host_fn, [av, bv], out_shapes=[(3, 4)],
                         out_dtypes=["float32"])
    (o,) = _run([out], {"a": a, "b": b})
    np.testing.assert_allclose(o, np.maximum(a, 0) + np.sort(b, -1),
                               rtol=1e-6)


def test_int8_freeze_matches_float_within_quant_error():
    from paddle_tpu.contrib.quantize import QuantizeTranspiler, freeze_int8

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        out = layers.fc(h, size=10)
    qt = QuantizeTranspiler()
    with pt.program_guard(prog, startup):
        qt.training_transpile(prog, startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        feed = {"x": rng.rand(8, 16).astype("float32")}
        # a few forward passes warm the moving-average activation scales
        for _ in range(10):
            (ref,) = exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        test_prog = prog.clone(for_test=True)
        (ref,) = exe.run(test_prog, feed=feed, fetch_list=[out],
                         scope=scope)
        n = freeze_int8(test_prog, scope)
        assert n == 2, n
        types = [op.type for op in test_prog.global_block().ops]
        assert "int8_mul" in types and "quantize" in types
        assert not any(t.startswith("fake_") for t in types)
        # weights now stored int8
        w_names = [p.name for p in prog.global_block().all_parameters()
                   if p.name.endswith("w_0")]
        assert any(np.asarray(scope.find_var(nm)).dtype == np.int8
                   for nm in w_names)
        (got,) = exe.run(test_prog, feed=feed, fetch_list=[out],
                         scope=scope)
    ref, got = np.asarray(ref), np.asarray(got)
    err = np.abs(ref - got).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err  # int8 quantization error bound


def test_slim_pruning_keeps_zeros_through_training():
    import pytest as _pytest

    from paddle_tpu.contrib.slim import Compressor, Pruner

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="tanh",
                      param_attr=pt.ParamAttr(name="pw"))
        loss = layers.mean(layers.square(layers.fc(h, size=1) - y))
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        # prune BEFORE minimize: the mask multiply joins the
        # differentiated graph, so pruned entries get zero grads
        comp = Compressor(prog, scope,
                          pruner=Pruner({"pw": 0.5})).compress()
        assert comp.pruned_params == ["pw"]
        with pt.program_guard(prog, startup):
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        # minimize added LR/accumulator initializers to the already-run
        # startup program; init-on-demand runs just those
        n_init = exe.run_startup_missing(startup, scope=scope)
        assert n_init >= 1
        s0 = comp.sparsity()
        assert 0.45 <= s0 <= 0.55
        w = rng.randn(8, 1).astype("float32")
        for i in range(20):
            xb = rng.randn(32, 8).astype("float32")
            exe.run(prog, feed={"x": xb, "y": xb @ w}, fetch_list=[loss],
                    scope=scope)
        # pruned entries stayed zero through 20 optimizer updates:
        # the mask multiply zeroes their gradients in the traced graph
        wv = np.asarray(scope.find_var("pw"))
        mask = np.asarray(scope.find_var("pw@prune_mask"))
        assert (wv[mask == 0] == 0).all()
        assert (wv[mask == 1] != 0).any()
        # pruning AFTER minimize must refuse (inconsistent grads otherwise)
        with _pytest.raises(RuntimeError, match="BEFORE"):
            Pruner({"pw": 0.5}).prune(prog, scope)


def test_slim_distillation_losses():
    from paddle_tpu.contrib import slim

    t = layers.data(name="t", shape=[10], dtype="float32")
    s = layers.data(name="s", shape=[10], dtype="float32")
    kd = slim.soft_label_loss(t, s, temperature=4.0)
    l2 = slim.l2_loss(t, s)
    tv = rng.randn(6, 10).astype("float32")
    r1, r2 = _run([kd, l2], {"t": tv, "s": tv})
    # identical logits: l2 = 0, KD = entropy * T^2 (> 0)
    np.testing.assert_allclose(r2, 0.0, atol=1e-6)
    assert r1 > 0
    # KD decreases as student approaches teacher
    sv = tv + rng.randn(6, 10).astype("float32")
    r_far = _run([kd], {"t": tv, "s": sv})[0]
    assert r_far > r1


def test_dropout_regenerated_mask_consistency():
    """Residual-free dropout: the backward regenerates the SAME mask from
    the static rng_id — positions zeroed in the forward must be exactly
    the positions with zero gradient."""
    x = layers.data(name="xd", shape=[64], dtype="float32")
    x.stop_gradient = False
    d = layers.dropout(x, dropout_prob=0.5,
                       dropout_implementation="upscale_in_train")
    total = layers.reduce_sum(d)
    grads = pt.calc_gradient(total, [x])
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((16, 64), "float32")
    out, g = exe.run(feed={"xd": xv}, fetch_list=[d, grads[0]])
    out, g = np.asarray(out), np.asarray(g)
    np.testing.assert_array_equal(out == 0, g == 0)
    # kept positions carry the upscale factor in BOTH directions
    np.testing.assert_allclose(out[out != 0], 2.0, rtol=1e-6)
    np.testing.assert_allclose(g[g != 0], 2.0, rtol=1e-6)
    # and the op carries a static rng_id (no Mask residual in backward)
    ops = pt.default_main_program().global_block().ops
    dgrad = [op for op in ops if op.type == "dropout_grad"]
    assert dgrad and not dgrad[0].inputs.get("Mask")


def test_int8_freeze_shared_weight():
    """A weight feeding TWO quantized consumers must be quantized once
    and its scale reused (re-quantizing the int8 tensor would read
    max|int8| ~ 127 as the scale and corrupt the model)."""
    from paddle_tpu.contrib.quantize import QuantizeTranspiler, freeze_int8

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        shared = pt.ParamAttr(name="shared_w")
        h1 = layers.fc(x, size=8, param_attr=shared, bias_attr=False)
        h2 = layers.fc(h1, size=8, param_attr=shared, bias_attr=False)
        out = layers.reduce_sum(h2, dim=1, keep_dim=True)
        QuantizeTranspiler().training_transpile(prog, startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        feed = {"x": rng.rand(8, 8).astype("float32")}
        for _ in range(6):  # warm the activation scales
            (ref,) = exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        test_prog = prog.clone(for_test=True)
        (ref,) = exe.run(test_prog, feed=feed, fetch_list=[out], scope=scope)
        n = freeze_int8(test_prog, scope)
        assert n == 2
        (got,) = exe.run(test_prog, feed=feed, fetch_list=[out], scope=scope)
    ref, got = np.asarray(ref), np.asarray(got)
    err = np.abs(ref - got).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err


def test_stack_and_streaming_auc():
    """layers.stack + the streaming auc op outside the deepfm trainer
    (their only other in-tree user): stack matches np.stack, and the
    persistent StatPos/StatNeg histograms accumulate across runs — a
    perfectly-separating predictor converges to AUC 1.0."""
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        a = layers.data(name="sa", shape=[4], dtype="float32")
        c = layers.data(name="sb", shape=[4], dtype="float32")
        st = layers.stack([a, c], axis=1)
        pred = layers.data(name="pred", shape=[2], dtype="float32")
        lbl = layers.data(name="lbl", shape=[1], dtype="int64")
        auc_var, _states = layers.auc(input=pred, label=lbl,
                                      num_thresholds=255)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    av = rng.randn(3, 4).astype("float32")
    cv = rng.randn(3, 4).astype("float32")
    pos = np.array([0.9, 0.8, 0.2], "float32")
    feed = {"sa": av, "sb": cv,
            "pred": np.stack([1 - pos, pos], axis=1),
            "lbl": np.array([[1], [1], [0]], "int64")}
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        for _ in range(2):  # second run reads back the stat state
            s, auc = exe.run(prog, feed=feed, fetch_list=[st, auc_var],
                             scope=scope)
    np.testing.assert_allclose(np.asarray(s), np.stack([av, cv], axis=1))
    assert float(np.asarray(auc)) == pytest.approx(1.0)
