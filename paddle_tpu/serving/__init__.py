"""Production serving tier: multi-model inference server with dynamic
batching on the AOT-bundle path (ROADMAP item 1 — the reference's
out-of-Python serving property, api/paddle_api.h:153, grown into the
"heavy traffic" story).

Three layers:

  * `model.py`   — ServingModel: a Predictor (+ optional int8 replica via
    contrib.quantize.freeze_int8) with a pad-to-bucket batch ladder,
    startup warmup, and serving-tier recompile tagging.
  * `batcher.py` — DynamicBatcher: per-model request queue drained by a
    scheduler thread that coalesces concurrent requests into bucket
    shapes (max-wait deadline, max-batch cap), so every executed batch
    hits a warm entry in the executor's compile cache.
  * `server.py`  — InferenceServer: stdlib-HTTP multi-model endpoint
    (JSON + npz), /v1/models introspection, /metrics //health //flight
    inherited from the monitor stack, persistent XLA compilation cache.

A fourth layer serves autoregressive generation (ROADMAP item 2):

  * `generation.py` — GenerationServingModel + ContinuousBatcher:
    continuous TOKEN-level batching of decode steps across in-flight
    sequences on the KV-cache program pair (paddle_tpu/generation); new
    sequences join at prefill via the active-mask feed, finished ones
    retire their cache slot, and nothing ever retraces.  Endpoint:
    POST /v1/models/<name>:generate.

The tier is overload-hardened (ISSUE 13): bounded queues + in-flight
cap shed with 429/Retry-After, request deadlines propagate into the
schedulers (expired work is dropped before dispatch), SIGTERM drains
gracefully (503 new work, finish admitted work, dump flight, exit 0),
a per-model circuit breaker fails fast past consecutive executor
failures, and /health reports `draining` / `scheduler_dead`.  Chaos
kinds in testing/chaos.py (serve latency / transient executor errors /
request flood) drive the CI overload gate.

Scale-out (ISSUE 18): `router.py` + `fleet.py` turn N replicas into one
durable endpoint — a health-probe-driven Router (least-inflight +
SLO-weighted balancing, deadline-budgeted retry-with-failover, optional
tail-latency hedging, traceparent passthrough) fronting a
ReplicaSupervisor that crash-restarts replicas with capped backoff and
rolling-restarts them with zero downtime against the shared persistent
compilation cache.  Both are lazy exports: the single-replica serving
path never imports them.

CLI: `python -m paddle_tpu.serving --model name=/path/to/export ...`
     (add `--demo-generation NAME` for the seeded tiny generation model;
      add `--replicas N` for a supervised fleet behind the router)
Load test: `python tools/loadgen.py --url http://host:port --model name`
           (`--generate` for prompt-in/tokens-out TTFT + tokens/sec;
            `--router` to scrape router fleet metrics into the artifact).
"""

from .batcher import (  # noqa: F401
    CircuitBreaker,
    DynamicBatcher,
    FILL_BUCKETS,
    Overloaded,
    Unavailable,
)
from .generation import (  # noqa: F401
    ContinuousBatcher,
    GenerationConfig,
    GenerationServingModel,
    build_demo_generation_model,
)
from .model import ModelConfig, ServingModel, parse_buckets  # noqa: F401
from .server import (  # noqa: F401
    InferenceServer,
    RequestError,
    ServingHandler,
    enable_compilation_cache,
)


def __getattr__(name):
    # the scale-out tier stays un-imported until someone asks for it:
    # single-replica serving pays nothing for the router/fleet code
    if name in ("Router", "RouterHandler", "Replica"):
        from . import router as _router

        return getattr(_router, name)
    if name == "ReplicaSupervisor":
        from .fleet import ReplicaSupervisor

        return ReplicaSupervisor
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
