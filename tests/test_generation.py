"""Autoregressive generation tier (PR 11): KV-cache contract, flash-decode
kernel, per-token program drivers, and continuous token-level batching.

Acceptance criteria covered here:
  * greedy decode through the KV-cache path is TOKEN-IDENTICAL to the
    flag-off full-prefix recompute path, and the executor compile cache
    stays FLAT after prefill + the first decode step across >= 64
    generated tokens at two batch sizes;
  * the flash-decode kernel passes interpret-mode parity (fwd-only
    contract) and falls back to XLA off-contract;
  * the beam-search While program is output-identical across
    FLAGS_kv_cache, and the per-token beam driver matches both;
  * a late-joining serving sequence neither retraces nor stalls
    in-flight decodes.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import executor as ex
from paddle_tpu.core import framework as fw
from paddle_tpu.flags import FLAGS
from paddle_tpu.generation import GenerationSession, KVCache
from paddle_tpu.models import transformer as T

TINY = dict(src_vocab_size=16, trg_vocab_size=16, max_length=12,
            n_layer=2, n_head=2, d_key=8, d_value=8, d_model=16,
            d_inner_hid=32)


def _src(rng, b, seq, vocab=16):
    return rng.randint(2, vocab, (b, seq, 1)).astype(np.int64)


# ---------------------------------------------------------------------------
# flash-decode kernel
# ---------------------------------------------------------------------------


class TestFlashDecodeKernel:
    def test_interpret_parity_ragged_lengths(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(0)
        for b, h, dh, t, blk in [(2, 8, 64, 64, 16), (3, 8, 64, 128, 32),
                                 (1, 16, 64, 256, 256)]:
            q = jnp.asarray(rng.randn(b, h, dh).astype(np.float32))
            k = jnp.asarray(rng.randn(b, t, h, dh).astype(np.float32))
            v = jnp.asarray(rng.randn(b, t, h, dh).astype(np.float32))
            lens = jnp.asarray(
                rng.randint(1, t + 1, (b,)).astype(np.int32))
            ref = kda.reference_decode(q, k, v, lens, scale=dh**-0.5)
            out = kda.flash_decode(q, k, v, lens, scale=dh**-0.5,
                                   block_t=blk, interpret=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5)

    def test_length_masks_garbage_tail(self):
        """Rows past each sequence's length must not influence the
        output — overwrite the tail with huge values and compare."""
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(1)
        b, h, dh, t = 2, 8, 64, 128
        q = jnp.asarray(rng.randn(b, h, dh).astype(np.float32))
        k = rng.randn(b, t, h, dh).astype(np.float32)
        v = rng.randn(b, t, h, dh).astype(np.float32)
        lens = np.asarray([5, 77], np.int32)
        k2, v2 = k.copy(), v.copy()
        for i, L in enumerate(lens):
            k2[i, L:] = 1e6
            v2[i, L:] = -1e6
        a = kda.flash_decode(q, jnp.asarray(k), jnp.asarray(v),
                             jnp.asarray(lens), interpret=True)
        bb = kda.flash_decode(q, jnp.asarray(k2), jnp.asarray(v2),
                              jnp.asarray(lens), interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=1e-6)

    def test_plan_gate_rejects_off_contract(self):
        import jax

        from paddle_tpu.kernels import decode_attention as kda

        def plan(b, h, dh, max_t):
            q = jax.ShapeDtypeStruct((b, h, dh), np.float32)
            k = jax.ShapeDtypeStruct((b, max_t, h, dh), np.float32)
            return kda._decode_plan(q, k, 256, False)[0]

        assert plan(1, 8, 64, 128)          # canonical: accepted
        assert not plan(1, 8, 48, 128)      # dh % 64
        assert not plan(1, 3, 64, 128)      # h % sublane
        assert not plan(1, 8, 64, 100)      # max_t not block-divisible

    def test_off_contract_falls_back_identically(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(2, 3, 48).astype(np.float32))
        k = jnp.asarray(rng.randn(2, 50, 3, 48).astype(np.float32))
        lens = jnp.asarray([10, 50], jnp.int32)
        out = kda.flash_decode(q, k, k, lens, interpret=True)
        ref = kda.reference_decode(q, k, k, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# generation ops (also the op-contract gate's execution coverage)
# ---------------------------------------------------------------------------


class TestGenerationOps:
    def test_kv_cache_update_and_attend(self):
        L, b, max_t, h, dh = 2, 3, 128, 8, 64
        cache = KVCache("t_cache", L, b, max_t, h, dh)
        scope = ex.Scope()
        cache.allocate(scope)
        k_var = layers.data(name="k", shape=[1, h, dh], dtype="float32")
        v_var = layers.data(name="v", shape=[1, h, dh], dtype="float32")
        q_var = layers.data(name="q", shape=[1, h, dh], dtype="float32")
        pos = layers.data(name="pos", shape=[1], dtype="int32")
        lens = layers.data(name="lens", shape=[1], dtype="int32")
        pos_r = layers.reshape(pos, [b])
        lens_r = layers.reshape(lens, [b])
        cache.write(k_var, v_var, pos_r, layer=1)
        out = cache.attend(q_var, lens_r, layer=1, scale=dh**-0.5)
        exe = pt.Executor(pt.CPUPlace())
        rng = np.random.RandomState(0)
        kv = rng.randn(b, 1, h, dh).astype(np.float32)
        vv = rng.randn(b, 1, h, dh).astype(np.float32)
        qv = rng.randn(b, 1, h, dh).astype(np.float32)
        posv = np.asarray([[0], [3], [7]], np.int32)
        lensv = posv + 1
        (o,) = exe.run(feed={"k": kv, "v": vv, "q": qv, "pos": posv,
                             "lens": lensv},
                       fetch_list=[out], scope=scope)
        ck = np.asarray(scope.find_var(cache.k_name))
        # rows landed at the per-sequence positions of layer 1 only
        assert np.abs(ck[0]).sum() == 0.0
        for i in range(b):
            np.testing.assert_allclose(ck[1, i, posv[i, 0]], kv[i, 0])
        # single-row attention over a 1-row window == softmax over 1 = v
        np.testing.assert_allclose(np.asarray(o)[0, 0], vv[0, 0],
                                   atol=1e-5)

    def test_kv_cache_update_active_mask(self):
        L, b, max_t, h, dh = 1, 4, 128, 8, 64
        cache = KVCache("t_mask", L, b, max_t, h, dh)
        scope = ex.Scope()
        cache.allocate(scope)
        k_var = layers.data(name="k", shape=[1, h, dh], dtype="float32")
        pos = layers.data(name="pos", shape=[1], dtype="int32")
        act = layers.data(name="act", shape=[1], dtype="int32")
        cache.write(k_var, k_var, layers.reshape(pos, [b]), layer=0,
                    active=layers.reshape(act, [b]))
        exe = pt.Executor(pt.CPUPlace())
        kv = np.ones((b, 1, h, dh), np.float32)
        exe.run(feed={"k": kv, "pos": np.zeros((b, 1), np.int32),
                      "act": np.asarray([[1], [0], [1], [0]], np.int32)},
                fetch_list=[], scope=scope)
        ck = np.asarray(scope.find_var(cache.k_name))
        assert ck[0, 0].sum() > 0 and ck[0, 2].sum() > 0
        assert ck[0, 1].sum() == 0 and ck[0, 3].sum() == 0

    def test_kv_cache_reorder(self):
        L, b, max_t, h, dh = 2, 4, 128, 8, 64
        cache = KVCache("t_reord", L, b, max_t, h, dh)
        scope = ex.Scope()
        cache.allocate(scope)
        import jax.numpy as jnp

        marked = np.zeros(cache.shape, np.float32)
        for i in range(b):
            marked[:, i] = i + 1
        scope.set_var(cache.k_name, jnp.asarray(marked))
        scope.set_var(cache.v_name, jnp.asarray(marked))
        par = layers.data(name="par", shape=[1], dtype="int64")
        cache.reorder(layers.reshape(par, [b]))
        exe = pt.Executor(pt.CPUPlace())
        exe.run(feed={"par": np.asarray([[3], [3], [0], [1]], np.int64)},
                fetch_list=[], scope=scope)
        ck = np.asarray(scope.find_var(cache.k_name))
        assert [ck[0, i, 0, 0, 0] for i in range(b)] == [4, 4, 1, 2]

    def test_sample_token_greedy_is_argmax(self):
        logits = layers.data(name="lg", shape=[7], dtype="float32")
        out = layers.sample_token(logits, strategy="greedy")
        exe = pt.Executor(pt.CPUPlace())
        lv = np.random.RandomState(0).randn(5, 7).astype(np.float32)
        (o,) = exe.run(feed={"lg": lv}, fetch_list=[out])
        np.testing.assert_array_equal(
            np.asarray(o).reshape(-1), lv.argmax(axis=1))

    def test_sample_token_topk_in_range_and_rng_threaded(self):
        logits = layers.data(name="lg", shape=[9], dtype="float32")
        out = layers.sample_token(logits, strategy="sample",
                                  temperature=0.7, top_k=3)
        prog = fw.default_main_program()
        # attr-gated RNG: the sampling program threads the step key ...
        assert ex.program_uses_random(prog.global_block())
        exe = pt.Executor(pt.CPUPlace())
        lv = np.random.RandomState(1).randn(6, 9).astype(np.float32)
        top3 = np.argsort(-lv, axis=1)[:, :3]
        draws = set()
        for _ in range(4):
            (o,) = exe.run(feed={"lg": lv}, fetch_list=[out])
            o = np.asarray(o).reshape(-1)
            for i in range(6):
                assert o[i] in top3[i]
            draws.add(tuple(o.tolist()))
        # ... and successive runs fold a fresh counter (not frozen draws)
        assert len(draws) > 1

    def test_greedy_program_is_key_free(self):
        logits = layers.data(name="lg", shape=[7], dtype="float32")
        layers.sample_token(logits, strategy="greedy")
        assert not ex.program_uses_random(
            fw.default_main_program().global_block())


# ---------------------------------------------------------------------------
# drivers: parity + compile-flat acceptance
# ---------------------------------------------------------------------------


class TestGreedyGeneration:
    @pytest.mark.parametrize("batch", [1, 4])
    def test_cached_token_identical_to_recompute_and_compile_flat(
            self, batch):
        """THE acceptance criterion: >= 64 greedy tokens, cached vs
        recompute token-identical, executor compile cache flat after
        prefill + first decode step — at two batch sizes."""
        dims = dict(TINY, max_length=66, batch_size=batch, src_seq_len=6,
                    max_out_len=64, bos_id=0, eos_id=-1)  # no early eos
        rng = np.random.RandomState(3 + batch)
        src = _src(rng, batch, 6)
        scope = ex.Scope()

        cached = GenerationSession(
            T.build_generation_programs(kv_cache=True, **dims),
            scope=scope)
        cached.init_params()
        toks_c, steps = cached.generate(src)
        assert steps == 64 and toks_c.shape == (batch, 64)
        n_compiled = cached.compile_count
        # 64 more tokens + a fresh generate: the cache may NOT grow
        cached.generate(src)
        assert cached.compile_count == n_compiled

        recompute = GenerationSession(
            T.build_generation_programs(kv_cache=False, **dims),
            scope=scope)
        toks_r, _ = recompute.generate(src)
        np.testing.assert_array_equal(toks_c, toks_r)
        n_compiled = recompute.compile_count
        recompute.generate(src)
        assert recompute.compile_count == n_compiled

    def test_eos_terminates_and_pads(self):
        """A trained-free check of the eos contract: with eos_id set to
        the argmax the model emits immediately, generation stops at step
        1 and the emitted stream is eos-padded."""
        dims = dict(TINY, batch_size=2, src_seq_len=6, max_out_len=8,
                    bos_id=0)
        rng = np.random.RandomState(5)
        src = _src(rng, 2, 6)
        probe = GenerationSession(
            T.build_generation_programs(eos_id=-1, **dims))
        probe.init_params()
        first = int(probe.generate(src, max_tokens=1)[0][0, 0])
        sess = GenerationSession(
            T.build_generation_programs(eos_id=first, **dims),
            scope=probe.scope)
        toks, steps = sess.generate(src)
        assert steps <= 8
        assert (toks[:, 0] == first).any()

    def test_trained_copy_task_greedy_decode(self):
        """End-to-end quality: train the tiny transformer on the copy
        task, then greedy-generate through the cache and check the
        output reproduces the source prefix."""
        vocab, seq, bs = 16, 6, 32
        dims = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
                    max_length=seq + 2, n_layer=1, n_head=2, d_key=16,
                    d_value=16, d_model=32, d_inner_hid=64)
        rng = np.random.RandomState(0)
        train_prog, train_startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(train_prog, train_startup):
                avg_cost, _, _ = T.transformer(
                    batch_size=bs, src_seq_len=seq, trg_seq_len=seq,
                    dropout_rate=0.0, **dims)
                pt.optimizer.AdamOptimizer(
                    learning_rate=3e-3).minimize(avg_cost)
        scope = ex.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(train_startup, scope=scope)
        losses = []
        for _ in range(120):
            src = rng.randint(2, vocab, (bs, seq, 1)).astype(np.int64)
            pos = np.tile(np.arange(seq, dtype=np.int64)[None, :, None],
                          (bs, 1, 1))
            trg_in = np.concatenate(
                [np.zeros((bs, 1, 1), np.int64), src[:, :-1]], axis=1)
            (lv,) = exe.run(
                train_prog,
                feed={"src_word": src, "src_pos": pos, "trg_word": trg_in,
                      "trg_pos": pos, "lbl_word": src,
                      "lbl_weight": np.ones((bs, seq, 1), np.float32)},
                fetch_list=[avg_cost], scope=scope)
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.5

        gen_b = 4
        sess = GenerationSession(
            T.build_generation_programs(
                batch_size=gen_b, src_seq_len=seq, max_out_len=seq,
                bos_id=0, eos_id=1, **dims),
            scope=scope)
        src = rng.randint(2, vocab, (gen_b, seq, 1)).astype(np.int64)
        toks, _ = sess.generate(src)
        acc = float((toks[:, :seq] == src[:, :, 0]).mean())
        assert acc > 0.55, (acc, toks, src[:, :, 0])


class TestBeamDecoding:
    def _trained_free_setup(self, beam=3, b=2, seq=6):
        rng = np.random.RandomState(0)
        src = _src(rng, b, seq)
        pos = np.tile(np.arange(seq, dtype=np.int64)[None, :, None],
                      (b, 1, 1))
        train_prog, train_startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(train_prog, train_startup):
                T.transformer(batch_size=b, src_seq_len=seq,
                              trg_seq_len=seq, dropout_rate=0.0, **TINY)
        scope = ex.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(train_startup, scope=scope)
        return src, pos, scope, exe

    def _run_while_decoder(self, exe, scope, src, pos, beam, b, seq):
        dec_prog, dec_startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(dec_prog, dec_startup):
                sent, scores, _ = T.build_decoder(
                    batch_size=b, src_seq_len=seq, max_out_len=5,
                    beam_size=beam, bos_id=0, eos_id=1, **TINY)
        s, sc = exe.run(dec_prog,
                        feed={"src_word": src, "src_pos": pos},
                        fetch_list=[sent, scores], scope=scope)
        return np.asarray(s), np.asarray(sc)

    def test_while_program_flag_parity_and_driver_match(self):
        """build_decoder cached-While == recompute-While == per-token
        beam driver, on one shared scope."""
        beam, b, seq = 3, 2, 6
        src, pos, scope, exe = self._trained_free_setup(beam, b, seq)
        try:
            FLAGS.kv_cache = True
            s_on, sc_on = self._run_while_decoder(exe, scope, src, pos,
                                                  beam, b, seq)
            FLAGS.kv_cache = False
            s_off, sc_off = self._run_while_decoder(exe, scope, src, pos,
                                                    beam, b, seq)
        finally:
            FLAGS.reset("kv_cache")
        np.testing.assert_array_equal(s_on, s_off)
        np.testing.assert_allclose(sc_on, sc_off, rtol=1e-4)

        sess = GenerationSession(
            T.build_generation_programs(
                batch_size=b, src_seq_len=seq, max_out_len=5,
                beam_size=beam, bos_id=0, eos_id=1, **TINY),
            scope=scope)
        sent, scores = sess.generate_beam(src)
        np.testing.assert_array_equal(sent, s_on)
        np.testing.assert_allclose(scores, sc_on, rtol=1e-4)
        # beam scores sorted best-first
        assert np.all(np.diff(scores, axis=1) <= 1e-5)
        # driver compile cache flat across another full generation
        n = sess.compile_count
        sess.generate_beam(src)
        assert sess.compile_count == n

    def test_beam_pair_requires_cache(self):
        with pytest.raises(ValueError, match="KV-cache"):
            T.build_generation_programs(
                batch_size=2, src_seq_len=6, max_out_len=5, beam_size=2,
                kv_cache=False, **TINY)


# ---------------------------------------------------------------------------
# static analysis coverage
# ---------------------------------------------------------------------------


class TestGenerationStaticAnalysis:
    def test_programs_verify_clean(self):
        from paddle_tpu.analysis import verify_program

        for strat in ("greedy", "sample"):
            p = T.build_generation_programs(
                batch_size=2, src_seq_len=6, max_out_len=5,
                strategy=strat, top_k=4, **TINY)
            for prog, feeds, fetch in (
                    (p.prefill, ["src_word", "src_pos", "gen_active"],
                     p.prefill_fetch),
                    # greedy self-feeds the token under
                    # FLAGS_fused_decode_step; decode_feeds names the
                    # route's actual feed list
                    (p.decode, p.decode_feeds, p.decode_fetch)):
                findings = verify_program(prog, feed_names=feeds,
                                          fetch_names=fetch,
                                          check_dead=True)
                assert not findings, [str(f) for f in findings]

    def test_decode_kernel_lint_red_gate(self):
        """check_decode_plan must NAME a gate that silently rejects a
        must-accept shape and a plan violating the block contract."""
        from paddle_tpu.analysis.kernel_lint import check_decode_plan

        cfg = dict(label="fab", b=1, h=8, dh=64, max_t=128,
                   dtype="float32")
        findings = []
        check_decode_plan(cfg, False, 128, False, findings)
        assert any(f.check == "kernel-plan-reject" for f in findings)
        findings = []
        check_decode_plan(cfg, True, 96, False, findings)  # 128 % 96
        assert any(f.check == "kernel-grid-divisibility"
                   for f in findings)
        findings = []
        check_decode_plan(dict(cfg, h=3, must_accept=False), True, 128,
                          False, findings)
        assert any(f.check == "kernel-misaligned-block"
                   for f in findings)

    def test_decode_matrix_must_accepts(self):
        """The perf-critical decode plans stay accepted (regression pin
        on the plan gate)."""
        from paddle_tpu.analysis.kernel_lint import (_DECODE_MATRIX,
                                                     lint_kernel_plans)

        findings, report = lint_kernel_plans()
        decode = {r["label"]: r for r in report["decode_attention"]}
        for cfg in _DECODE_MATRIX:
            expect = cfg.get("must_accept", True)
            assert decode[cfg["label"]]["accepted"] == expect, cfg
        assert not [f for f in findings
                    if "decode" in getattr(f, "op_type", "")]


# ---------------------------------------------------------------------------
# serving: continuous token-level batching
# ---------------------------------------------------------------------------


def _tiny_serving_model(name, slots=4, max_out=24):
    from paddle_tpu.serving.generation import (GenerationConfig,
                                               GenerationServingModel)

    cfg = GenerationConfig(
        name, slots=slots,
        src_vocab_size=32, trg_vocab_size=32, max_length=32,
        n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
        d_inner_hid=32, src_seq_len=8, max_out_len=max_out,
        bos_id=0, eos_id=1)
    model = GenerationServingModel(cfg)
    for prog in (model.session.p.prefill, model.session.p.decode,
                 model.session.p.startup):
        prog.random_seed = 13
    model.init_params()
    return model


class TestContinuousBatching:
    def test_concurrent_requests_coalesce_without_retrace(self):
        from paddle_tpu.serving.generation import ContinuousBatcher

        model = _tiny_serving_model("genloc")
        model.warmup()
        batcher = ContinuousBatcher(model)
        batcher.start()
        try:
            n_compiled = model.compile_count
            results = [None] * 6
            def worker(i):
                results[i] = batcher.submit([2 + i, 5], max_tokens=8,
                                            timeout=60.0)
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for toks, meta in results:
                assert 1 <= len(toks) <= 8
                assert meta["ttft_ms"] >= 0
                assert meta["finished"] in ("eos", "max_tokens")
            # 6 requests over 4 slots: someone waited for a retirement
            slots = {meta["slot"] for _, meta in results}
            assert slots <= set(range(model.slots))
            # the whole burst compiled NOTHING (warm program pair)
            assert model.compile_count == n_compiled
        finally:
            batcher.stop()

    def test_late_join_does_not_stall_or_retrace(self):
        from paddle_tpu.serving.generation import ContinuousBatcher

        model = _tiny_serving_model("genlate", max_out=24)
        model.warmup()
        batcher = ContinuousBatcher(model)
        batcher.start()
        try:
            n_compiled = model.compile_count
            done = {}

            def long_req():
                done["long"] = (batcher.submit([3, 5, 7], max_tokens=24),
                                time.perf_counter())

            t = threading.Thread(target=long_req)
            t.start()
            time.sleep(0.005)
            short = batcher.submit([9, 2], max_tokens=2, timeout=60.0)
            t_short = time.perf_counter()
            t.join(timeout=60)
            (long_toks, long_meta), t_long = done["long"]
            assert len(short[0]) <= 2
            if long_meta["finished"] == "max_tokens":
                # the short request must not have waited for the long one
                assert t_short <= t_long
            assert model.compile_count == n_compiled
        finally:
            batcher.stop()

    def test_validation_errors(self):
        from paddle_tpu.serving.generation import ContinuousBatcher

        model = _tiny_serving_model("genval")
        model.warmup()
        batcher = ContinuousBatcher(model)
        batcher.start()
        try:
            with pytest.raises(ValueError, match="empty"):
                batcher.submit([])
            with pytest.raises(ValueError, match="pad id"):
                batcher.submit([999])
            with pytest.raises(ValueError, match="pad id"):
                batcher.submit([3, 0, 5])  # mid-prompt pad id rejected
            with pytest.raises(ValueError, match="max_prompt_len"):
                batcher.submit(list(range(2, 13)))
            with pytest.raises(ValueError, match="positive"):
                batcher.submit([3], max_tokens=0)
        finally:
            batcher.stop()

    def test_requires_kv_cache_flag(self):
        from paddle_tpu.serving.generation import (GenerationConfig,
                                                   GenerationServingModel)

        FLAGS.kv_cache = False
        try:
            with pytest.raises(ValueError, match="kv_cache"):
                GenerationServingModel(GenerationConfig(
                    "nocache", src_vocab_size=8, trg_vocab_size=8,
                    max_length=16, n_layer=1, n_head=2, d_key=8,
                    d_value=8, d_model=16, d_inner_hid=32,
                    src_seq_len=4, max_out_len=4))
        finally:
            FLAGS.reset("kv_cache")

    def test_server_generate_endpoint(self):
        """HTTP :generate round-trip on an in-process InferenceServer
        (readiness, models_info, and the endpoint contract)."""
        import json
        import urllib.request

        from paddle_tpu.serving import InferenceServer

        srv = InferenceServer([], port=0)
        model = _tiny_serving_model("genhttp")
        srv.add_generation_model(model)
        port = srv.start()
        try:
            body = json.dumps({"prompt": [3, 5], "max_tokens": 4}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/genhttp:generate",
                data=body, headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                payload = json.loads(r.read())
            assert 1 <= len(payload["tokens"]) <= 4
            assert payload["meta"]["ttft_ms"] >= 0
            infos = {m["name"]: m for m in srv.models_info()}
            assert infos["genhttp"]["type"] == "generation"
            assert infos["genhttp"]["ready"]
            assert srv.readiness()["ready"]
        finally:
            srv.stop()
