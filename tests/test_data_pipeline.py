"""RecordIO (native C++ + python fallback), double-buffer prefetch,
datasets (reference: paddle/fluid/recordio/, operators/reader/
buffered_reader.cc, python/paddle/dataset/)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import recordio
from paddle_tpu.reader import decorator

RECORDS = [b"alpha", b"", b"x" * 100, b"beta" * 1000, b"tail"]


def _roundtrip(tmp_path, write_native, read_native, chunk=64):
    path = str(tmp_path / f"t_{write_native}_{read_native}.rio")
    with recordio.Writer(path, max_chunk_bytes=chunk,
                         use_native=write_native) as w:
        for r in RECORDS:
            w.write(r)
    got = list(recordio.Scanner(path, use_native=read_native))
    assert got == RECORDS


def test_recordio_python_roundtrip(tmp_path):
    _roundtrip(tmp_path, False, False)


@pytest.mark.skipif(not recordio.native_available(),
                    reason="no C++ toolchain")
def test_recordio_native_roundtrip(tmp_path):
    _roundtrip(tmp_path, True, True)


@pytest.mark.skipif(not recordio.native_available(),
                    reason="no C++ toolchain")
def test_recordio_native_python_interop(tmp_path):
    """Same on-disk format both ways."""
    _roundtrip(tmp_path, True, False)
    _roundtrip(tmp_path, False, True)


def test_recordio_sharded_chunks_partition(tmp_path):
    path = str(tmp_path / "shard.rio")
    recs = [f"rec{i}".encode() for i in range(40)]
    with recordio.Writer(path, max_chunk_bytes=20) as w:  # many chunks
        for r in recs:
            w.write(r)
    n_chunks = recordio.Scanner(path).num_chunks()
    assert n_chunks >= 4
    shards = [
        list(recordio.Scanner(path, shard_id=i, num_shards=3))
        for i in range(3)
    ]
    union = [r for s in shards for r in s]
    assert sorted(union) == sorted(recs)      # complete, no overlap
    assert all(len(s) > 0 for s in shards)    # each shard gets chunks


def test_recordio_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "bad.rio")
    with recordio.Writer(path) as w:
        w.write(b"payload-payload-payload")
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(OSError):
        list(recordio.Scanner(path, use_native=False))
    if recordio.native_available():
        with pytest.raises(OSError):
            list(recordio.Scanner(path, use_native=True))


def test_double_buffer_prefetches_device_arrays():
    import jax

    batches = [{"x": np.full((2, 3), i, "float32")} for i in range(5)]

    def src():
        yield from batches

    got = list(decorator.double_buffer(src)())
    assert len(got) == 5
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)  # already device-resident
        np.testing.assert_array_equal(np.asarray(b["x"]), batches[i]["x"])


def test_double_buffer_feeds_training():
    from paddle_tpu import layers

    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(pred - y))
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(2)

    def src():
        for _ in range(10):
            xv = rng.randn(8, 4).astype("float32")
            yield {"x": xv,
                   "y": xv.sum(axis=1, keepdims=True).astype("float32")}

    losses = [
        float(np.asarray(exe.run(feed=b, fetch_list=[loss])[0]))
        for b in decorator.double_buffer(src)()
    ]
    assert losses[-1] < losses[0]


def test_double_buffer_propagates_errors():
    def src():
        yield {"x": np.zeros(2, "float32")}
        raise ValueError("boom")

    it = decorator.double_buffer(src)()
    next(it)
    with pytest.raises(ValueError, match="boom"):
        next(it)


# ---------------------------------------------------------------------------
# datasets (synthetic mode — offline)
# ---------------------------------------------------------------------------


def test_uci_housing_synthetic():
    train = list(pt.dataset.uci_housing.train(synthetic=True)())
    test = list(pt.dataset.uci_housing.test(synthetic=True)())
    assert len(train) == 404 and len(test) == 102
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_cifar_synthetic():
    samples = list(pt.dataset.cifar.train10(synthetic=True)())
    assert len(samples) == 512
    im, lb = samples[0]
    assert im.shape == (3072,) and 0 <= lb < 10
    s100 = list(pt.dataset.cifar.train100(synthetic=True)())
    assert max(lb for _, lb in s100) > 10


def test_imdb_synthetic():
    wd = pt.dataset.imdb.word_dict(synthetic=True)
    assert "<unk>" in wd
    samples = list(pt.dataset.imdb.train(wd, synthetic=True)())
    assert len(samples) == 500
    ids, label = samples[0]
    assert label in (0, 1) and all(0 <= i < len(wd) for i in ids)


def test_movielens_synthetic():
    samples = list(pt.dataset.movielens.train(synthetic=True)())
    assert len(samples) == 2000
    uid, gender, age, job, mid, cats, title, score = samples[0]
    assert gender in (0, 1)
    assert 0 <= age < len(pt.dataset.movielens.age_table())
    assert all(
        0 <= c < len(pt.dataset.movielens.movie_categories()) for c in cats)
    assert 1.0 <= score <= 5.0


def test_double_buffer_chunked_large_array():
    """Arrays >32MB take the chunked threaded-put path; values intact."""
    big = np.arange(12 * 1024 * 1024, dtype="float32").reshape(12, -1)  # 48MB

    def src():
        yield {"x": big}

    (got,) = list(decorator.double_buffer(src)())
    np.testing.assert_array_equal(np.asarray(got["x"]), big)


def test_imikolov_synthetic():
    wd = pt.dataset.imikolov.build_dict(synthetic=True)
    assert "<unk>" in wd
    grams = list(pt.dataset.imikolov.train(wd, 5, synthetic=True)())
    assert len(grams) > 100
    assert all(len(g) == 5 for g in grams[:20])
    seqs = list(pt.dataset.imikolov.train(
        wd, 5, pt.dataset.imikolov.DataType.SEQ, synthetic=True)())
    assert all(isinstance(s, list) for s in seqs[:5])


def test_conll05_synthetic():
    wd, vd, ld = pt.dataset.conll05.get_dict()
    samples = list(pt.dataset.conll05.test()())
    assert len(samples) == 300
    s = samples[0]
    assert len(s) == 9  # 9 SRL feature slots
    words, *ctx, verb, mark, labels = s
    assert len(words) == len(labels) == len(mark)
    assert sum(mark) == 1  # exactly one predicate
    assert ld["B-V"] in labels


def test_wmt16_synthetic():
    samples = list(pt.dataset.wmt16.train(n_samples=50)())
    assert len(samples) == 50
    src, trg, trg_next = samples[0]
    assert src[0] == pt.dataset.wmt16.BOS and src[-1] == pt.dataset.wmt16.EOS
    assert trg[0] == pt.dataset.wmt16.BOS
    assert trg_next[-1] == pt.dataset.wmt16.EOS
    assert trg[1:] == trg_next[:-1]  # shifted pair
    d = pt.dataset.wmt16.get_dict("en", 1000)
    assert d["<s>"] == 0 and len(d) == 1000


# -- round-4 datasets (flowers/sentiment/voc2012/wmt14/mq2007 + image) -----


def test_flowers_synthetic():
    import paddle_tpu as pt

    sample = next(iter(pt.dataset.flowers.train(synthetic=True)()))
    im, label = sample
    assert im.shape[0] == 3 and im.dtype == np.float32
    assert 0 <= label < 102
    assert len(list(pt.dataset.flowers.valid(synthetic=True)())) > 0


def test_sentiment_synthetic():
    import paddle_tpu as pt

    d = pt.dataset.sentiment.get_word_dict(synthetic=True)
    assert len(d) >= 1000
    ids, label = next(iter(pt.dataset.sentiment.train(synthetic=True)()))
    assert label in (0, 1) and all(0 <= i < len(d) for i in ids)


def test_voc2012_synthetic():
    import paddle_tpu as pt

    im, lbl = next(iter(pt.dataset.voc2012.train(synthetic=True)()))
    assert im.shape[0] == 3 and lbl.ndim == 2
    assert lbl.max() >= 1  # an object mask exists


def test_wmt14_synthetic_transduction():
    import paddle_tpu as pt

    src, trg, nxt = next(iter(pt.dataset.wmt14.train(50)()))
    assert src[0] == 0 and src[-1] == 1  # <s> ... <e>
    assert trg[0] == 0 and nxt[-1] == 1
    assert trg[1:] == nxt[:-1]
    d_src, d_trg = pt.dataset.wmt14.get_dict(50)
    assert d_src[0] == "<s>" and d_trg[1] == "<e>"


def test_mq2007_synthetic_formats():
    import paddle_tpu as pt

    pair = next(iter(pt.dataset.mq2007.train("pairwise", synthetic=True)()))
    assert pair[0] == 1.0 and pair[1].shape == (46,)
    pt_feat, pt_label = next(
        iter(pt.dataset.mq2007.train("pointwise", synthetic=True)()))
    assert pt_feat.shape == (46,) and 0 <= pt_label <= 2
    labels, feats = next(
        iter(pt.dataset.mq2007.train("listwise", synthetic=True)()))
    assert feats.shape == (len(labels), 46)


def test_image_utils_numpy():
    from paddle_tpu.dataset import image as im_utils

    rs = np.random.RandomState(0)
    im = (rs.rand(40, 60, 3) * 255).astype("uint8")
    r = im_utils.resize_short(im, 32)
    assert min(r.shape[:2]) == 32
    c = im_utils.center_crop(r, 32)
    assert c.shape[:2] == (32, 32)
    rc = im_utils.random_crop(r, 24, rng=rs)
    assert rc.shape[:2] == (24, 24)
    fl = im_utils.left_right_flip(c)
    np.testing.assert_allclose(np.asarray(fl)[:, ::-1], c)
    chw = im_utils.to_chw(c)
    assert chw.shape == (3, 32, 32)
    t = im_utils.simple_transform(im, 36, 32, is_train=True, rng=rs)
    assert t.shape == (3, 32, 32)
    # bilinear resize sanity vs constant image
    const = np.full((10, 10, 3), 7.0, "float32")
    rr = im_utils.resize_short(const, 23)
    np.testing.assert_allclose(rr, 7.0, rtol=1e-5)
