"""LR schedules as graph ops over a persistable global-step counter
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py —
noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup).

The returned Variable is recomputed every step inside the same compiled XLA
program (the step counter increments as Scope state)."""

from __future__ import annotations

import math

from ..core import framework as fw
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from . import tensor as T


def _global_step_counter():
    """Persistable step counter incremented once per run."""
    helper = LayerHelper("global_step")
    counter = helper.create_global_variable(
        persistable=True,
        name=fw.unique_name("@LR_DECAY_COUNTER@"),
        shape=[1],
        dtype="float32",
    )
    helper.set_variable_initializer(counter, ConstantInitializer(0.0))
    helper.append_op(
        "increment",
        inputs={"X": [counter]},
        outputs={"Out": [counter]},
        attrs={"step": 1.0, fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.LRSched},
    )
    return counter


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference learning_rate_scheduler.py noam_decay)."""
    step = _global_step_counter()
    helper = LayerHelper("noam_decay")
    a = T.elementwise_pow(step, T.fill_constant([1], "float32", -0.5))
    b = T.scale(step, scale=warmup_steps ** -1.5)
    m = T.elementwise_min(a, b)
    return T.scale(m, scale=d_model ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = T.scale(step, scale=1.0 / decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op("floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    pow_ = T.elementwise_pow(
        T.fill_constant([1], "float32", decay_rate), div
    )
    return T.scale(pow_, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = T.scale(step, scale=1.0 / decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op("floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    helper = LayerHelper("natural_exp_decay")
    e = helper.create_variable_for_type_inference("float32")
    neg = T.scale(div, scale=-decay_rate)
    helper.append_op("exp", inputs={"X": [neg]}, outputs={"Out": [e]})
    return T.scale(e, scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = T.scale(step, scale=1.0 / decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op("floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    denom = T.scale(div, scale=decay_rate, bias=1.0)
    lr = T.fill_constant([1], "float32", float(learning_rate))
    return T.elementwise_div(lr, denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _global_step_counter()
    if cycle:
        # reference learning_rate_scheduler.py polynomial_decay: the decay
        # horizon stretches to decay_steps * max(ceil(step/decay_steps), 1).
        # XLA strength-reduces divide-by-constant to multiply-by-
        # reciprocal, so float32(21/7) can land at 3.0000002 and ceil
        # would overshoot a whole period exactly at cycle boundaries (187
        # of decay_steps in 2..2000 mis-round).  A relative epsilon
        # (2e-6 > the 1.2e-7 f32 rounding bound, and far below one step
        # for any practical horizon) makes ceil land on the true integer.
        from .ops import ceil

        ds = T.fill_constant([1], "float32", float(decay_steps))
        div = T.scale(T.elementwise_div(step, ds), scale=1.0 - 2e-6)
        ceil_div = ceil(div)
        ceil_div = T.elementwise_max(
            ceil_div, T.fill_constant([1], "float32", 1.0))
        horizon = T.elementwise_mul(ceil_div, ds)
        ratio = T.elementwise_div(step, horizon)
    else:
        capped = T.elementwise_min(
            step, T.fill_constant([1], "float32", float(decay_steps))
        )
        ratio = T.scale(capped, scale=1.0 / decay_steps)
    one_minus = T.scale(ratio, scale=-1.0, bias=1.0)
    poly = T.elementwise_pow(
        one_minus, T.fill_constant([1], "float32", float(power))
    )
    return T.scale(poly, scale=float(learning_rate) - end_learning_rate,
                   bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """Step function via sum of gated constants."""
    assert len(values) == len(boundaries) + 1
    step = _global_step_counter()
    helper = LayerHelper("piecewise_decay")
    lr = T.fill_constant([1], "float32", float(values[0]))
    for b, (v_prev, v_next) in zip(boundaries, zip(values[:-1], values[1:])):
        cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            "greater_than",
            inputs={"X": [step], "Y": [T.fill_constant([1], "float32", float(b))]},
            outputs={"Out": [cond]},
        )
        gate = T.cast(cond, "float32")
        delta = T.scale(gate, scale=float(v_next) - float(v_prev))
        lr = T.elementwise_add(lr, delta)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step_counter()
    helper = LayerHelper("cosine_decay")
    epoch_f = T.scale(step, scale=1.0 / step_each_epoch)
    fl = helper.create_variable_for_type_inference("float32")
    helper.append_op("floor", inputs={"X": [epoch_f]}, outputs={"Out": [fl]})
    angle = T.scale(fl, scale=math.pi / epochs)
    c = helper.create_variable_for_type_inference("float32")
    helper.append_op("cos", inputs={"X": [angle]}, outputs={"Out": [c]})
    return T.scale(T.scale(c, bias=1.0), scale=float(learning_rate) / 2.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Blend from start_lr to end_lr over warmup_steps, then the wrapped
    schedule (or constant)."""
    step = _global_step_counter()
    helper = LayerHelper("lr_warmup")
    frac = T.scale(step, scale=1.0 / warmup_steps)
    capped = T.elementwise_min(frac, T.fill_constant([1], "float32", 1.0))
    warm = T.scale(capped, scale=float(end_lr - start_lr), bias=float(start_lr))
    if isinstance(learning_rate, (int, float)):
        after = T.fill_constant([1], "float32", float(learning_rate))
    else:
        after = learning_rate
    cond = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        "less_than",
        inputs={"X": [step],
                "Y": [T.fill_constant([1], "float32", float(warmup_steps))]},
        outputs={"Out": [cond]},
    )
    gate = T.cast(cond, "float32")
    inv_gate = T.scale(gate, scale=-1.0, bias=1.0)
    return T.elementwise_add(
        T.elementwise_mul(warm, gate), T.elementwise_mul(after, inv_gate)
    )
