"""MNIST dataset (reference: python/paddle/dataset/mnist.py — idx-format
parser, train()/test() reader creators yielding (image[784] in [-1,1],
label)).

Offline fallback: `synthetic=True` (or PADDLE_TPU_SYNTH_DATA=1) yields a
deterministic separable pseudo-MNIST so training pipelines can run without
network egress."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

URL_PREFIX = "https://ossci-datasets.s3.amazonaws.com/mnist/"
TRAIN_IMAGE = "train-images-idx3-ubyte.gz"
TRAIN_LABEL = "train-labels-idx1-ubyte.gz"
TEST_IMAGE = "t10k-images-idx3-ubyte.gz"
TEST_LABEL = "t10k-labels-idx1-ubyte.gz"


def _parse(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    images = images.astype(np.float32) / 127.5 - 1.0
    return images, labels.astype(np.int64)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.int64)
    images = rng.rand(n, 784).astype(np.float32) * 0.1 - 1.0
    img2d = images.reshape(n, 28, 28)
    for i in range(n):
        k = int(labels[i])
        img2d[i, k * 2 : k * 2 + 4, k * 2 : k * 2 + 4] = 1.0
    return images, labels


def _use_synth(synthetic):
    return common.use_synthetic(synthetic)


def _reader_creator(image_file, label_file, synthetic, n_synth, seed):
    def reader():
        if _use_synth(synthetic):
            images, labels = _synthetic(n_synth, seed)
        else:
            images, labels = _parse(
                common.download(URL_PREFIX + image_file, "mnist", None),
                common.download(URL_PREFIX + label_file, "mnist", None),
            )
        for img, lbl in zip(images, labels):
            yield img, int(lbl)

    return reader


def train(synthetic=False):
    return _reader_creator(TRAIN_IMAGE, TRAIN_LABEL, synthetic, 6000, 0)


def test(synthetic=False):
    return _reader_creator(TEST_IMAGE, TEST_LABEL, synthetic, 1000, 1)
