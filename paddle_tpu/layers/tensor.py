"""Tensor-building layer fns (reference: python/paddle/fluid/layers/tensor.py
and parts of layers/nn.py for shape ops)."""

from __future__ import annotations

from ..core import framework as fw
from ..layer_helper import LayerHelper


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0,
         type=None, stop_gradient=True):
    """Declare a feed variable (reference: layers/io.py:39 `data`).
    `append_batch_size` prepends -1; the executor specializes the batch dim
    from the fed array (static shapes per compiled executable)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = fw.default_main_program().current_block()
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        stop_gradient=stop_gradient,
        is_data=True,
    )


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype, persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    from ..initializer import ConstantInitializer

    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        persistable=persistable,
        name=name or fw.unique_name("global_var"),
        shape=shape,
        dtype=dtype,
    )
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": fw.convert_dtype(dtype), "value": float(value)},
    )
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"out_dtype": fw.convert_dtype(dtype), "in_dtype": x.dtype},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        "concat", inputs={"X": list(input)}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    import numpy as np

    if isinstance(input, fw.Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": [input]}, outputs={"Out": [output]})
        if input.shape and not output.shape:
            output.shape = tuple(input.shape)
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(str(arr.dtype))
        helper.append_op(
            "assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "values": arr.ravel().tolist(),
            },
        )
    return output


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out) if act else out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in range(num or len(sections))
    ]
    helper.append_op(
        "split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "num": num, "sections": sections},
    )
    if input.shape and input.shape[dim] not in (None, -1):
        sizes = ([input.shape[dim] // num] * num if num
                 else list(sections))
        for o, sz in zip(outs, sizes):
            shp = list(input.shape)
            shp[dim] = sz
            o.shape = tuple(shp)
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": list(x)}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "arg_max", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "arg_min", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis},
    )
    return out, ids


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gather", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def scatter(input, index, updates, overwrite=True):
    helper = LayerHelper("scatter")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "one_hot", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"depth": depth}
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": value}
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "cumsum",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis, "exclusive": exclusive, "reverse": reverse},
    )
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def _reduce(op, input, dim, keep_dim, name):
    helper = LayerHelper(op, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    helper.append_op(
        op,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "dim": list(dim) if dim is not None else [0],
            "keep_dim": keep_dim,
            "reduce_all": dim is None,
        },
    )
    return out


def _elementwise(op, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op, name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        op, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "clip", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"min": min, "max": max}
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "clip_by_norm",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"max_norm": max_norm},
    )
    return out


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("equal", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]})
    return cond


def take_along_axis(input, index, axis=0):
    """Batched gather along `axis` (numpy semantics); see
    ops/tensor_ops.py take_along_axis."""
    helper = LayerHelper("take_along_axis")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "take_along_axis",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    """reference: layers/tensor.py fill_constant_batch_size_like."""
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    return out


def not_equal(x, y, cond=None):
    helper = LayerHelper("not_equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("not_equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def greater_than(x, y, cond=None):
    helper = LayerHelper("greater_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("greater_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def greater_equal(x, y, cond=None):
    helper = LayerHelper("greater_equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("greater_equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_equal(x, y, cond=None):
    helper = LayerHelper("less_equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("less_equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def _logical(op, x, y, out=None):
    helper = LayerHelper(op)
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    inputs = {"X": [x]} if y is None else {"X": [x], "Y": [y]}
    helper.append_op(op, inputs=inputs, outputs={"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_floordiv", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mod", x, y, axis, act, name)


def minus(x, y, name=None):
    """reference minus_op.cc (the v2-era x - y)."""
    helper = LayerHelper("minus", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("minus", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    out.shape = x.shape
    return out


def sign(x, name=None):
    helper = LayerHelper("sign", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sign", inputs={"X": [x]}, outputs={"Out": [out]})
    out.shape = x.shape
    return out


def expand_as(x, target_tensor, name=None):
    """Tile x to target_tensor's shape (reference expand_as_op.cc)."""
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "expand_as",
        inputs={"X": [x], "target_tensor": [target_tensor]},
        outputs={"Out": [out]},
    )
    out.shape = target_tensor.shape
    return out


def fill(shape, dtype="float32", value=0.0, name=None):
    """reference fill_op.cc: `value` is a flat element list sized to
    `shape`; a scalar here is expanded to the full size."""
    import numpy as _np

    helper = LayerHelper("fill", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    n = int(_np.prod(shape))
    vals = ([float(value)] * n if _np.isscalar(value)
            else [float(v) for v in _np.asarray(value).ravel()])
    helper.append_op(
        "fill",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": vals},
    )
    return out


def flatten(x, axis=1, name=None):
    """Flatten to 2D at `axis` (reference flatten_op.cc; emits flatten2
    like reshape->reshape2)."""
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype,
                                                       stop_gradient=True)
    helper.append_op(
        "flatten2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": axis},
    )
    if x.shape:
        import numpy as _np

        lead = int(_np.prod(x.shape[:axis])) if axis > 0 else 1
        tail = int(_np.prod(x.shape[axis:])) if axis < len(x.shape) else 1
        out.shape = (lead, tail)
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    """reference pad_op.cc: paddings = [before0, after0, before1, ...]."""
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape (reference pad_constant_like_op.cc)."""
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(
        "pad_constant_like",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"pad_value": float(pad_value)},
    )
    out.shape = x.shape
    return out


def unstack(x, axis=0, num=None):
    """Split x along axis into unstacked pieces (reference unstack_op.cc)."""
    helper = LayerHelper("unstack")
    if num is None:
        if not x.shape or x.shape[axis] in (None, -1):
            raise ValueError("unstack needs a static dim or explicit num")
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op(
        "unstack",
        inputs={"X": [x]},
        outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Recompute ids for a vocabulary shard (reference shard_index_op.cc)."""
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "shard_index",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"index_num": index_num, "nshards": nshards,
               "shard_id": shard_id, "ignore_value": ignore_value},
    )
    out.shape = input.shape
    return out


def is_empty(x, cond=None):
    """True iff x has zero elements (reference is_empty_op.cc)."""
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


def isfinite(x):
    """True iff all elements are finite (reference isfinite_op.cc)."""
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, name=None):
    """Densify a SelectedRows variable (reference
    get_tensor_from_selected_rows_op.cc)."""
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("get_tensor_from_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def merge_selected_rows(x, name=None):
    """Sum duplicate rows of a SelectedRows (reference
    merge_selected_rows_op.cc)."""
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out
