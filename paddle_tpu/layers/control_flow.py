"""Control-flow DSL (reference: python/paddle/fluid/layers/control_flow.py —
While:~800, Switch, array_write/array_read/array_length, increment...).

While builds a sub-block; the `while` op lowers it to lax.while_loop."""

from __future__ import annotations

from ..core import framework as fw
from ..layer_helper import LayerHelper
from . import tensor as T


class While:
    """reference control_flow.py While.

    with While(cond).block():  build the loop body; update cond inside.
    Every var written inside the body that exists outside is loop-carried.
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.main_program = self.helper.main_program

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.w = while_op

    def __enter__(self):
        self.sub_block = self.w.main_program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        prog = self.w.main_program
        if exc_type is not None:
            prog._rollback()  # don't leave the program inside the sub-block
            return False
        sub = self.sub_block
        prog._rollback()
        written = []
        seen = set()
        for op in sub.ops:
            for n in op.output_arg_names():
                if n and n not in seen:
                    seen.add(n)
                    written.append(n)
        parent = prog.current_block()
        out_names = [n for n in written if parent._find_var_recursive(n) is not None]
        parent.append_op(
            "while",
            inputs={"Condition": [self.w.cond_var]},
            outputs={"Out": out_names},
            attrs={"sub_block": sub},
        )
        return True


def array_write(x, i, array=None, capacity=64):
    helper = LayerHelper("array_write")
    if array is None:
        if x.shape is None or any(s is None or s < 0 for s in x.shape):
            raise ValueError(
                f"array_write: {x.name} has non-static shape {x.shape}; "
                "create the array explicitly with create_array(dtype, "
                "element_shape=<concrete shape>) and pass it in"
            )
        array = helper.create_variable(
            name=fw.unique_name("array"), dtype=x.dtype,
            type=fw.VarType.DENSE_TENSOR,
        )
        helper.append_op(
            "create_array",
            outputs={"Out": [array]},
            attrs={
                "capacity": capacity,
                "element_shape": list(x.shape),
                "dtype": x.dtype,
            },
        )
    # Out rebinds the array var itself (reference array_write mutates the
    # LoDTensorArray in place) — so writes inside a While body make the
    # array a loop-carried var instead of orphaning the update in a temp.
    helper.append_op(
        "write_to_array",
        inputs={"Array": [array], "X": [x], "I": [i]},
        outputs={"Out": [array]},
    )
    return array


def create_array(dtype, element_shape, capacity=64):
    helper = LayerHelper("create_array")
    array = helper.create_variable(name=fw.unique_name("array"), dtype=dtype)
    helper.append_op(
        "create_array",
        outputs={"Out": [array]},
        attrs={
            "capacity": capacity,
            "element_shape": list(element_shape),
            "dtype": dtype,
        },
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        "read_from_array", inputs={"X": [array], "I": [i]}, outputs={"Out": [out]}
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("array_length", inputs={"X": [array]}, outputs={"Out": [out]})
    return out


class Switch:
    """reference control_flow.py Switch — sequential case guards built on
    conditional_block."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []

    def case(self, condition):
        return _SwitchCaseGuard(self, condition)

    def default(self):
        return _SwitchCaseGuard(self, None)

    def __enter__(self):
        return self

    def __exit__(self, *args):
        return False


class _SwitchCaseGuard:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    @staticmethod
    def _and(a, b):
        helper = LayerHelper("logical_and")
        out = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            "logical_and", inputs={"X": [a], "Y": [b]}, outputs={"Out": [out]}
        )
        return out

    def __enter__(self):
        prog = self.switch.helper.main_program
        prev = self.switch.pre_not_conditions
        cond = self.condition
        if cond is None:
            # default: none of the previous conditions held
            assert prev, "Switch.default() before any case()"
            cond = prev[0]
            for c in prev[1:]:
                cond = self._and(cond, c)
        else:
            # first-match-wins (reference Switch): this case fires only if no
            # earlier case matched
            helper = LayerHelper("logical_not")
            notc = helper.create_variable_for_type_inference("bool")
            helper.append_op(
                "logical_not", inputs={"X": [cond]}, outputs={"Out": [notc]}
            )
            for c in prev:
                cond = self._and(cond, c)
            prev.append(notc)
        self.cond = cond
        self.sub_block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        prog = self.switch.helper.main_program
        if exc_type is not None:
            prog._rollback()  # don't leave the program inside the sub-block
            return False
        sub = self.sub_block
        prog._rollback()
        written = []
        seen = set()
        for op in sub.ops:
            for n in op.output_arg_names():
                if n and n not in seen:
                    seen.add(n)
                    written.append(n)
        parent = prog.current_block()
        outs = [n for n in written if parent._find_var_recursive(n) is not None]
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [self.cond]},
            outputs={"Out": outs},
            attrs={"sub_block": sub},
        )
        return True


class IfElse:
    """Row-wise conditional (reference: control_flow.py IfElse, ~L1500).

    TPU-first divergence: the reference gathers true/false row subsets and
    runs each block only on its subset; under XLA both blocks run on the
    FULL batch and results merge with a masked select — the standard
    dense-compute idiom (no dynamic shapes), same results.

        ie = layers.IfElse(cond)          # cond: [b, 1] bool
        with ie.true_block():
            ie.output(f_true(ie.input(x)))
        with ie.false_block():
            ie.output(f_false(ie.input(x)))
        (merged,) = ie()
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._outputs = {True: [], False: []}
        self._in_branch = None

    def _branch(self, flag):
        ie = self

        class _Guard:
            def __enter__(self):
                if ie._in_branch is not None:
                    raise RuntimeError("IfElse blocks do not nest")
                ie._in_branch = flag

            def __exit__(self, *exc):
                ie._in_branch = None
                return False

        return _Guard()

    def true_block(self):
        return self._branch(True)

    def false_block(self):
        return self._branch(False)

    def input(self, x):
        """The reference splits x by cond here; dense execution passes it
        through untouched."""
        if self._in_branch is None:
            raise RuntimeError("IfElse.input() outside a block")
        return x

    def output(self, *outs):
        if self._in_branch is None:
            raise RuntimeError("IfElse.output() outside a block")
        self._outputs[self._in_branch].extend(outs)

    def __call__(self):
        from . import tensor as T

        t_outs = self._outputs[True]
        f_outs = self._outputs[False]
        if len(t_outs) != len(f_outs):
            raise ValueError(
                f"IfElse: true block registered {len(t_outs)} outputs, "
                f"false block {len(f_outs)}")
        helper = self.helper
        cond_f = T.cast(self.cond, "float32")
        merged = []
        for tv, fv in zip(t_outs, f_outs):
            # out = cond * true + (1 - cond) * false ([b,1] broadcasts)
            not_cond = T.elementwise_sub(
                T.fill_constant([1], "float32", 1.0), cond_f)
            a = T.elementwise_mul(tv, cond_f)
            b = T.elementwise_mul(fv, not_cond)
            merged.append(T.elementwise_add(a, b))
        return merged
