"""NCE, hierarchical sigmoid, linear-chain CRF + viterbi decoding
(reference: operators/nce_op.cc, hierarchical_sigmoid_op.cc,
linear_chain_crf_op.cc, crf_decoding_op.cc; book models word2vec /
label_semantic_roles)."""

import itertools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr

from op_test import OpTest

rng = np.random.RandomState(11)


# ---------------------------------------------------------------------------
# linear_chain_crf / crf_decoding vs brute force
# ---------------------------------------------------------------------------


def _logsumexp(xs):
    m = max(xs)
    return m + np.log(sum(np.exp(x - m) for x in xs))


def _crf_score(emission, start, stop, trans, path):
    s = start[path[0]] + stop[path[-1]]
    s += sum(emission[t, path[t]] for t in range(len(path)))
    s += sum(trans[path[t - 1], path[t]] for t in range(1, len(path)))
    return float(s)


def _crf_brute(emission, transition, label, length):
    start, stop, trans = transition[0], transition[1], transition[2:]
    b, t_max, n = emission.shape
    nll, best = [], []
    for i in range(b):
        ln = int(length[i])
        scores = {
            p: _crf_score(emission[i], start, stop, trans, p)
            for p in itertools.product(range(n), repeat=ln)
        }
        log_z = _logsumexp(list(scores.values()))
        gold = scores[tuple(label[i, :ln])]
        nll.append(log_z - gold)
        bp = max(scores, key=scores.get)
        best.append(list(bp) + [0] * (t_max - ln))
    return np.array(nll, "float32")[:, None], np.array(best, "int64")


class TestLinearChainCRF(OpTest):
    op_type = "linear_chain_crf"

    def _data(self):
        b, t_max, n = 3, 4, 3
        emission = rng.uniform(-1, 1, (b, t_max, n)).astype("float32")
        transition = rng.uniform(-0.5, 0.5, (n + 2, n)).astype("float32")
        label = rng.randint(0, n, (b, t_max)).astype("int64")
        length = np.array([4, 2, 3], "int64")
        return emission, transition, label, length

    def test_nll_matches_brute_force(self):
        emission, transition, label, length = self._data()
        nll, _ = _crf_brute(emission, transition, label, length)
        self.check_output(
            {"Emission": emission, "Transition": transition,
             "Label": label, "Length": length},
            {"LogLikelihood": nll},
            atol=1e-4,
        )

    def test_grads(self):
        emission, transition, label, length = self._data()
        self.check_grad(
            {"Emission": emission, "Transition": transition,
             "Label": label, "Length": length},
            {"LogLikelihood": ["nll"]},
            ["Emission", "Transition"],
        )


class TestCRFDecoding(OpTest):
    op_type = "crf_decoding"

    def test_viterbi_matches_brute_force(self):
        b, t_max, n = 3, 4, 3
        emission = rng.uniform(-1, 1, (b, t_max, n)).astype("float32")
        transition = rng.uniform(-0.5, 0.5, (n + 2, n)).astype("float32")
        length = np.array([4, 3, 1], "int64")
        _, best = _crf_brute(
            emission, transition,
            np.zeros((b, t_max), "int64"), length)
        self.check_output(
            {"Emission": emission, "Transition": transition,
             "Length": length},
            {"ViterbiPath": best},
        )


def test_crf_train_and_decode():
    """Sequence labeling: tag = token % n_tags; CRF training must push
    viterbi accuracy high (label_semantic_roles book-model pattern)."""
    vocab, n_tags, t_max, bs = 12, 4, 6, 32
    word = layers.data(name="word", shape=[t_max], dtype="int64")
    label = layers.data(name="label", shape=[t_max], dtype="int64")
    emb = layers.embedding(
        layers.reshape(word, [-1, t_max, 1]), size=[vocab, 16])
    emission = layers.fc(emb, size=n_tags, num_flatten_dims=2)
    crf_cost = layers.linear_chain_crf(
        emission, label, param_attr=ParamAttr(name="crf_w"))
    avg = layers.mean(crf_cost)
    pt.optimizer.AdamOptimizer(learning_rate=0.05).minimize(avg)
    path = layers.crf_decoding(emission, param_attr=ParamAttr(name="crf_w"))

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    def batch():
        w = rng.randint(0, vocab, (bs, t_max)).astype("int64")
        return {"word": w, "label": (w % n_tags).astype("int64")}

    losses = []
    feed = None
    for _ in range(60):
        feed = batch()
        (lv,) = exe.run(feed=feed, fetch_list=[avg])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    prog = pt.default_main_program().clone(for_test=True)
    (p,) = exe.run(prog, feed=feed, fetch_list=[path])
    acc = float((np.asarray(p) == feed["word"] % n_tags).mean())
    assert acc > 0.9, acc


# ---------------------------------------------------------------------------
# NCE
# ---------------------------------------------------------------------------


class TestNCEGrad(OpTest):
    op_type = "nce"

    def test_grads(self):
        b, d, v = 4, 6, 9
        x = rng.uniform(-1, 1, (b, d)).astype("float32")
        label = rng.randint(0, v, (b, 1)).astype("int64")
        w = rng.uniform(-1, 1, (v, d)).astype("float32")
        bias = rng.uniform(-1, 1, (v,)).astype("float32")
        self.check_grad(
            {"Input": x, "Label": label, "Weight": w, "Bias": bias},
            {"Cost": ["cost"]},
            ["Input", "Weight", "Bias"],
            attrs={"num_total_classes": v, "num_neg_samples": 5, "seed": 3},
        )


def test_nce_word2vec_trains():
    """word2vec-style: predict target = sum(context) % vocab via NCE
    (dist_word2vec.py pattern)."""
    vocab, d, bs = 20, 12, 64
    ctx = layers.data(name="ctx", shape=[2, 1], dtype="int64")
    target = layers.data(name="target", shape=[1], dtype="int64")
    emb = layers.embedding(ctx, size=[vocab, d])
    feat = layers.reshape(emb, [-1, 2 * d])
    cost = layers.nce(feat, target, num_total_classes=vocab,
                      num_neg_samples=6, seed=7)
    avg = layers.mean(cost)
    pt.optimizer.AdamOptimizer(learning_rate=0.02).minimize(avg)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(80):
        c = rng.randint(0, vocab, (bs, 2, 1)).astype("int64")
        t = (c.sum(axis=1) % vocab).astype("int64")
        (lv,) = exe.run(feed={"ctx": c, "target": t}, fetch_list=[avg])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# hierarchical sigmoid
# ---------------------------------------------------------------------------


def _hsigmoid_ref(x, label, w, bias, num_classes):
    """Straight-loop mirror of the complete-binary-tree path walk."""
    b = x.shape[0]
    out = np.zeros((b, 1), "float32")
    for i in range(b):
        n = int(label[i]) + num_classes
        j = 0
        while (n >> (j + 1)) >= 1:
            anc = n >> (j + 1)
            bit = (n >> j) & 1
            z = float(x[i] @ w[anc - 1] + bias[anc - 1])
            out[i, 0] += np.log1p(np.exp((1 - 2 * bit) * z))
            j += 1
    return out


class TestHSigmoid(OpTest):
    op_type = "hierarchical_sigmoid"

    def test_output_and_grad(self):
        b, d, v = 5, 6, 11
        x = rng.uniform(-1, 1, (b, d)).astype("float32")
        label = rng.randint(0, v, (b, 1)).astype("int64")
        w = rng.uniform(-1, 1, (v - 1, d)).astype("float32")
        bias = rng.uniform(-1, 1, (v - 1,)).astype("float32")
        expected = _hsigmoid_ref(x, label, w, bias, v)
        self.check_output(
            {"X": x, "Label": label, "W": w, "Bias": bias},
            {"Out": expected},
            attrs={"num_classes": v},
            atol=1e-4,
        )
        self.check_grad(
            {"X": x, "Label": label, "W": w, "Bias": bias},
            {"Out": ["out"]},
            ["X", "W", "Bias"],
            attrs={"num_classes": v},
        )


def test_hsigmoid_trains():
    vocab, d, bs = 16, 10, 64
    x = layers.data(name="x", shape=[d], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    h = layers.fc(x, size=d, act="tanh")
    cost = layers.hsigmoid(h, label, num_classes=vocab)
    avg = layers.mean(cost)
    pt.optimizer.AdamOptimizer(learning_rate=0.03).minimize(avg)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    proto = rng.randn(vocab, d).astype("float32")
    losses = []
    for _ in range(80):
        lab = rng.randint(0, vocab, (bs, 1)).astype("int64")
        xs = proto[lab[:, 0]] + 0.1 * rng.randn(bs, d).astype("float32")
        (lv,) = exe.run(feed={"x": xs, "label": lab}, fetch_list=[avg])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
