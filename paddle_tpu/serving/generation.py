"""Continuous token-level batching: the decode-aware serving model.

Where the PR-6 DynamicBatcher coalesces whole REQUESTS into one padded
forward pass, generation requests are thousands of per-token steps — so
the unit of batching here is the DECODE STEP (Orca OSDI'22 iteration-
level scheduling, mapped onto the executor's donated-cache machinery):

  * a GenerationServingModel owns one GenerationSession whose programs
    are compiled for a fixed SLOT count (the decode batch dimension);
    each slot is one cache lane ([L, slots, max_t, h, dh]);
  * the ContinuousBatcher scheduler thread runs one decode program call
    per iteration for ALL occupied slots (active-mask feed) — in-flight
    sequences share every step;
  * new requests join between steps: the prefill program runs with an
    active mask selecting only the joining slots (the kv_cache_update
    Active input keeps every other slot's cache rows and counters
    untouched), so a late arrival costs one prefill call and ZERO
    retraces — both programs were compiled at warmup and their feed
    shapes never change;
  * finished sequences (eos or token budget) retire their slot at the
    end of the step; the slot is immediately reusable.

Observability (PR-1 registry): per-model time-to-first-token histogram
(serving.gen.<name>.ttft_seconds), generated-token + decode-step
counters (tokens/sec = rate(tokens)), request latency histogram,
occupancy gauge.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from ..flags import FLAGS
from ..monitor import tracing
from .batcher import (_STOP, _fail_waiters, _record_shed, _slo_bad,
                      CircuitBreaker, Overloaded, Unavailable)

# TTFT is dominated by queue wait + one prefill + one decode step: a
# finer-than-default ladder at the low end keeps p50 informative
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0)


class GenerationConfig:
    """Policy + model geometry for one generation serving model."""

    __slots__ = ("name", "slots", "max_tokens", "model_kw")

    def __init__(self, name: str, slots: Optional[int] = None,
                 max_tokens: Optional[int] = None, **model_kw):
        from ..flags import FLAGS

        if not name or "/" in name or ":" in name:
            raise ValueError(f"model name {name!r} must be URL-path safe")
        self.name = name
        self.slots = int(slots if slots is not None
                         else FLAGS.serving_decode_slots)
        # model_kw forwards to models/transformer.build_generation_programs
        # (vocab sizes, depth, src_seq_len, max_out_len, bos/eos, ...)
        self.model_kw = dict(model_kw)
        self.max_tokens = int(max_tokens if max_tokens is not None
                              else self.model_kw.get("max_out_len", 16))


class _GenRequest:
    __slots__ = ("prompt", "max_tokens", "t_enqueue", "deadline",
                 "t_first_token", "event", "tokens", "error", "meta",
                 "cancelled", "trace", "t_done", "t_joined")

    def __init__(self, prompt, max_tokens, timeout=None, trace=None):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.t_enqueue = time.perf_counter()
        # the scheduler-side mirror of the client timeout: expired
        # requests never admit, and an expired SLOT retires at the next
        # iteration boundary even if the client thread is gone
        self.deadline = (None if timeout is None
                         else self.t_enqueue + float(timeout))
        self.t_first_token = None
        self.event = threading.Event()
        self.tokens: List[int] = []
        self.error = None
        self.meta = None
        # set by a timed-out client: the scheduler retires the slot at
        # the next step instead of decoding the abandoned sequence to
        # its full budget (repeated timeouts must not starve the slots)
        self.cancelled = False
        # request trace (None unless FLAGS_trace_requests): the prefill
        # + per-iteration decode spans attach as the slot is scheduled
        self.trace = trace
        self.t_done = None    # scheduler finish stamp (trace only)
        self.t_joined = None  # prefill-done stamp (trace only)


class GenerationServingModel:
    """One generation program pair + cache state, servable via the
    continuous batcher.  Requires the KV-cache route (FLAGS_kv_cache):
    continuous batching is meaningless when every step recomputes the
    full prefix."""

    def __init__(self, config: GenerationConfig, scope=None,
                 session=None):
        from ..core import executor as ex
        from ..flags import FLAGS
        from ..generation import GenerationSession
        from ..models.transformer import build_generation_programs

        if not FLAGS.kv_cache:
            raise ValueError(
                "generation serving requires FLAGS_kv_cache=1 (the "
                "recompute oracle has no per-slot cache for continuous "
                "batching to schedule)")
        self.config = config
        self.name = config.name
        if session is None:
            kw = dict(config.model_kw)
            kw["batch_size"] = config.slots
            kw.setdefault("strategy", "greedy")
            programs = build_generation_programs(beam_size=None, **kw)
            session = GenerationSession(programs,
                                        scope=scope or ex.Scope())
        p = session.p
        if p.beam_size is not None or not p.kv_cache:
            raise ValueError(
                "generation serving needs a non-beam KV-cached session")
        self.session = session
        self.slots = p.batch_size
        self.max_prompt_len = p.src_seq_len
        self.max_tokens = min(config.max_tokens, p.max_out_len)
        self.bos_id, self.eos_id = p.bos_id, p.eos_id
        self.vocab = p.src_vocab_size
        # resident KV footprint (self + cross caches): the capacity
        # denominator of generation.<name>.tokens_per_sec_per_hbm_gb
        self.kv_cache_bytes = (p.self_cache.hbm_bytes
                               + p.cross_cache.hbm_bytes)
        # paged cache (FLAGS_paged_kv_cache at build time): the batcher
        # switches admission to block-budget accounting, shares same-
        # prefix cross blocks, and guards forked self blocks with COW
        self.paged = bool(getattr(p, "paged", False))
        # slots whose SELF blocks may be shared (fork_slot): the per-
        # step COW guard walks only this set, so unforked serving pays
        # nothing for the copy-on-write machinery
        self._shared_self_slots: set = set()
        self.ready = False

    def fork_slot(self, dst_slot: int, src_slot: int) -> None:
        """Clone src_slot's sequence state into dst_slot by SHARING its
        self-cache blocks (ref++) — the speculative-decode skeleton on
        the paged cache.  Counters and self-feed state are copied
        host-side; the first divergent append on either slot triggers
        the batcher's copy-on-write guard, so the clone costs zero HBM
        until the sequences actually diverge."""
        import jax.numpy as jnp

        if not self.paged:
            raise ValueError("fork_slot requires the paged KV cache")
        p = self.session.p
        scope = self.session.scope
        rows = int(p.self_cache.lengths(scope)[src_slot])
        p.self_cache.fork_slot(scope, dst_slot, src_slot, rows)

        def patch(name, value):
            arr = np.array(scope.find_var(name))
            arr[dst_slot] = arr[src_slot] if value is None else value
            scope.set_var(name, jnp.asarray(arr))

        patch(p.self_cache.len_name, None)
        patch(p.cross_cache.len_name, None)
        if getattr(p, "self_feed_token", False):
            patch(p.last_tok_name, None)
            patch(p.finished_name, None)
        self._shared_self_slots.update((dst_slot, src_slot))

    def init_params(self):
        self.session.init_params()

    def warmup(self) -> int:
        """Compile prefill + decode with an all-inactive mask (no slot
        state is touched); production steps then never pay a trace."""
        zeros_active = np.zeros((self.slots,), np.float32)
        self.session.prefill(
            np.zeros((self.slots, self.max_prompt_len, 1), np.int64),
            active=zeros_active)
        self.session.decode_step(
            np.full((self.slots,), self.bos_id, np.int64),
            active=zeros_active)
        self.ready = True
        self.publish_attribution()
        return 2

    def publish_attribution(self) -> None:
        """Static capacity/attribution gauges for this model: KV-cache
        HBM bytes plus per-program roofline costs of the prefill and
        decode programs (op/launch counts, predicted step time, and the
        decode launch-bound fraction ROADMAP item 1 tracks).  One
        enabled() read when FLAGS_monitor is off."""
        from .. import monitor

        if not monitor.enabled():
            return
        from ..analysis.costmodel import cost_program, publish_cost

        monitor.gauge(
            f"generation.{self.name}.kv_cache_hbm_bytes").set(
            self.kv_cache_bytes)
        monitor.gauge(f"generation.{self.name}.slots").set(self.slots)
        p = self.session.p
        for tag, prog in (("prefill", p.prefill), ("decode", p.decode)):
            cost = cost_program(prog, name=f"gen.{self.name}.{tag}",
                                batch_size=self.slots)
            publish_cost(cost)
            if tag == "decode":
                # the megastep scoreboard: fusion-corrected launches per
                # generated token (FLAGS_fused_decode_step collapses the
                # per-layer op chains into 1-2 launches each)
                monitor.gauge(
                    f"generation.{self.name}.launches_per_token").set(
                    cost.n_launches_fused)

    @property
    def compile_count(self) -> int:
        return self.session.compile_count

    def readiness_detail(self) -> dict:
        """Structured readiness for /health (router probe): generation's
        'ladder' is the prefill+decode program pair compiled at warmup."""
        return {
            "ready": self.ready,
            "state": "ready" if self.ready else "warming",
            "type": "generation",
            "warm_buckets": 2 if self.ready else 0,
            "ladder_size": 2,
        }

    def info(self) -> dict:
        from .. import monitor

        reg = monitor.default_registry()
        ttft = reg.get(f"serving.gen.{self.name}.ttft_seconds")
        toks = reg.get(f"serving.gen.{self.name}.tokens")
        info = {
            "name": self.name,
            "type": "generation",
            "ready": self.ready,
            "slots": self.slots,
            "max_prompt_len": self.max_prompt_len,
            "max_tokens": self.max_tokens,
            "vocab_size": self.vocab,
            "bos_id": self.bos_id,
            "eos_id": self.eos_id,
            "compiled_signatures": self.compile_count,
            "tokens_generated": toks.value if toks is not None else 0,
        }
        if ttft is not None and ttft.count:
            info["ttft_s"] = {"p50": ttft.quantile(0.5),
                              "p99": ttft.quantile(0.99),
                              "count": ttft.count}
        slo = tracing.slo_info(self.name)
        if slo is not None:
            info["slo"] = slo
        return info


class ContinuousBatcher:
    """One scheduler thread per generation model: admits requests into
    free cache slots at prefill and coalesces every occupied slot's next
    token into one decode-program call."""

    def __init__(self, model: GenerationServingModel):
        self.model = model
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._draining = False
        # admission-wait EWMA (scheduler-written, submit-read): the
        # Retry-After basis for a shed :generate request
        self._wait_ewma_s = 0.0
        # consecutive prefill/decode failures open the breaker exactly
        # like batch failures do on the predict path (gauge/flight name
        # prefix "gen.<model>")
        self.breaker = CircuitBreaker(f"gen.{model.name}")
        # slot state (scheduler-thread-private once started)
        self._slot_req: List[Optional[_GenRequest]] = \
            [None] * model.slots
        self._slot_token = np.full((model.slots,), model.bos_id, np.int64)
        self._pending_join: collections.deque = collections.deque()
        # capacity-efficiency EWMA (scheduler-thread-private): emitted
        # tokens/sec smoothed across decode iterations, divided by the
        # resident KV-cache GB — ROADMAP item 2's gate metric
        self._tps_ewma: Optional[float] = None
        self._t_last_decode: Optional[float] = None
        # iteration clock anchor (tracing only): each decode.step span
        # starts where the previous iteration's span ENDED, so the
        # scheduler's between-iteration overhead (queue poll, span
        # bookkeeping, counters) is attributed to the iteration instead
        # of leaking into the unattributed remainder; reset while idle
        self._t_anchor: Optional[float] = None
        # paged-cache bookkeeping (scheduler-thread-private): which
        # blocks each slot owns, and the shared-prefix registry mapping
        # a full-prompt content hash to the cross blocks its prefill
        # populated.  Arming dynamic mode re-points every table entry at
        # the trap block, so the warmup's all-inactive prefill/decode
        # stays harmless whether it runs before or after construction.
        self._slot_blocks: List[Optional[dict]] = [None] * model.slots
        self._prefix_map: dict = {}
        if model.paged:
            p = model.session.p
            scope = model.session.scope
            p.self_cache.reset_dynamic(scope)
            p.cross_cache.reset_dynamic(scope)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._draining = False
        self._thread = threading.Thread(
            target=self._loop,
            name=f"serving-genbatcher-{self.model.name}", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        if self._running:
            self._running = False
            self._queue.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # a dead/never-started scheduler can't run its finally-drain:
        # fail queued waiters with the named 503 instead of leaving
        # them to ride out their full client timeout
        self._fail_queued()

    def begin_drain(self) -> None:
        """Stop admitting (submit -> 503); in-flight sequences and
        already-admitted joins still run to completion."""
        self._draining = True

    def drain(self, timeout: float) -> bool:
        """begin_drain(), then wait (bounded) for every occupied slot
        and queued join to finish; True when fully drained in budget."""
        self.begin_drain()
        t_end = time.monotonic() + max(0.0, timeout)
        while True:
            idle = self._idle()
            if idle:
                time.sleep(0.02)  # re-confirm across the join hand-off
                idle = self._idle()
            if idle or time.monotonic() >= t_end:
                return idle
            time.sleep(0.02)

    def _idle(self) -> bool:
        return (self._queue.qsize() == 0 and not self._pending_join
                and not any(r is not None for r in self._slot_req))

    @property
    def scheduler_alive(self) -> bool:
        """False only when the batcher should be running but its
        scheduler thread died — the /health `scheduler_dead` probe."""
        if not self._running:
            return True
        return self._thread is not None and self._thread.is_alive()

    def _fail_queued(self) -> None:
        _fail_waiters(
            self._queue, self._pending_join,
            f"generation batcher for {self.model.name!r} stopped")

    # -- client side -----------------------------------------------------
    def submit(self, prompt, max_tokens: Optional[int] = None,
               timeout: float = 60.0, trace=None):
        """Block until the sequence finishes; returns (tokens, meta)."""
        from .. import monitor

        model = self.model
        if trace is not None:
            t_submit0 = time.perf_counter()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > model.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the model's "
                f"max_prompt_len {model.max_prompt_len}")
        # id 0 is the pad id (the cross-attention length mask assumes
        # padding is TRAILING — a mid-prompt 0 would be attended as a
        # real token, unlike the training-side pad bias): reject it
        bad = [t for t in prompt if not 0 < t < model.vocab]
        if bad:
            raise ValueError(
                f"prompt ids must be in (0, {model.vocab}) — 0 is the "
                f"pad id: {bad[:5]}")
        mt = (model.max_tokens if max_tokens is None
              else min(int(max_tokens), model.max_tokens))
        if mt <= 0:
            raise ValueError(f"max_tokens must be positive, got {mt}")
        # -- admission control (validated requests only: bad input is a
        # 4xx, not a shed) ------------------------------------------------
        if self._draining:
            _slo_bad(self.model.name)
            tracing.reject(trace, "draining")
            raise Unavailable(
                f"generation model {model.name!r} is draining",
                reason="draining")
        depth = FLAGS.serving_max_queue_depth
        if (depth > 0
                and self._queue.qsize() + len(self._pending_join) >= depth):
            # cache-slot exhaustion beyond the bounded wait-queue fails
            # fast with 429 — never a silent stall behind full slots
            ra = self.retry_after()
            _record_shed(f"serving.gen.{model.name}.shed_total",
                         "gen_queue_depth", ra, model=model.name)
            _slo_bad(self.model.name)
            tracing.reject(trace, "gen_queue_depth")
            raise Overloaded(
                f"generation model {model.name!r}: slot wait-queue full "
                f"({depth} waiting)",
                retry_after_s=ra, reason="gen_queue_depth")
        if not self.breaker.allow():
            if monitor.enabled():
                monitor.counter(
                    f"serving.gen.{model.name}.breaker_rejected_total"
                ).inc()
            _slo_bad(self.model.name)
            tracing.reject(trace, "breaker_open")
            raise Unavailable(
                f"generation model {model.name!r}: circuit breaker open "
                f"({FLAGS.serving_breaker_threshold} consecutive "
                "prefill/decode failures; half-open probe pending)",
                retry_after_s=FLAGS.serving_breaker_cooldown_s,
                reason="breaker_open")
        req = _GenRequest(prompt, mt, timeout=timeout, trace=trace)
        if trace is not None:
            trace.add_span("admission", tracing.pc_to_epoch(t_submit0),
                           tracing.pc_to_epoch(req.t_enqueue),
                           outcome="admitted",
                           prompt_len=len(prompt), max_tokens=mt)
        self._queue.put(req)
        if not req.event.wait(timeout):
            req.cancelled = True  # scheduler retires the slot next step
            req.error = TimeoutError(
                f"generation not finished within {timeout}s "
                f"(model {model.name!r})")
            if monitor.enabled():
                monitor.counter(
                    f"serving.gen.{model.name}.timeouts").inc()
                _slo_bad(self.model.name)
            if trace is not None:
                trace.finish(status="timeout")
            raise req.error
        if req.error is not None:
            _slo_bad(self.model.name)
            raise req.error
        if trace is not None:
            # the scheduler-side finish -> this waiter waking (the last
            # hand-off, measured waiter-side so the wakeup gap is
            # attributed); plus the TTFT linkage on the root span
            t_wake = time.perf_counter()
            if req.t_done is not None:
                trace.add_span("deliver",
                               tracing.pc_to_epoch(req.t_done),
                               tracing.pc_to_epoch(t_wake))
            meta = req.meta or {}
            trace.set_attr(tokens=len(req.tokens),
                           ttft_ms=meta.get("ttft_ms"),
                           finished=meta.get("finished"),
                           slot=meta.get("slot"))
        if monitor.enabled():
            dt = time.perf_counter() - req.t_enqueue
            monitor.counter(f"serving.gen.{model.name}.requests").inc()
            monitor.histogram(
                f"serving.gen.{model.name}.request_seconds").observe(dt)
            tracing.slo_observe(model.name, dt, ok=True)
        return req.tokens, req.meta

    def retry_after(self) -> float:
        """Suggested back-off for a shed :generate request: ~2x the
        observed admission-wait EWMA, capped at 30s."""
        return min(30.0, max(0.05, 2.0 * self._wait_ewma_s))

    # -- scheduler side --------------------------------------------------
    def _drain_queue(self, block: bool) -> bool:
        """Move arrivals into the pending-join deque; returns False on
        STOP."""
        while True:
            try:
                item = (self._queue.get(timeout=0.05) if block
                        else self._queue.get_nowait())
            except queue.Empty:
                return True
            if item is _STOP:
                return False
            self._pending_join.append(item)
            block = False

    # -- paged-cache plumbing (no-ops in ring mode) -----------------------
    def _publish_blocks(self) -> None:
        """Block-pool occupancy gauges (self + cross pools summed) —
        the generation.<m>.blocks_{used,free} capacity signal."""
        from .. import monitor

        if not (self.model.paged and monitor.enabled()):
            return
        p = self.model.session.p
        used = free = 0
        for cache in (p.self_cache, p.cross_cache):
            alloc = cache.allocator
            if alloc is not None:
                used += alloc.used_count
                free += alloc.free_count
        m = self.model.name
        monitor.gauge(f"generation.{m}.blocks_used").set(used)
        monitor.gauge(f"generation.{m}.blocks_free").set(free)

    def _patch_sharer_state(self, slot: int, src_len: int) -> None:
        """A shared-prefix joiner skips prefill, so the per-slot scope
        state the masked prefill would have reset is patched host-side:
        cross length = the shared prefix's, self length = 0, and the
        self-feed latch re-armed at BOS.  Zero-retrace: scope rewrites
        between steps never change the compile key."""
        import jax.numpy as jnp

        sess = self.model.session
        scope, p = sess.scope, sess.p

        def patch(name, value):
            arr = np.array(scope.find_var(name))
            arr[slot] = value
            scope.set_var(name, jnp.asarray(arr))

        patch(p.cross_cache.len_name, src_len)
        patch(p.self_cache.len_name, 0)
        if getattr(p, "self_feed_token", False):
            patch(p.last_tok_name, self.model.bos_id)
            patch(p.finished_name, 0)

    def _release_slot(self, slot: int) -> None:
        """Return a retired slot's blocks to the pools: self blocks are
        freed outright; cross blocks are deref'd (shared-prefix sharers
        keep them alive) and the prefix registry entry is dropped when
        its last user leaves."""
        from .. import monitor

        info = self._slot_blocks[slot]
        if info is None:
            return
        self._slot_blocks[slot] = None
        p = self.model.session.p
        if info["self"]:
            p.self_cache.allocator.free(info["self"])
        if info["cross"]:
            p.cross_cache.allocator.free(info["cross"])
        key = info["key"]
        if key is not None:
            ent = self._prefix_map.get(key)
            if ent is not None:
                ent["users"] -= 1
                if ent["users"] <= 0:
                    del self._prefix_map[key]
        self.model._shared_self_slots.discard(slot)
        if monitor.enabled():
            monitor.flight.record(
                "kv.page", event="free", model=self.model.name,
                slot=slot, self_blocks=len(info["self"]),
                cross_blocks=len(info["cross"]))
        self._publish_blocks()

    def _admit(self) -> None:
        """Prefill every pending request that fits a free slot — ONE
        masked prefill call regardless of how many join this round."""
        from .. import monitor

        model = self.model
        free = [i for i, r in enumerate(self._slot_req) if r is None]
        if not free or not self._pending_join:
            return
        now = time.perf_counter()
        joining = []
        while free and self._pending_join:
            req = self._pending_join.popleft()
            if req.cancelled:  # timed out while still queued
                if req.trace is not None:
                    req.trace.finish(status="cancelled")
                continue
            if req.deadline is not None and now >= req.deadline:
                # expired while waiting for a slot: never admitted, never
                # prefilled — the deadline-propagation contract
                req.error = TimeoutError(
                    f"request expired before a cache slot freed "
                    f"(model {model.name!r})")
                if req.trace is not None:
                    req.trace.add_span(
                        "queue.wait", tracing.pc_to_epoch(req.t_enqueue),
                        tracing.pc_to_epoch(now))
                    req.trace.finish(status="expired")
                req.event.set()
                if monitor.enabled():
                    # no SLO count here: the waiter sees req.error
                    # and counts the bad event once
                    monitor.counter(
                        f"serving.gen.{model.name}.expired_dropped_total"
                    ).inc()
                    monitor.counter("serving.expired_dropped_total").inc()
                continue
            if model.paged:
                p = model.session.p
                key = tuple(req.prompt)
                ent = self._prefix_map.get(key)
                need_self = p.self_cache.blocks_for(req.max_tokens)
                need_cross = (0 if ent is not None else
                              p.cross_cache.blocks_for(len(req.prompt)))
                if (p.self_cache.allocator.free_count < need_self
                        or p.cross_cache.allocator.free_count
                        < need_cross):
                    # admission is by HBM bytes now, not slot count: a
                    # free slot without block budget keeps the request
                    # queued (FIFO head) until a retirement frees pages
                    self._pending_join.appendleft(req)
                    break
            # admission-wait EWMA (Retry-After basis for sheds)
            self._wait_ewma_s += 0.2 * (
                (now - req.t_enqueue) - self._wait_ewma_s)
            slot = free.pop(0)
            self._slot_req[slot] = req
            self._slot_token[slot] = model.bos_id
            if not model.paged:
                joining.append((slot, req, False))
                continue
            # map the slot's blocks before the masked prefill.  A
            # prefix HIT shares the registered cross blocks (ref++) and
            # skips prefill entirely; a MISS allocates fresh cross
            # blocks, registers them, and prefills as the prefix's
            # leader.  Same-round sharers see the leader's entry at
            # once, so N identical prompts in one round still cost one
            # prefill lane.
            scope = model.session.scope
            self_blocks = p.self_cache.allocator.alloc(need_self)
            p.self_cache.set_table_row(scope, slot, self_blocks)
            if ent is not None:
                p.cross_cache.allocator.share(ent["blocks"])
                p.cross_cache.set_table_row(scope, slot, ent["blocks"])
                ent["users"] += 1
                self._slot_blocks[slot] = {
                    "self": self_blocks, "cross": list(ent["blocks"]),
                    "key": key}
                self._patch_sharer_state(slot, ent["src_len"])
                joining.append((slot, req, True))
                if monitor.enabled():
                    monitor.counter(
                        f"generation.{model.name}.prefix_hits_total"
                    ).inc()
                    monitor.flight.record(
                        "kv.page", event="hit", model=model.name,
                        slot=slot, shared_blocks=len(ent["blocks"]),
                        self_blocks=len(self_blocks))
            else:
                cross_blocks = p.cross_cache.allocator.alloc(need_cross)
                p.cross_cache.set_table_row(scope, slot, cross_blocks)
                # prompt ids are validated nonzero (submit rejects the
                # pad id), so the prefill's trailing-pad length scan
                # lands exactly on len(prompt)
                self._prefix_map[key] = {"blocks": cross_blocks,
                                         "src_len": len(req.prompt),
                                         "users": 1}
                self._slot_blocks[slot] = {"self": self_blocks,
                                           "cross": cross_blocks,
                                           "key": key}
                joining.append((slot, req, False))
                if monitor.enabled():
                    monitor.flight.record(
                        "kv.page", event="alloc", model=model.name,
                        slot=slot, cross_blocks=len(cross_blocks),
                        self_blocks=len(self_blocks))
        if not joining:
            return
        self._publish_blocks()
        prefilling = [(slot, req) for slot, req, shared in joining
                      if not shared]
        src = np.zeros((model.slots, model.max_prompt_len, 1), np.int64)
        active = np.zeros((model.slots,), np.float32)
        for slot, req in prefilling:
            src[slot, :len(req.prompt), 0] = req.prompt
            active[slot] = 1.0
        traces = [req.trace for _, req, _s in joining
                  if req.trace is not None]
        pre_traces = [req.trace for _, req in prefilling
                      if req.trace is not None]
        if traces:
            t_pre0 = time.perf_counter()
            for slot, req, _s in joining:
                if req.trace is not None:
                    # slot wait: enqueue -> this admission round
                    req.trace.add_span(
                        "queue.wait", tracing.pc_to_epoch(req.t_enqueue),
                        tracing.pc_to_epoch(t_pre0), slot=slot)
            if prefilling:
                with tracing.executor_context(pre_traces):
                    model.session.prefill(src, active=active)
            # ONE masked prefill joins N sequences — the generation
            # tier's fan-in span (prefix-hit joiners skipped it and get
            # only queue.wait: their cross cache is already resident)
            t_pre1 = time.perf_counter()
            if pre_traces:
                tracing.add_shared_span(
                    pre_traces, "prefill", tracing.pc_to_epoch(t_pre0),
                    tracing.pc_to_epoch(t_pre1), joined=len(prefilling))
            for _, req, _s in joining:
                if req.trace is not None:
                    # first decode.step span clamps to this: a joiner's
                    # iteration accounting must not overlap its prefill
                    req.t_joined = t_pre1
            if self._t_anchor is None:
                # start the iteration clock here (fresh/untraced slots):
                # the first decode.step then covers the admit tail too.
                # With the clock already running (traced sequences in
                # flight), leave it — their next iteration span must
                # keep the prefill stall they just sat through.
                self._t_anchor = time.perf_counter()
        elif prefilling:
            model.session.prefill(src, active=active)
        if monitor.enabled() and prefilling:
            # counts actually-prefilled lanes: N same-prefix joiners
            # move this by exactly 1 (the leader)
            monitor.counter(
                f"serving.gen.{model.name}.prefills").inc(
                len(prefilling))

    def _step(self) -> bool:
        """One coalesced decode step for every occupied slot; returns
        True when a decode actually ran (breaker-success evidence)."""
        from .. import monitor
        from ..testing import chaos

        model = self.model
        active = np.asarray(
            [1.0 if r is not None else 0.0 for r in self._slot_req],
            np.float32)
        if not active.any():
            return False
        if model.paged and model._shared_self_slots:
            # copy-on-write guard for forked sequences (fork_slot, the
            # speculative-decode skeleton): any slot about to append
            # into a self block it shares gets a private copy first, so
            # the divergent write can't corrupt its sharer.  Unforked
            # serving never enters here — the set stays empty.
            p = model.session.p
            scope = model.session.scope
            lens = p.self_cache.lengths(scope)
            copies = 0
            for slot in sorted(model._shared_self_slots):
                if active[slot] and p.self_cache.cow_if_shared(
                        scope, slot, int(lens[slot])):
                    copies += 1
                    info = self._slot_blocks[slot]
                    if info is not None:
                        info["self"] = p.self_cache.slot_blocks(
                            scope, slot, int(lens[slot]) + 1)
            if copies and monitor.enabled():
                monitor.counter(
                    f"generation.{model.name}.cow_copies_total").inc(
                    copies)
                monitor.flight.record("kv.page", event="cow",
                                      model=model.name, copies=copies)
        # iteration-level accounting (the Orca pattern): one decode.step
        # span per scheduled iteration in EVERY occupied slot's trace,
        # carrying the slot + occupancy; covers the whole iteration
        # (mask build, executor call, bookkeeping) so the per-token
        # decomposition tiles the request window
        traced = [(slot, r) for slot, r in enumerate(self._slot_req)
                  if r is not None and r.trace is not None]
        occupancy = int(active.sum())
        if traced:
            t_it0 = (self._t_anchor if self._t_anchor is not None
                     else time.perf_counter())
        chaos.maybe_serve_latency()
        if traced:
            with tracing.executor_context([r.trace for _, r in traced]):
                nxt = model.session.decode_step(self._slot_token,
                                                active=active)
        else:
            nxt = model.session.decode_step(self._slot_token,
                                            active=active)
        now = time.perf_counter()
        mon = monitor.enabled()
        emitted = 0
        finished: List[_GenRequest] = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            expired = req.deadline is not None and now >= req.deadline
            if req.cancelled or expired:
                # abandoned by a timed-out client, or past its deadline
                # (the scheduler-side mirror — holds even when the
                # client thread is gone): free the slot at this
                # iteration boundary instead of decoding the rest of
                # its budget
                self._slot_req[slot] = None
                self._release_slot(slot)
                if req.trace is not None:
                    req.trace.finish(
                        status="expired" if expired else "cancelled")
                if expired and not req.cancelled:
                    req.error = TimeoutError(
                        f"generation deadline passed mid-decode "
                        f"(model {model.name!r}, slot {slot})")
                    req.event.set()
                    if mon:
                        # SLO bad event lands waiter-side via req.error
                        monitor.counter(
                            f"serving.gen.{model.name}."
                            "expired_slots_total").inc()
                continue
            tok = int(nxt[slot])
            if req.t_first_token is None:
                req.t_first_token = now
                if mon:
                    monitor.histogram(
                        f"serving.gen.{model.name}.ttft_seconds",
                        buckets=TTFT_BUCKETS).observe(
                        now - req.t_enqueue)
            req.tokens.append(tok)
            emitted += 1
            self._slot_token[slot] = tok
            if tok == model.eos_id or len(req.tokens) >= req.max_tokens:
                req.meta = {
                    "slot": slot,
                    "tokens": len(req.tokens),
                    "ttft_ms": round(
                        (req.t_first_token - req.t_enqueue) * 1e3, 3),
                    "total_ms": round((now - req.t_enqueue) * 1e3, 3),
                    "finished": ("eos" if tok == model.eos_id
                                 else "max_tokens"),
                }
                self._slot_req[slot] = None  # retire the slot
                self._release_slot(slot)
                finished.append(req)
        if traced:
            t_it1 = time.perf_counter()
            e_it0, e_it1 = (tracing.pc_to_epoch(t_it0),
                            tracing.pc_to_epoch(t_it1))
            # one shared iteration span, floored per trace at its OWN
            # prefill end (the shared anchor may predate a late join)
            tracing.add_shared_span(
                [req.trace for _, req in traced], "decode.step",
                e_it0, max(e_it0, e_it1),
                floors=[None if req.t_joined is None
                        else tracing.pc_to_epoch(req.t_joined)
                        for _, req in traced],
                per_attrs=[{"slot": slot,
                            "token_index": max(0, len(req.tokens) - 1)}
                           for slot, req in traced],
                fan_in_attrs=False, occupancy=occupancy)
            self._t_anchor = time.perf_counter()
        # wake the finished waiters only AFTER the iteration's spans are
        # recorded — a waiter closing its trace must not race the final
        # decode.step span out of the decomposition
        for req in finished:
            if req.trace is not None:
                req.t_done = time.perf_counter()
            req.event.set()
        if mon:
            monitor.counter(f"serving.gen.{model.name}.tokens").inc(
                emitted)
            monitor.counter(
                f"serving.gen.{model.name}.decode_steps").inc()
            occ = sum(1 for r in self._slot_req if r is not None)
            monitor.gauge(f"serving.gen.{model.name}.occupancy").set(occ)
            monitor.gauge(
                f"serving.gen.{model.name}.occupancy_fraction").set(
                occ / max(model.slots, 1))
            if self._t_last_decode is not None:
                # tokens/sec over the inter-iteration interval, EWMA-
                # smoothed (alpha 0.2), per resident KV-cache GB: the
                # capacity-efficiency number a fleet scheduler bins by
                dt_it = max(now - self._t_last_decode, 1e-9)
                inst = emitted / dt_it
                self._tps_ewma = (
                    inst if self._tps_ewma is None
                    else 0.2 * inst + 0.8 * self._tps_ewma)
                kv_gb = model.kv_cache_bytes / 1e9
                if kv_gb > 0:
                    monitor.gauge(
                        f"generation.{model.name}"
                        ".tokens_per_sec_per_hbm_gb").set(
                        self._tps_ewma / kv_gb)
            self._t_last_decode = now
        return True

    def _fail_slots(self, exc: Exception) -> None:
        """A prefill/decode call raised: fail every occupied slot (the
        shared step means their state is suspect) but KEEP the scheduler
        alive for future requests — the DynamicBatcher 'fail the batch,
        not the loop' contract (batcher.py _execute)."""
        from .. import monitor

        self.breaker.record_failure()
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._slot_req[slot] = None
            self._release_slot(slot)
            req.error = exc
            if req.trace is not None:
                req.trace.finish(status="error:step")
            req.event.set()
        # SLO bad events land waiter-side (each waiter sees req.error) —
        # counting per slot here would double them
        if monitor.enabled():
            monitor.counter(
                f"serving.gen.{self.model.name}.step_errors").inc()

    def _loop(self) -> None:
        try:
            while self._running:
                idle = not any(r is not None for r in self._slot_req)
                if idle:
                    self._t_anchor = None  # iteration clock stops
                if not self._drain_queue(block=idle):
                    break
                try:
                    self._admit()
                    if self._step():
                        self.breaker.record_success()
                except Exception as e:  # noqa: BLE001 — fail the
                    # in-flight slots, not the scheduler (a dead loop
                    # would hang every current AND future caller)
                    self._fail_slots(e)
        finally:
            # fail whatever is still in flight/queued so no caller
            # hangs — in a finally so even an unexpected scheduler
            # crash drains its callers, with the NAMED 503 error
            slotted = [r for r in self._slot_req if r is not None]
            self._slot_req = [None] * self.model.slots
            for slot in range(self.model.slots):
                self._release_slot(slot)
            for r in slotted:
                r.error = Unavailable(
                    f"generation batcher for {self.model.name!r} stopped",
                    reason="stopped")
                tracing.reject(r.trace, "stopped")
                r.event.set()
            self._fail_queued()


def build_demo_generation_model(name: str = "gendemo",
                                slots: Optional[int] = None,
                                seed: int = 11) -> GenerationServingModel:
    """Deterministic tiny transformer generation model (random-init,
    seeded) — the CLI `--demo-generation` target the CI smoke and
    loadgen's generation mode drive."""
    cfg = GenerationConfig(
        name, slots=slots,
        src_vocab_size=32, trg_vocab_size=32, max_length=72,
        n_layer=2, n_head=2, d_key=16, d_value=16, d_model=32,
        d_inner_hid=64, src_seq_len=8, max_out_len=64,
        bos_id=0, eos_id=1)
    model = GenerationServingModel(cfg)
    for prog in (model.session.p.prefill, model.session.p.decode,
                 model.session.p.startup):
        prog.random_seed = seed
    model.init_params()
    return model
