"""Contrib layers: fused/TPU-native extensions beyond the reference API."""

from __future__ import annotations

from ..layer_helper import LayerHelper


def fused_attention(q, k, v, bias=None, scale=1.0, causal=False,
                    dropout_rate=0.0, block_q=512, block_k=512,
                    fmt="bhtd", name=None):
    """Flash-attention layer (Pallas kernel on TPU) over [B,H,T,D] tensors
    (fmt="bhtd") or [B,T,H,D] tensors (fmt="bthd" — the transpose-free
    convention: reshape the projection output [B,T,H*D] to [B,T,H,D] and
    skip split/merge-head transposes entirely).

    NOTE: with dropout_rate > 0 this applies dropout to the attention
    *output* (flash-style), not to the attention weights like the unfused
    path — toggling use_flash changes regularization semantics under
    dropout."""
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op(
        "fused_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "causal": causal,
            "block_q": block_q,
            "block_k": block_k,
            "fmt": fmt,
        },
    )
    out.shape = q.shape
    if dropout_rate:
        from .nn import dropout

        out = dropout(out, dropout_prob=dropout_rate,
                      dropout_implementation="upscale_in_train")
    return out


def ring_attention(q, k, v, scale=1.0, causal=False, axis_name="sp",
                   name=None):
    """Context-parallel attention layer over [B,H,T,D] tensors: the T axis
    shards over mesh axis `axis_name` (see ops/fused_ops.py ring_attention).
    Use through a ShardingPlan whose mesh declares that axis."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        "ring_attention",
        inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "causal": causal,
               "axis_name": axis_name},
    )
    return out
